"""Paged attention parity (ops/pallas_paged.py).

Two oracles pin the paged decode path:

1. ``paged_attention_reference`` vs the DENSE cached attention the
   gather engine runs (``decoder._cached_attention`` /
   ``_chunk_cached_attention`` over a full ``kv_cache.gather``) —
   BITWISE in bf16 and int8 alike: the reference gathers only the pages
   the block table names, and the masked tail contributes exact zeros
   through the f32 softmax. This is the argument that lets the engine
   keep its greedy-pin bitwise guarantee through the paged path.
2. The fused kernel (interpret mode, CPU-executable) vs that reference
   — float tolerance (online softmax reassociates the reduction), over
   the full matrix: bf16/int8 pools, GQA, sliding window, decode and
   chunk variants, ragged lengths crossing page boundaries.

Plus the allocator-facing pieces: ``write_page_rows`` must scatter
bitwise-identically to ``kv_cache.write_rows``, and parity must hold on
FRAGMENTED tables (random admit/evict traces leave physical pages
shuffled and interleaved across slots).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models import decoder  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.ops import pallas_paged  # noqa: E402
from dlrover_tpu.serving import kv_cache as kvc  # noqa: E402


def _cfg(**kw):
    base = dict(
        n_layer=2, d_model=32, d_ff=64, n_head=4, vocab_size=32, max_seq=64
    )
    base.update(kw)
    return get_config("tiny", **base)


# slot lengths chosen to cross page boundaries every way page_size=4
# allows: 9 = 2 full pages + 1 row, 14 = 3 full + 2, 3 = one partial page
_LENS = (9, 14, 3)


def _setup(mode, *, lens=_LENS, page_size=4, max_len=32, cfg=None, seed=0):
    """Pools holding random K/V rows for ``lens`` tokens per slot."""
    cfg = cfg or _cfg()
    n_slots = len(lens)
    geom = kvc.make_geometry(
        cfg, n_slots=n_slots, max_len=max_len, page_size=page_size,
        mode=mode,
    )
    alloc = kvc.PageAllocator(geom, n_slots)
    for i, n in enumerate(lens):
        assert alloc.admit(i, n)
    pools = kvc.init_pools(geom)
    tables = jnp.asarray(alloc.block_tables())
    c = max(lens)
    shape = (cfg.n_layer, n_slots, c, cfg.kv_heads, cfg.head_dim)
    ks = jax.random.split(jax.random.key(seed), 2)
    dt = jnp.dtype(cfg.dtype)
    k = jax.random.normal(ks[0], shape).astype(dt)
    v = jax.random.normal(ks[1], shape).astype(dt)
    positions = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32),
                                 (n_slots, c))
    valid = jnp.asarray(np.arange(c)[None, :] < np.asarray(lens)[:, None])
    pools = kvc.write_rows(pools, tables, positions, valid, k, v, geom)
    return cfg, geom, alloc, pools, tables


def _layer(pools, layer):
    return {key: arr[layer] for key, arr in pools.items()}


def _q(cfg, b, c, seed=7):
    return jax.random.normal(
        jax.random.key(seed), (b, c, cfg.n_head, cfg.head_dim)
    ).astype(jnp.dtype(cfg.dtype))


# ---------------------------------------------------------------------------
# reference vs the dense gather path — the bitwise oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["bf16", "int8"])
@pytest.mark.parametrize("window", [0, 6])
def test_reference_matches_dense_decode_bitwise(mode, window):
    cfg, geom, _, pools, tables = _setup(mode, cfg=_cfg(attn_window=window))
    b, h, d = len(_LENS), cfg.n_head, cfg.head_dim
    q = _q(cfg, b, 1)
    pos = jnp.asarray(np.asarray(_LENS) - 1, jnp.int32)
    dense = kvc.gather(pools, tables, geom)
    for layer in range(cfg.n_layer):
        ref = pallas_paged.paged_attention_reference(
            q, _layer(pools, layer), tables, pos, scale=d ** -0.5,
            window=window, kv_heads=cfg.kv_heads,
        )
        oracle = decoder._cached_attention(
            q, dense["k"][layer], dense["v"][layer], pos, cfg
        ).reshape(b, 1, h, d)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(oracle))


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_reference_matches_dense_chunk_bitwise(mode):
    cfg, geom, _, pools, tables = _setup(mode, lens=(9, 14, 6))
    b, c = 3, 4
    q = _q(cfg, b, c)
    # the last c tokens of each slot — queries at ragged depths
    pos = (
        jnp.asarray([8, 13, 5], jnp.int32)[:, None]
        - jnp.arange(c - 1, -1, -1, dtype=jnp.int32)[None, :]
    )
    dense = kvc.gather(pools, tables, geom)
    for layer in range(cfg.n_layer):
        ref = pallas_paged.paged_attention_reference(
            q, _layer(pools, layer), tables, pos,
            scale=cfg.head_dim ** -0.5, kv_heads=cfg.kv_heads,
            variant="chunk",
        )
        oracle = decoder._chunk_cached_attention(
            q, dense["k"][layer], dense["v"][layer], pos, cfg,
            cfg.head_dim ** -0.5,
        )
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(oracle))


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_partial_walk_max_pages_bitwise(mode):
    """Slicing the walk to the pages actually held (4 of 8 here) is
    invisible: the dropped tail is all -1-clamped trash that the
    position mask zeroes exactly."""
    cfg, geom, alloc, pools, tables = _setup(mode)
    held = max(alloc.slot_pages(i) for i in range(len(_LENS)))
    assert held < geom.max_pages_per_slot
    q = _q(cfg, len(_LENS), 1)
    pos = jnp.asarray(np.asarray(_LENS) - 1, jnp.int32)
    full = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, pos, scale=cfg.head_dim ** -0.5,
        kv_heads=cfg.kv_heads,
    )
    part = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, pos, scale=cfg.head_dim ** -0.5,
        kv_heads=cfg.kv_heads, max_pages=held,
    )
    np.testing.assert_array_equal(np.asarray(full), np.asarray(part))


def test_reference_matches_dense_gqa_bitwise():
    cfg = _cfg(n_kv_head=2)
    cfg2, geom, _, pools, tables = _setup("bf16", cfg=cfg)
    assert cfg2.kv_heads == 2 and cfg2.n_head == 4
    b = len(_LENS)
    q = _q(cfg, b, 1)
    pos = jnp.asarray(np.asarray(_LENS) - 1, jnp.int32)
    dense = kvc.gather(pools, tables, geom)
    ref = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, pos, scale=cfg.head_dim ** -0.5,
        kv_heads=cfg.kv_heads,
    )
    oracle = decoder._cached_attention(
        q, dense["k"][0], dense["v"][0], pos, cfg
    ).reshape(b, 1, cfg.n_head, cfg.head_dim)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(oracle))


def test_random_admit_evict_trace_fragmented_parity():
    """After a random admit/grow/evict trace the physical pages behind
    each slot are shuffled and interleaved — parity with the dense
    gather must not depend on pages being contiguous or ascending."""
    cfg = _cfg()
    geom = kvc.make_geometry(
        cfg, n_slots=4, max_len=24, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 4)
    rng = np.random.default_rng(3)
    lens = [0, 0, 0, 0]
    for _ in range(60):
        slot = int(rng.integers(4))
        if lens[slot] == 0:
            n = int(rng.integers(1, geom.max_len + 1))
            if alloc.can_admit(n):
                alloc.admit(slot, n)
                lens[slot] = n
        elif rng.random() < 0.4:
            alloc.evict(slot)
            lens[slot] = 0
        else:
            n = min(geom.max_len, lens[slot] + int(rng.integers(0, 5)))
            if alloc.ensure(slot, n):
                lens[slot] = n
    assert any(lens), "trace left no live slot"
    # physical layout really is fragmented after the trace
    live_rows = alloc.block_tables()[[i for i in range(4) if lens[i]]]
    phys = [int(p) for row in live_rows for p in row if p >= 0]
    assert phys != sorted(phys) or len(phys) != max(phys) - min(phys) + 1

    pools = kvc.init_pools(geom)
    tables = jnp.asarray(alloc.block_tables())
    c = max(max(lens), 1)
    shape = (cfg.n_layer, 4, c, cfg.kv_heads, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    k = jax.random.normal(jax.random.key(5), shape).astype(dt)
    v = jax.random.normal(jax.random.key(6), shape).astype(dt)
    positions = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32), (4, c))
    valid = jnp.asarray(np.arange(c)[None, :] < np.asarray(lens)[:, None])
    pools = kvc.write_rows(pools, tables, positions, valid, k, v, geom)

    q = _q(cfg, 4, 1)
    pos = jnp.asarray(np.maximum(np.asarray(lens) - 1, 0), jnp.int32)
    dense = kvc.gather(pools, tables, geom)
    ref = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, pos, scale=cfg.head_dim ** -0.5,
        kv_heads=cfg.kv_heads,
    )
    oracle = decoder._cached_attention(
        q, dense["k"][0], dense["v"][0], pos, cfg
    ).reshape(4, 1, cfg.n_head, cfg.head_dim)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(oracle))


# ---------------------------------------------------------------------------
# the fused kernel, interpret mode (CPU-executable)
# ---------------------------------------------------------------------------


def _skip_unless_interpretable():
    if not pallas_paged.kernels_available(True):
        pytest.skip("pallas tpu backend not importable")


@pytest.mark.parametrize("mode", ["bf16", "int8"])
@pytest.mark.parametrize("window", [0, 6])
@pytest.mark.parametrize("gqa", [False, True])
def test_kernel_decode_matches_reference(mode, window, gqa):
    _skip_unless_interpretable()
    cfg = _cfg(attn_window=window, n_kv_head=2 if gqa else None)
    cfg, geom, _, pools, tables = _setup(mode, cfg=cfg)
    q = _q(cfg, len(_LENS), 1)
    pos = jnp.asarray(np.asarray(_LENS) - 1, jnp.int32)
    kw = dict(scale=cfg.head_dim ** -0.5, window=window,
              kv_heads=cfg.kv_heads)
    out_k = pallas_paged.paged_attention(
        q, _layer(pools, 0), tables, pos, interpret=True, **kw
    )
    out_r = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, pos, **kw
    )
    np.testing.assert_allclose(
        np.asarray(out_k, np.float32), np.asarray(out_r, np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_kernel_chunk_matches_reference(mode):
    _skip_unless_interpretable()
    cfg, geom, _, pools, tables = _setup(mode, lens=(9, 14, 6))
    c = 4
    q = _q(cfg, 3, c)
    pos = (
        jnp.asarray([8, 13, 5], jnp.int32)[:, None]
        - jnp.arange(c - 1, -1, -1, dtype=jnp.int32)[None, :]
    )
    kw = dict(scale=cfg.head_dim ** -0.5, kv_heads=cfg.kv_heads,
              variant="chunk")
    out_k = pallas_paged.paged_attention(
        q, _layer(pools, 0), tables, pos, interpret=True, **kw
    )
    out_r = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, pos, **kw
    )
    np.testing.assert_allclose(
        np.asarray(out_k, np.float32), np.asarray(out_r, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_kernel_partial_walk_matches_full(mode="bf16"):
    _skip_unless_interpretable()
    cfg, geom, alloc, pools, tables = _setup(mode)
    held = max(alloc.slot_pages(i) for i in range(len(_LENS)))
    q = _q(cfg, len(_LENS), 1)
    pos = jnp.asarray(np.asarray(_LENS) - 1, jnp.int32)
    kw = dict(scale=cfg.head_dim ** -0.5, kv_heads=cfg.kv_heads)
    full = pallas_paged.paged_attention(
        q, _layer(pools, 0), tables, pos, interpret=True, **kw
    )
    part = pallas_paged.paged_attention(
        q, _layer(pools, 0), tables, pos, interpret=True,
        max_pages=held, **kw
    )
    np.testing.assert_allclose(
        np.asarray(full, np.float32), np.asarray(part, np.float32),
        rtol=2e-2, atol=2e-2,
    )


# ---------------------------------------------------------------------------
# dispatch, capability table, write parity
# ---------------------------------------------------------------------------


def test_dispatch_falls_to_reference_off_tpu():
    """With interpret forced off on CPU the op IS the reference —
    bitwise, which is what lets the serving engine keep its bf16
    greedy pin on the CPU test backend."""
    cfg, geom, _, pools, tables = _setup("bf16")
    q = _q(cfg, len(_LENS), 1)
    pos = jnp.asarray(np.asarray(_LENS) - 1, jnp.int32)
    kw = dict(scale=cfg.head_dim ** -0.5, kv_heads=cfg.kv_heads)
    out = pallas_paged.paged_attention(
        q, _layer(pools, 0), tables, pos, interpret=False, **kw
    )
    ref = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, pos, **kw
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_capability_table_gates_on_interpret():
    from dlrover_tpu.accelerate.device_context import kernel_capabilities

    caps_on = kernel_capabilities(interpret=True)
    caps_off = kernel_capabilities(interpret=False)
    if pallas_paged.pltpu is None:
        assert not caps_on.paged_attention
    else:
        assert caps_on.paged_attention
    if not jax.default_backend() == "tpu":
        assert not caps_off.paged_attention


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_write_page_rows_matches_write_rows(mode):
    """The per-layer scan twin scatters bitwise-identically to the
    [L, ...] kv_cache.write_rows — same phys/offset math, same trash
    routing, same int8 encode."""
    cfg = _cfg()
    geom = kvc.make_geometry(
        cfg, n_slots=3, max_len=32, page_size=4, mode=mode
    )
    alloc = kvc.PageAllocator(geom, 3)
    for i, n in enumerate(_LENS):
        assert alloc.admit(i, n)
    tables = jnp.asarray(alloc.block_tables())
    c = 2
    shape = (cfg.n_layer, 3, c, cfg.kv_heads, cfg.head_dim)
    k = jax.random.normal(jax.random.key(8), shape).astype(jnp.bfloat16)
    v = jax.random.normal(jax.random.key(9), shape).astype(jnp.bfloat16)
    positions = jnp.asarray([[0, 5], [3, 13], [1, 2]], jnp.int32)
    valid = jnp.asarray([[True, True], [True, True], [True, False]])

    full = kvc.write_rows(
        kvc.init_pools(geom), tables, positions, valid, k, v, geom
    )
    ref_pools = kvc.init_pools(geom)
    layers = []
    for layer in range(cfg.n_layer):
        layers.append(pallas_paged.write_page_rows(
            _layer(ref_pools, layer), tables, positions, valid,
            k[layer], v[layer],
        ))
    for key in full:
        stacked = jnp.stack([lay[key] for lay in layers])
        np.testing.assert_array_equal(
            np.asarray(full[key]), np.asarray(stacked)
        )


# ---------------------------------------------------------------------------
# verify variant — speculative-decoding chunk over in-flight extra keys
# ---------------------------------------------------------------------------


def _verify_inputs(cfg, lens, c, seed=11):
    """Chunk queries + in-flight K/V rows starting at each slot's last
    committed position (row 0 = the unwritten last token, exactly the
    engine's verify layout)."""
    b = len(lens)
    start = jnp.asarray(np.asarray(lens) - 1, jnp.int32)
    positions = start[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
    dt = jnp.dtype(cfg.dtype)
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(
        ks[0], (b, c, cfg.n_head, cfg.head_dim)
    ).astype(dt)
    ink = jax.random.normal(
        ks[1], (b, c, cfg.kv_heads, cfg.head_dim)
    ).astype(dt)
    inv = jax.random.normal(
        ks[2], (b, c, cfg.kv_heads, cfg.head_dim)
    ).astype(dt)
    return q, ink, inv, positions, start


@pytest.mark.parametrize("mode", ["bf16", "int8"])
@pytest.mark.parametrize("window", [0, 6])
def test_reference_verify_matches_dense_bitwise(mode, window):
    """The in-flight-extras formulation is bitwise the dense per-query
    attention with the chunk rows sitting IN PLACE in the cache: masked
    lanes contribute exact zeros, so moving the chunk rows to appended
    key slots never changes the f32 accumulation order of the nonzero
    terms."""
    cfg, geom, _, pools, tables = _setup(
        mode, cfg=_cfg(attn_window=window)
    )
    c = 4
    q, ink, inv, positions, start = _verify_inputs(cfg, _LENS, c)
    dense = kvc.gather(pools, tables, geom)
    for layer in range(cfg.n_layer):
        ref = pallas_paged.paged_attention_reference(
            q, _layer(pools, layer), tables, positions,
            scale=cfg.head_dim ** -0.5, window=window,
            kv_heads=cfg.kv_heads, variant="verify",
            extra_k=ink, extra_v=inv,
        )
        # dense per-query oracle: chunk rows written in place at their
        # true indices, identical view for every query
        ck, cv = dense["k"][layer], dense["v"][layer]
        upd = jax.vmap(
            lambda cc, u, p: jax.lax.dynamic_update_slice_in_dim(
                cc, u, p, axis=0
            )
        )
        ck = upd(ck, ink.astype(ck.dtype), start)
        cv = upd(cv, inv.astype(cv.dtype), start)
        b = len(_LENS)
        ck_q = jnp.broadcast_to(ck[:, None], (b, c) + ck.shape[1:])
        cv_q = jnp.broadcast_to(cv[:, None], (b, c) + cv.shape[1:])
        oracle = decoder._verify_cached_attention(
            q, ck_q, cv_q, positions, cfg
        ).reshape(b, c, cfg.n_head, cfg.head_dim)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(oracle))


@pytest.mark.parametrize("mode", ["bf16", "int8"])
@pytest.mark.parametrize("window", [0, 6])
@pytest.mark.parametrize("gqa", [False, True])
def test_kernel_verify_matches_reference(mode, window, gqa):
    _skip_unless_interpretable()
    cfg = _cfg(attn_window=window, n_kv_head=2 if gqa else None)
    cfg, geom, _, pools, tables = _setup(mode, cfg=cfg)
    c = 4
    q, ink, inv, positions, _ = _verify_inputs(cfg, _LENS, c)
    kw = dict(scale=cfg.head_dim ** -0.5, window=window,
              kv_heads=cfg.kv_heads, variant="verify",
              extra_k=ink, extra_v=inv)
    out_k = pallas_paged.paged_attention(
        q, _layer(pools, 0), tables, positions, interpret=True, **kw
    )
    out_r = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, positions, **kw
    )
    np.testing.assert_allclose(
        np.asarray(out_k, np.float32), np.asarray(out_r, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_verify_stale_pool_rows_at_chunk_positions_ignored(mode="bf16"):
    """Pool cells at positions >= start may hold a previous tenant's
    rows (pages are not zeroed on free); the verify mask must read the
    in-flight rows there, never the stale cells."""
    cfg, geom, _, pools, tables = _setup(mode)
    c = 4
    q, ink, inv, positions, start = _verify_inputs(cfg, _LENS, c)
    out1 = pallas_paged.paged_attention_reference(
        q, _layer(pools, 0), tables, positions,
        scale=cfg.head_dim ** -0.5, kv_heads=cfg.kv_heads,
        variant="verify", extra_k=ink, extra_v=inv,
    )
    # poison every pool cell at the chunk positions with garbage
    garbage = jnp.full(
        (cfg.n_layer, len(_LENS), c, cfg.kv_heads, cfg.head_dim), 37.0,
        jnp.dtype(cfg.dtype),
    )
    valid = jnp.ones((len(_LENS), c), bool)
    pois = kvc.write_rows(
        pools, tables,
        jnp.asarray(np.asarray(_LENS))[:, None] - 1
        + jnp.arange(c, dtype=jnp.int32)[None, :],
        valid, garbage, garbage, geom,
    )
    out2 = pallas_paged.paged_attention_reference(
        q, _layer(pois, 0), tables, positions,
        scale=cfg.head_dim ** -0.5, kv_heads=cfg.kv_heads,
        variant="verify", extra_k=ink, extra_v=inv,
    )
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
