"""Cross-node in-memory checkpoint replica tests.

Reference behavior: replica.py ShardCkptReplicaManager — back up staged
shards to a peer; a replaced node restores from the peer's RAM
(engine.py:349 _restore_memory_from_replica).
"""

import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.checkpointer import state_template
from dlrover_tpu.checkpoint.replica import (
    ReplicaConfig,
    ReplicaManager,
    wait_peer_steps,
)


@pytest.fixture(autouse=True)
def _run_id(monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_RUN_ID", f"rep{os.getpid()}_{time.time_ns()}"
    )


def _mk_manager(rank, count, peers=None, num_replicas=1):
    # explicit token: hosts of one run share RUN_ID; the test's simulated
    # replacement host keeps it even though we rotate RUN_ID to get
    # fresh shm segments
    cfg = ReplicaConfig(
        num_replicas=num_replicas,
        bind_host="127.0.0.1",
        advertise_host="127.0.0.1",
        token="test-run",
    )
    return ReplicaManager(rank, count, peers=peers or {}, config=cfg)


def _state():
    return {
        "w": jnp.arange(32, dtype=jnp.float32).reshape(4, 8),
        "step": jnp.asarray(5),
    }


def test_backup_and_peer_fetch(monkeypatch):
    m1 = _mk_manager(1, 2)
    m0 = _mk_manager(0, 2, peers={1: m1.addr})
    try:
        engine = CheckpointEngine("/tmp/unused", use_agent=False, replica=m0)
        state = _state()
        assert engine.save_to_memory(11, state)
        m0.wait_backup()
        assert wait_peer_steps(m1, {0: 11}, timeout=10)

        # "host 0 dies": a replacement with fresh shm restores from peer 1
        monkeypatch.setenv("DLROVER_TPU_RUN_ID", f"new{time.time_ns()}")
        m0b = _mk_manager(0, 2, peers={1: m1.addr})
        try:
            engine2 = CheckpointEngine(
                "/tmp/unused", use_agent=False, replica=m0b
            )
            out = engine2.load(state_template(state))
            assert out is not None
            np.testing.assert_array_equal(
                np.asarray(out["w"]), np.asarray(state["w"])
            )
            assert int(out["step"]) == 5
        finally:
            m0b.close()
    finally:
        m0.close()
        m1.close()


def test_newer_step_replaces_stale(monkeypatch):
    m1 = _mk_manager(1, 2)
    m0 = _mk_manager(0, 2, peers={1: m1.addr})
    try:
        engine = CheckpointEngine("/tmp/unused", use_agent=False, replica=m0)
        engine.save_to_memory(1, _state())
        m0.wait_backup()
        state2 = {"w": jnp.ones((4, 8)), "step": jnp.asarray(9)}
        engine.save_to_memory(2, state2)
        m0.wait_backup()
        assert wait_peer_steps(m1, {0: 2}, timeout=10)
        got_step, _ = m1._store.get(0)
        assert got_step == 2
        # stale re-put is a no-op
        assert m1._store.put(0, 1, b"old")
        assert m1._store.get(0)[0] == 2
    finally:
        m0.close()
        m1.close()


def test_multi_replica_ring():
    m1 = _mk_manager(1, 3, num_replicas=2)
    m2 = _mk_manager(2, 3, num_replicas=2)
    m0 = _mk_manager(
        0, 3, peers={1: m1.addr, 2: m2.addr}, num_replicas=2
    )
    try:
        engine = CheckpointEngine("/tmp/unused", use_agent=False, replica=m0)
        assert engine.save_to_memory(7, _state())
        m0.wait_backup()
        assert wait_peer_steps(m1, {0: 7}, timeout=10)
        assert wait_peer_steps(m2, {0: 7}, timeout=10)
        # even if holder 1 vanished, holder 2 serves the pack
        m0c = _mk_manager(0, 3, peers={2: m2.addr}, num_replicas=2)
        try:
            hit = m0c.fetch()
            assert hit is not None and hit[0] == 7
        finally:
            m0c.close()
    finally:
        m0.close()
        m1.close()
        m2.close()


def test_store_budget_rejects_oversize():
    cfg = ReplicaConfig(
        bind_host="127.0.0.1",
        advertise_host="127.0.0.1",
        max_store_bytes=64,
    )
    holder = ReplicaManager(1, 2, config=cfg)
    sender = _mk_manager(0, 2, peers={1: holder.addr})
    try:
        assert holder._store.put(0, 1, b"x" * 32)
        # second source pushing 64B would exceed the 64B budget
        assert not holder._store.put(5, 1, b"y" * 64)
    finally:
        sender.close()
        holder.close()


def test_wrong_token_rejected():
    from dlrover_tpu.checkpoint.replica import ReplicaConfig, ReplicaManager

    holder = ReplicaManager(
        1,
        2,
        config=ReplicaConfig(
            bind_host="127.0.0.1", advertise_host="127.0.0.1", token="good"
        ),
    )
    intruder = ReplicaManager(
        0,
        2,
        peers={1: holder.addr},
        config=ReplicaConfig(
            bind_host="127.0.0.1", advertise_host="127.0.0.1", token="evil"
        ),
    )
    try:
        assert not intruder._put(holder.addr, 1, b"poison")
        assert holder.local_steps() == {}
    finally:
        intruder.close()
        holder.close()


def test_fetch_wrong_step_returns_none():
    m1 = _mk_manager(1, 2)
    m0 = _mk_manager(0, 2, peers={1: m1.addr})
    try:
        engine = CheckpointEngine("/tmp/unused", use_agent=False, replica=m0)
        engine.save_to_memory(3, _state())
        m0.wait_backup()
        assert wait_peer_steps(m1, {0: 3}, timeout=10)
        assert m0.fetch(step=99) is None
        assert m0.fetch(step=3) is not None
    finally:
        m0.close()
        m1.close()


def test_fetch_exclude_and_with_holder():
    """exclude skips a holder that failed restore; with_holder reports
    which ring peer served the pack (the next-peer retry in
    engine._load_from_replica is built on both)."""
    m1 = _mk_manager(1, 3, num_replicas=2)
    m2 = _mk_manager(2, 3, num_replicas=2)
    m0 = _mk_manager(0, 3, peers={1: m1.addr, 2: m2.addr}, num_replicas=2)
    try:
        engine = CheckpointEngine("/tmp/unused", use_agent=False, replica=m0)
        assert engine.save_to_memory(7, _state())
        m0.wait_backup()
        assert wait_peer_steps(m1, {0: 7}, timeout=10)
        assert wait_peer_steps(m2, {0: 7}, timeout=10)
        got = m0.fetch(with_holder=True)
        assert got is not None and got[0] == 7 and got[2] == 1
        got2 = m0.fetch(exclude=(1,), with_holder=True)
        assert got2 is not None and got2[0] == 7 and got2[2] == 2
        assert m0.fetch(exclude=(1, 2)) is None
    finally:
        m0.close()
        m1.close()
        m2.close()


class _FlakyReplica:
    """Holder 1 serves a corrupt pack; holder 2 a good one."""

    def __init__(self, good_pack, step):
        self.calls = []
        self._good = good_pack
        self._step = step

    def fetch(self, src=None, step=None, exclude=(), with_holder=False):
        self.calls.append(tuple(sorted(exclude)))
        if 1 not in exclude:
            return self._step, b"garbage-not-a-pack", 1
        if 2 not in exclude:
            return self._step, self._good, 2
        return None


def test_load_from_replica_retries_next_peer():
    from dlrover_tpu.checkpoint import core

    state = _state()
    entries, payload = core.plan_pack(state)
    header = core.header_bytes(9, entries)
    buf = bytearray(core.pack_size(header, payload))
    core.write_pack(memoryview(buf), 9, state, entries, header=header)

    replica = _FlakyReplica(bytes(buf), 9)
    engine = CheckpointEngine("/tmp/unused", use_agent=False, replica=replica)
    out = engine._load_from_replica(state_template(state), None, 9)
    assert out is not None
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(state["w"]))
    # first try hit the corrupt holder, retry excluded it
    assert replica.calls == [(), (1,)]


def test_load_from_replica_gives_up_when_all_holders_corrupt():
    class _AllBad:
        def fetch(self, src=None, step=None, exclude=(), with_holder=False):
            nxt = next((r for r in (1, 2) if r not in exclude), None)
            return None if nxt is None else (9, b"garbage", nxt)

    engine = CheckpointEngine("/tmp/unused", use_agent=False, replica=_AllBad())
    assert engine._load_from_replica(state_template(_state()), None, 9) is None
