"""Auto-scaler, diagnosis, config tuner, metrics tests."""

import json
import time
import urllib.request

import pytest

from dlrover_tpu.common import messages as msgs
from dlrover_tpu.diagnosis.manager import (
    DiagnosisAction,
    DiagnosisManager,
    classify_failure,
)
from dlrover_tpu.master.auto_scaler import JobAutoScaler
from dlrover_tpu.master.job_metrics import (
    JobMetricCollector,
    MetricsHTTPServer,
)
from dlrover_tpu.master.node_manager import JobManager, NoopScaler
from dlrover_tpu.master.resource_optimizer import LocalHeuristicOptimizer
from dlrover_tpu.master.speed_monitor import SpeedMonitor


def test_classify_failures():
    assert classify_failure("RESOURCE_EXHAUSTED: out of memory")[0] == "oom"
    assert classify_failure("ICI link failure on chip 2")[0] == (
        "hardware_error"
    )
    assert classify_failure("ModuleNotFoundError: no module")[1] == (
        DiagnosisAction.ABORT_JOB
    )
    cls, action = classify_failure("something weird")
    assert action == DiagnosisAction.RESTART_WORKER


def test_diagnosis_actions_queue():
    dm = DiagnosisManager()
    # hang report with the worker still alive → restart is prescribed
    dm.collect_failure(
        msgs.NodeFailureReport(node_id=3, error_data="barrier timeout"),
        worker_alive=True,
    )
    assert dm.take_actions(3) == [DiagnosisAction.RESTART_WORKER]
    assert dm.take_actions(3) == []
    assert dm.failure_summary() == {"hang": 1}

    # dead-worker failure → the agent restarts it itself; no duplicate
    # restart action is queued, but stronger actions still are
    dm.collect_failure(
        msgs.NodeFailureReport(node_id=4, error_data="worker exit code 1")
    )
    assert dm.take_actions(4) == []
    dm.collect_failure(
        msgs.NodeFailureReport(node_id=5, error_data="ImportError: x")
    )
    assert dm.take_actions(5) == [DiagnosisAction.ABORT_JOB]


def test_autoscaler_scale_out_and_in():
    jm = JobManager(num_workers=2)
    sm = SpeedMonitor()
    scaler = NoopScaler()
    opt = LocalHeuristicOptimizer(min_workers=2, max_workers=8, node_unit=2)
    asc = JobAutoScaler(
        jm,
        sm,
        scaler,
        optimizer=opt,
        min_workers=2,
        max_workers=8,
        node_unit=2,
    )
    # both workers running & speed healthy → scale out by node_unit
    for i in range(2):
        jm.register_node(msgs.NodeMeta(node_id=i, node_rank=i))
    # interval math runs on the injectable monotonic arrival clock
    sm.collect_global_step(0, now=90.0)
    sm.collect_global_step(50, now=100.0)
    asc.adjust_once()
    assert jm.worker_num == 4
    assert scaler.plans and scaler.plans[-1].worker_num == 4

    # within the grace window booting nodes don't trigger scale-in
    asc.adjust_once()
    assert jm.worker_num == 4

    # after the grace expires, still-unplaced nodes force scale-in
    asc.pending_grace_s = 0.0
    asc.adjust_once()
    assert jm.worker_num == 2


def test_config_tuner_writes_file(tmp_path):
    class FakeClient:
        def get_parallel_config(self):
            return msgs.ParallelConfig(batch_size=32, version=2)

    from dlrover_tpu.agent.config_tuner import ParalConfigTuner

    path = tmp_path / "cfg.json"
    tuner = ParalConfigTuner(FakeClient(), config_path=str(path))
    assert tuner.poll_once()
    doc = json.loads(path.read_text())
    assert doc["batch_size"] == 32 and doc["version"] == 2
    # same version → no rewrite
    assert not tuner.poll_once()


def test_goodput_tracker():
    from dlrover_tpu.master.job_metrics import GoodputTracker

    t = GoodputTracker(now=100.0)
    # startup counts as stalled until the first step report
    t.mark_productive(now=110.0)          # first step at t+10
    assert t.goodput(now=110.0) == pytest.approx(0.0)
    assert t.goodput(now=210.0) == pytest.approx(1 - 10 / 110)
    # node failure at t+110 (training was at step 50) → a STALE in-flight
    # report at/below the stall step must not close the stall
    t.mark_stalled(now=210.0, at_step=50)
    t.mark_stalled(now=215.0)             # idempotent while stalled
    t.mark_productive(now=212.0, step=50)  # stale step — ignored
    # racing in-flight report: step ABOVE the stall point but taken
    # before the stall opened — must not close it
    t.mark_productive(now=213.0, step=51, report_ts=209.0)
    # real post-restart progress (taken after the stall opened)
    t.mark_productive(now=240.0, step=51, report_ts=239.5)
    assert t.lost_seconds(now=240.0) == pytest.approx(40.0)
    # 300s wall, 40s lost → 86.7% goodput
    assert t.goodput(now=400.0) == pytest.approx(1 - 40 / 300)
    # productive while not stalled is a no-op
    t.mark_productive(now=500.0)
    assert t.lost_seconds(now=500.0) == pytest.approx(40.0)

    # hang backdating: detection at t+500 backdates accounting to t+420,
    # clamped to the last close (t+240 in this history is older, so the
    # full backdate stands); the in-flight guard keys on DETECTION time
    t.mark_stalled(now=500.0, at_step=80, accounted_from=420.0)
    t.mark_productive(now=505.0, step=81, report_ts=460.0)  # in-window
    assert t.lost_seconds(now=505.0) == pytest.approx(40.0 + 85.0)
    t.mark_productive(now=520.0, step=81, report_ts=510.0)
    assert t.lost_seconds(now=520.0) == pytest.approx(40.0 + 100.0)
    # a backdate reaching before the last close is clamped — the span
    # [520, 530] is charged once even though accounted_from says 400
    t.mark_stalled(now=530.0, at_step=90, accounted_from=400.0)
    t.mark_productive(now=540.0, step=91, report_ts=539.0)
    assert t.lost_seconds(now=540.0) == pytest.approx(140.0 + 20.0)


def test_goodput_completion_freezes_lost_time():
    from dlrover_tpu.master.job_metrics import GoodputTracker

    t = GoodputTracker(now=0.0)
    t.mark_productive(now=5.0)            # startup stall closes at t+5
    # a worker finishes training at t+100 while a stall is open: the
    # stall is charged up to completion, then accounting freezes
    t.mark_stalled(now=90.0, at_step=60)
    t.mark_completed(now=100.0)
    assert t.lost_seconds(now=100.0) == pytest.approx(5.0 + 10.0)
    # a peer death detected AFTER completion (heartbeat timeout racing
    # teardown) opens no stall — its at_step equals the final step, so
    # no report could ever close it
    t.mark_stalled(now=120.0, at_step=60)
    assert t.lost_seconds(now=500.0) == pytest.approx(15.0)


def test_goodput_exported():
    from dlrover_tpu.master.job_metrics import GoodputTracker

    col = JobMetricCollector()
    col.goodput_tracker = GoodputTracker(now=0.0)
    col.goodput_tracker.mark_productive(now=0.0)
    assert "dlrover_tpu_goodput" in col.prometheus_text()
    out = json.loads(col.to_json())
    assert out["goodput"] is not None
    # raw terms for windowed (two-sample) goodput — the drill's
    # regression gate computes across-failure goodput from deltas
    assert out["goodput_lost_seconds"] >= 0.0
    assert out["goodput_wall_seconds"] >= 0.0


def test_metrics_export_http():
    col = JobMetricCollector()
    col.set_job_meta(job_name="j", model_name="tiny", num_params=123)
    col.collect_runtime(10, 2.5, 4, hbm_used_mb_avg=1000.0)
    col.inc("node_failures_total")
    server = MetricsHTTPServer(col, port=0)
    server.start()
    try:
        text = urllib.request.urlopen(
            f"http://localhost:{server.port}/metrics", timeout=5
        ).read().decode()
        assert "dlrover_tpu_global_step 10" in text
        assert "dlrover_tpu_node_failures_total 1" in text
        doc = json.loads(
            urllib.request.urlopen(
                f"http://localhost:{server.port}/json", timeout=5
            ).read()
        )
        assert doc["meta"]["model_name"] == "tiny"
        assert doc["records"][-1]["speed_steps_per_s"] == 2.5
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# runtime kernel timing (xpu_timer analog: periodic trace sampling)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_runtime_timer_samples_real_op_breakdown(tmp_path):
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.observability.runtime_timer import RuntimeKernelTimer

    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: jnp.tanh(a @ a) @ a)
    f(x)  # compile outside the trace
    timer = RuntimeKernelTimer(interval_steps=3, top_k=8)
    # step 1, 2: plain calls; step 3: sampled
    for step in (1, 2):
        timer.profiled_call(step, f, x)
        assert timer.sampled_at == -1
    timer.profiled_call(3, f, x)
    assert timer.sampled_at == 3
    bd = timer.breakdown
    assert bd, "no ops parsed from the trace"
    names = " ".join(o.name for o in bd)
    assert "dot" in names  # the matmuls dominate
    # fractions normalize, python-frame noise filtered out
    assert abs(sum(o.fraction for o in bd) - 1.0) < 1e-6 or len(bd) == 8
    assert not any("$" in o.name or "/" in o.name for o in bd)
    text = timer.prometheus_text()
    assert "dlrover_tpu_kernel_time_us" in text and 'op="' in text


@pytest.mark.slow  # tier-1 budget: full Trainer loop (~23s); the timer
# itself is pinned fast by the forced-one-shot unit below
def test_runtime_timer_in_trainer(tmp_path):
    """profile_interval wires the timer around the live train step."""
    import numpy as np

    from dlrover_tpu.models import get_config
    from dlrover_tpu.parallel import MeshConfig, build_mesh
    from dlrover_tpu.train import Trainer, TrainerArgs, make_optimizer

    def data():
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        while True:
            base = rng.randint(0, 8, size=(8, 33))
            yield {
                "tokens": jnp.asarray(base[:, :-1], jnp.int32),
                "targets": jnp.asarray(base[:, 1:], jnp.int32),
            }

    cfg = get_config("tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
                     vocab_size=128, max_seq=32)
    args = TrainerArgs(
        output_dir=str(tmp_path), max_steps=4, log_interval=0,
        save_interval=0, report_to_master=False,
        detect_loss_spikes=False, profile_interval=2,
    )
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=50)
    tr = Trainer(cfg, args, data(), opt,
                 mesh=build_mesh(MeshConfig(dp=-1)))
    tr.train()
    assert tr.runtime_timer.sampled_at in (2, 4)
    assert tr.runtime_timer.breakdown


# ---------------------------------------------------------------------------
# runtime-timer plumbing the watchdog's triggered captures rely on
# ---------------------------------------------------------------------------


def _write_trace(root, events, sub="plugins/profile/run1"):
    import gzip
    import os

    d = os.path.join(str(root), sub)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "perfetto_trace.json.gz")
    with gzip.open(path, "wt") as fh:
        json.dump({"traceEvents": events}, fh)
    return path


def test_parse_perfetto_canned_fixture(tmp_path):
    """Canned perfetto payload: aggregation, ordering, fraction
    normalization, noise filtering, and top_k truncation — without a
    live profiler run."""
    from dlrover_tpu.observability.runtime_timer import parse_perfetto_dir

    assert parse_perfetto_dir(str(tmp_path)) == []  # no trace yet
    _write_trace(
        tmp_path,
        [
            {"ph": "X", "name": "fusion.1", "dur": 100.0},
            {"ph": "X", "name": "fusion.1", "dur": 50.0},
            {"ph": "X", "name": "dot.2", "dur": 300.0},
            # noise: python frames, runtime threads, non-complete events
            {"ph": "X", "name": "$py_frame", "dur": 999.0},
            {"ph": "X", "name": "jit/fn/call", "dur": 999.0},
            {"ph": "X", "name": "PjitFunction(step)", "dur": 999.0},
            {"ph": "X", "name": "Thread 12", "dur": 999.0},
            {"ph": "M", "name": "dot.2", "dur": 999.0},
            {"ph": "X", "name": "", "dur": 999.0},
        ],
    )
    bd = parse_perfetto_dir(str(tmp_path))
    assert [o.name for o in bd] == ["dot.2", "fusion.1"]
    assert bd[0].total_us == 300.0 and bd[0].count == 1
    assert bd[1].total_us == 150.0 and bd[1].count == 2
    assert bd[0].fraction == pytest.approx(300.0 / 450.0)
    assert sum(o.fraction for o in bd) == pytest.approx(1.0)
    top = parse_perfetto_dir(str(tmp_path), top_k=1)
    assert [o.name for o in top] == ["dot.2"]


def test_parse_perfetto_picks_newest_trace(tmp_path):
    import os
    import time as _time

    from dlrover_tpu.observability.runtime_timer import parse_perfetto_dir

    old = _write_trace(
        tmp_path, [{"ph": "X", "name": "old_op", "dur": 1.0}], sub="a"
    )
    new = _write_trace(
        tmp_path, [{"ph": "X", "name": "new_op", "dur": 1.0}], sub="b"
    )
    now = _time.time()
    os.utime(old, (now - 60, now - 60))
    os.utime(new, (now, now))
    assert [o.name for o in parse_perfetto_dir(str(tmp_path))] == ["new_op"]


def test_runtime_timer_forced_one_shot(tmp_path):
    """interval_steps=0 is forced-only mode: the cadence never fires,
    force_next() arms exactly one sample, and profiled_call records the
    block size it actually traced."""
    from dlrover_tpu.observability.runtime_timer import RuntimeKernelTimer

    with pytest.raises(ValueError):
        RuntimeKernelTimer(interval_steps=-1)

    timer = RuntimeKernelTimer(interval_steps=0, logdir=str(tmp_path))
    assert not any(timer.should_sample(s) for s in range(1, 50))
    timer.force_next()
    assert timer.should_sample(7)

    out = timer.profiled_call(7, lambda a, b: a + b, 2, 3, n_steps=4)
    assert out == 5
    assert timer.sampled_at == 7
    # a 4-step fused block is labeled as such, never as one step
    assert timer.sampled_block_k == 4
    # one-shot: the forced flag is consumed by the sample
    assert not any(timer.should_sample(s) for s in range(8, 50))


def test_loss_spike_publishes_numeric_event_with_culprits():
    """The spike detector is a telemetry producer: a detected spike
    lands on the hub as a NumericEvent whose detail names the worst
    offending sample ids (satellite: sample-id attribution)."""
    from dlrover_tpu.observability import telemetry
    from dlrover_tpu.observability.loss_spike import LossSpikeDetector

    telemetry.reset_hub()
    try:
        hub = telemetry.configure_hub()
        got = []
        hub.subscribe(got.append, types=("NumericEvent",))
        det = LossSpikeDetector(
            save_dir="", min_iter=0, min_loss=0.0, publish_events=True
        )
        for i in range(30):  # jittered baseline: sd > 0
            det.update(i, 1.0 + 0.01 * (i % 5))
        assert det.update(
            30,
            10.0,
            sample_ids=[3, 7, 9],
            per_sample_losses=[0.5, 9.0, 2.0],
        )
        (ev,) = got
        assert ev.kind == "loss_spike" and ev.step == 30
        assert ev.value == pytest.approx(10.0)
        assert ev.detail.startswith("7:9.0000")  # worst sample first
        assert "9:2.0000" in ev.detail and "3:0.5000" in ev.detail
    finally:
        telemetry.reset_hub()
