"""Kernel numerics tests (Pallas interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.attention import mha_reference
from dlrover_tpu.ops.quant import dequantize, quantize, quantize_optimizer_state


def _qkv(key, b=2, s=256, h=4, hkv=None, d=64, dtype=jnp.float32):
    hkv = hkv or h
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, hkv, d), dtype)
    v = jax.random.normal(kv, (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    from dlrover_tpu.ops.pallas_attention import _flash_fwd

    q, k, v = _qkv(jax.random.key(0))
    scale = q.shape[-1] ** -0.5
    out = _flash_fwd(
        q, k, v, causal, scale, block_q=128, block_k=128, interpret=True
    )
    ref = mha_reference(q, k, v, causal=causal, softmax_scale=scale)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_flash_kernel_gqa():
    from dlrover_tpu.ops.pallas_attention import _flash_fwd

    q, k, v = _qkv(jax.random.key(1), h=8, hkv=2)
    scale = q.shape[-1] ** -0.5
    out = _flash_fwd(
        q, k, v, True, scale, block_q=128, block_k=128, interpret=True
    )
    ref = mha_reference(q, k, v, causal=True, softmax_scale=scale)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_quant_roundtrip():
    x = jax.random.normal(jax.random.key(0), (333, 57)) * 3.0
    qa = quantize(x)
    assert qa.q.dtype == jnp.int8
    out = dequantize(qa)
    assert out.shape == x.shape and out.dtype == x.dtype
    # blockwise int8: ~1% relative error on the block max scale
    err = np.abs(np.asarray(out - x)).max()
    assert err <= float(jnp.abs(x).max()) / 127.0 + 1e-6


def test_quantized_optimizer_trains():
    import optax

    opt = quantize_optimizer_state(optax.adam(1e-2))
    params = {"w": jnp.ones((128, 64)), "b": jnp.zeros((4,))}
    state = opt.init(params)
    # large leaf quantized, small leaf untouched
    from dlrover_tpu.ops.quant import QuantizedArray

    leaves = jax.tree.leaves(
        state, is_leaf=lambda x: isinstance(x, QuantizedArray)
    )
    assert any(isinstance(leaf, QuantizedArray) for leaf in leaves)

    def loss(p):
        return jnp.sum(p["w"] ** 2) + jnp.sum(p["b"] ** 2)

    for _ in range(3):
        g = jax.grad(loss)(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < 128 * 64  # moved toward the minimum
