"""Kernel numerics tests (Pallas interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.attention import mha_reference
from dlrover_tpu.ops.quant import dequantize, quantize, quantize_optimizer_state


def _qkv(key, b=2, s=256, h=4, hkv=None, d=64, dtype=jnp.float32):
    hkv = hkv or h
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, hkv, d), dtype)
    v = jax.random.normal(kv, (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    from dlrover_tpu.ops.pallas_attention import _flash_fwd

    q, k, v = _qkv(jax.random.key(0))
    scale = q.shape[-1] ** -0.5
    out, lse = _flash_fwd(
        q, k, v, causal, scale, block_q=128, block_k=128, interpret=True
    )
    ref = mha_reference(q, k, v, causal=causal, softmax_scale=scale)
    assert lse.shape == (q.shape[0], q.shape[2], q.shape[1])
    assert np.isfinite(np.asarray(lse)).all()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_flash_kernel_gqa():
    from dlrover_tpu.ops.pallas_attention import _flash_fwd

    q, k, v = _qkv(jax.random.key(1), h=8, hkv=2)
    scale = q.shape[-1] ** -0.5
    out, _ = _flash_fwd(
        q, k, v, True, scale, block_q=128, block_k=128, interpret=True
    )
    ref = mha_reference(q, k, v, causal=True, softmax_scale=scale)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


@pytest.mark.parametrize("causal,hkv", [(True, 4), (False, 4), (True, 2)])
def test_pallas_backward_matches_reference(causal, hkv):
    """FA2 pallas backward (interpret) == vjp through plain attention,
    including GQA group-summed dk/dv."""
    from dlrover_tpu.ops import pallas_attention as pa

    q, k, v = _qkv(jax.random.key(2), s=256, h=4, hkv=hkv)
    scale = q.shape[-1] ** -0.5
    out, lse = pa._flash_fwd(
        q, k, v, causal, scale, block_q=128, block_k=128, interpret=True
    )
    g = jax.random.normal(jax.random.key(3), out.shape)
    dq, dk, dv = pa._pallas_backward(
        q, k, v, out, lse, g, causal, scale, 128, 128, interpret=True
    )
    ref = lambda q, k, v: jnp.vdot(  # noqa: E731
        mha_reference(q, k, v, causal=causal, softmax_scale=scale), g
    )
    rq, rk, rv = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rq),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rk),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rv),
                               rtol=2e-3, atol=2e-3)


def test_pallas_backward_unequal_seq_lens():
    """Regression: causal sk > sq must not clamp the dkv q-block index
    out of range (jnp.maximum alone could exceed nq-1). Compared against
    the chunked backward, which shares the kernel's mask convention."""
    from dlrover_tpu.ops import pallas_attention as pa

    ks = jax.random.split(jax.random.key(6), 4)
    b, sq, sk, h, d = 2, 128, 256, 2, 32
    q = jax.random.normal(ks[0], (b, sq, h, d))
    k = jax.random.normal(ks[1], (b, sk, h, d))
    v = jax.random.normal(ks[2], (b, sk, h, d))
    scale = d**-0.5
    out, lse = pa._flash_fwd(
        q, k, v, True, scale, block_q=128, block_k=128, interpret=True
    )
    g = jax.random.normal(ks[3], out.shape)
    dq, dk, dv = pa._pallas_backward(
        q, k, v, out, lse, g, True, scale, 128, 128, interpret=True
    )
    rq, rk, rv = pa._chunked_backward(
        q, k, v, out, lse, g, True, scale, chunk=128
    )
    for a, r in zip((dq, dk, dv), (rq, rk, rv)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-3, atol=2e-3
        )


def test_pallas_backward_via_custom_vjp(monkeypatch):
    """The full _flash_attention custom_vjp routes through the pallas
    backward when INTERPRET is on."""
    from dlrover_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "INTERPRET", True)
    q, k, v = _qkv(jax.random.key(4), s=256)
    scale = q.shape[-1] ** -0.5
    g = jax.random.normal(jax.random.key(5), q.shape)
    f = lambda q, k, v: jnp.vdot(  # noqa: E731
        pa._flash_attention(q, k, v, None, None, True, scale, 128, 128), g
    )
    fr = lambda q, k, v: jnp.vdot(  # noqa: E731
        mha_reference(q, k, v, causal=True, softmax_scale=scale), g
    )
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3
        )


def test_quant_roundtrip():
    x = jax.random.normal(jax.random.key(0), (333, 57)) * 3.0
    qa = quantize(x)
    assert qa.q.dtype == jnp.int8
    out = dequantize(qa)
    assert out.shape == x.shape and out.dtype == x.dtype
    # blockwise int8: ~1% relative error on the block max scale
    err = np.abs(np.asarray(out - x)).max()
    assert err <= float(jnp.abs(x).max()) / 127.0 + 1e-6


def test_quantized_optimizer_trains():
    import optax

    opt = quantize_optimizer_state(optax.adam(1e-2))
    params = {"w": jnp.ones((128, 64)), "b": jnp.zeros((4,))}
    state = opt.init(params)
    # large leaf quantized, small leaf untouched
    from dlrover_tpu.ops.quant import QuantizedArray

    leaves = jax.tree.leaves(
        state, is_leaf=lambda x: isinstance(x, QuantizedArray)
    )
    assert any(isinstance(leaf, QuantizedArray) for leaf in leaves)

    def loss(p):
        return jnp.sum(p["w"] ** 2) + jnp.sum(p["b"] ** 2)

    for _ in range(3):
        g = jax.grad(loss)(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < 128 * 64  # moved toward the minimum


def test_quant4_roundtrip():
    x = jax.random.normal(jax.random.key(1), (200, 33)) * 2.0
    qa = quantize(x, bits=4)
    # packed: half the bytes of the 8-bit payload
    assert qa.q.shape[-1] == 128  # BLOCK // 2
    out = dequantize(qa)
    assert out.shape == x.shape and out.dtype == x.dtype
    # blockwise int4: error bounded by scale/2 = blockmax/14
    err = np.abs(np.asarray(out - x)).max()
    assert err <= float(jnp.abs(x).max()) / 14.0 + 1e-6


def test_quant4_exact_levels():
    # values on the int4 grid survive the roundtrip exactly
    x = jnp.array([-7.0, -3.0, 0.0, 1.0, 5.0, 7.0] * 100)
    out = dequantize(quantize(x, bits=4))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)


def test_quantized4_optimizer_trains():
    import optax

    opt = quantize_optimizer_state(optax.adam(1e-2), bits=4)
    params = {"w": jnp.ones((128, 64))}
    state = opt.init(params)

    def loss(p):
        return jnp.sum(p["w"] ** 2)

    for _ in range(5):
        g = jax.grad(loss)(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < 128 * 64


@pytest.mark.slow
def test_lowbit_adamw_chunking_is_exact():
    """Streaming in many chunks must be bit-identical to one big chunk."""
    from dlrover_tpu.ops.quant import BLOCK, lowbit_adamw

    params = {"w": jax.random.normal(jax.random.key(0), (40, 512))}
    g = {"w": jax.random.normal(jax.random.key(1), (40, 512))}
    small = lowbit_adamw(1e-2, weight_decay=0.01, chunk_elems=BLOCK * 2)
    big = lowbit_adamw(1e-2, weight_decay=0.01, chunk_elems=1 << 30)
    s1, s2 = small.init(params), big.init(params)
    for _ in range(3):
        u1, s1 = small.update(g, s1, params)
        u2, s2 = big.update(g, s2, params)
    np.testing.assert_array_equal(np.asarray(u1["w"]), np.asarray(u2["w"]))
    np.testing.assert_array_equal(
        np.asarray(s1["m"]["w"].q), np.asarray(s2["m"]["w"].q)
    )
    np.testing.assert_array_equal(
        np.asarray(s1["v"]["w"].scale), np.asarray(s2["v"]["w"].scale)
    )


def test_lowbit_adamw_matches_generic_wrapper():
    """Fused streaming AdamW ≡ dequant-everything wrapper around
    optax.adamw (same blockwise scheme, bounded memory instead)."""
    import optax

    from dlrover_tpu.ops.quant import lowbit_adamw, quantize_optimizer_state

    wd, lr = 0.05, 3e-3
    params = {"w": jax.random.normal(jax.random.key(2), (64, 128))}
    fused = lowbit_adamw(lr, weight_decay=wd)
    ref = quantize_optimizer_state(optax.adamw(lr, weight_decay=wd))
    pf, pr = params, params
    sf, sr = fused.init(pf), ref.init(pr)

    def loss(p):
        return jnp.sum((p["w"] - 1.0) ** 2)

    for _ in range(5):
        uf, sf = fused.update(jax.grad(loss)(pf), sf, pf)
        ur, sr = ref.update(jax.grad(loss)(pr), sr, pr)
        pf = optax.apply_updates(pf, uf)
        pr = optax.apply_updates(pr, ur)
    np.testing.assert_allclose(
        np.asarray(pf["w"]), np.asarray(pr["w"]), rtol=1e-4, atol=1e-6
    )


@pytest.mark.parametrize("bits", [8, 4])
def test_lowbit_adamw_converges(bits):
    import optax

    from dlrover_tpu.ops.quant import QuantizedArray, lowbit_adamw

    opt = lowbit_adamw(1e-1, bits=bits)
    params = {"w": jnp.ones((128, 64)), "b": jnp.zeros((4,))}
    state = opt.init(params)
    assert isinstance(state["m"]["w"], QuantizedArray)
    assert state["m"]["w"].bits == bits
    assert isinstance(state["m"]["b"], jax.Array)  # small leaf stays dense

    def loss(p):
        return jnp.sum(p["w"] ** 2) + jnp.sum(p["b"] ** 2)

    step = jax.jit(opt.update)
    for _ in range(10):
        g = jax.grad(loss)(params)
        updates, state = step(g, state, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < 0.2 * 128 * 64


def test_make_optimizer_int8_uses_fused_path():
    from dlrover_tpu.train.optimizer import make_optimizer

    opt = make_optimizer(state_dtype="int8", learning_rate=1e-2)
    params = {"w": jnp.ones((128, 64))}
    state = opt.init(params)
    # chain state: (clip, lowbit) — lowbit state is the step/m/v dict
    flat = jax.tree.leaves(
        state, is_leaf=lambda x: hasattr(x, "bits")
    )
    assert any(getattr(x, "bits", None) == 8 for x in flat)
    g = {"w": jnp.full((128, 64), 0.5)}
    updates, state = jax.jit(opt.update)(g, state, params)
    assert jnp.all(jnp.isfinite(updates["w"]))


def test_wsam_converges_and_matches_sam_at_half_gamma():
    import optax

    from dlrover_tpu.train.optimizer import wsam

    def loss(p):
        return jnp.sum((p["w"] - 2.0) ** 2)

    # gamma=0.5 → coef=1 → pure SAM gradient at the perturbed point
    opt = wsam(optax.sgd(0.05), rho=0.01, gamma=0.5)
    params = {"w": jnp.zeros((8,))}
    state = opt.init(params)
    step = jax.jit(opt.update)
    for _ in range(200):  # 100 effective steps (2 phases each)
        g = jax.grad(loss)(params)
        updates, state = step(g, state, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < 1e-3


def test_wsam_gamma_zero_is_vanilla():
    import optax

    from dlrover_tpu.train.optimizer import wsam

    def loss(p):
        return jnp.sum(p["w"] ** 2)

    opt = wsam(optax.sgd(0.1), rho=0.05, gamma=0.0)
    ref = optax.sgd(0.1)
    params = {"w": jnp.full((4,), 3.0)}
    rparams = {"w": jnp.full((4,), 3.0)}
    state, rstate = opt.init(params), ref.init(rparams)
    for _ in range(40):  # 20 effective steps
        g = jax.grad(loss)(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
    # one vanilla step per two wsam phases: at gamma=0 the descent applies
    # the cached params-point gradient and undoes the ascent exactly, so
    # the net trajectory IS vanilla sgd
    for _ in range(20):
        rg = jax.grad(loss)(rparams)
        rupd, rstate = ref.update(rg, rstate, rparams)
        rparams = optax.apply_updates(rparams, rupd)
    np.testing.assert_allclose(
        np.asarray(params["w"]), np.asarray(rparams["w"]), rtol=1e-5
    )


def test_wsam_gamma_bounds():
    import optax

    from dlrover_tpu.train.optimizer import make_optimizer, wsam

    with pytest.raises(ValueError):
        wsam(optax.sgd(0.1), gamma=1.0)
    with pytest.raises(ValueError):
        make_optimizer(name="wsam", state_dtype="int8")


def test_make_optimizer_wsam_and_int4():
    from dlrover_tpu.train.optimizer import make_optimizer

    opt = make_optimizer(name="wsam", learning_rate=1e-2)
    params = {"w": jnp.ones((16,))}
    state = opt.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    updates, state = opt.update(g, state, params)
    assert jax.tree.structure(updates) == jax.tree.structure(params)

    opt4 = make_optimizer(state_dtype="int4")
    state4 = opt4.init({"w": jnp.ones((128, 64))})
    from dlrover_tpu.ops.quant import QuantizedArray

    leaves = jax.tree.leaves(
        state4, is_leaf=lambda x: isinstance(x, QuantizedArray)
    )
    assert any(
        isinstance(leaf, QuantizedArray) and leaf.bits == 4
        for leaf in leaves
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_backward_matches_reference(causal):
    """The chunked flash backward (lse-based) must match autodiff through
    the reference attention — without materializing [S, S]."""
    from dlrover_tpu.ops.pallas_attention import (
        _chunked_backward,
        _flash_fwd,
    )

    q, k, v = _qkv(jax.random.key(2), b=2, s=256, h=4, d=64)
    scale = q.shape[-1] ** -0.5
    out, lse = _flash_fwd(
        q, k, v, causal, scale, block_q=128, block_k=128, interpret=True
    )
    g = jax.random.normal(jax.random.key(3), out.shape, out.dtype)

    dq, dk, dv = _chunked_backward(
        q, k, v, out, lse, g, causal, scale, chunk=64
    )

    def ref(q, k, v):
        return mha_reference(q, k, v, causal=causal, softmax_scale=scale)

    _, vjp = jax.vjp(ref, q, k, v)
    rdq, rdk, rdv = vjp(g)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), rtol=2e-3, atol=2e-3)


def test_flash_backward_gqa():
    from dlrover_tpu.ops.pallas_attention import (
        _chunked_backward,
        _flash_fwd,
    )

    q, k, v = _qkv(jax.random.key(4), b=2, s=128, h=8, hkv=2, d=32)
    scale = q.shape[-1] ** -0.5
    out, lse = _flash_fwd(
        q, k, v, True, scale, block_q=128, block_k=128, interpret=True
    )
    g = jax.random.normal(jax.random.key(5), out.shape, out.dtype)
    dq, dk, dv = _chunked_backward(q, k, v, out, lse, g, True, scale, chunk=64)

    def ref(q, k, v):
        return mha_reference(q, k, v, causal=True, softmax_scale=scale)

    _, vjp = jax.vjp(ref, q, k, v)
    rdq, rdk, rdv = vjp(g)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), rtol=2e-3, atol=2e-3)


def test_chunked_backward_with_lse_cotangent():
    """Ring attention differentiates through the flash lse output; the
    chunked backward's g_lse term must match autodiff of (out, lse)."""
    from dlrover_tpu.ops.pallas_attention import (
        _chunked_backward,
        _flash_fwd,
    )

    q, k, v = _qkv(jax.random.key(7), b=2, s=128, h=4, d=32)
    scale = q.shape[-1] ** -0.5
    out, lse = _flash_fwd(
        q, k, v, True, scale, block_q=128, block_k=128, interpret=True
    )
    g_out = jax.random.normal(jax.random.key(8), out.shape, out.dtype)
    g_lse = jax.random.normal(jax.random.key(9), lse.shape, lse.dtype)

    def ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        lse = jax.nn.logsumexp(s, axis=-1)
        p = jnp.exp(s - lse[..., None])
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return o, lse

    _, vjp = jax.vjp(ref, q, k, v)
    rdq, rdk, rdv = vjp((g_out, g_lse))
    dq, dk, dv = _chunked_backward(
        q, k, v, out, lse, g_out, True, scale, chunk=64, g_lse=g_lse
    )
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# fp8 delayed-scaling GEMM (ops/fp8.py)
# ---------------------------------------------------------------------------


def test_fp8_dot_close_to_exact():
    from dlrover_tpu.ops import fp8

    x = jax.random.normal(jax.random.key(0), (64, 128)) * 2.0
    w = jax.random.normal(jax.random.key(1), (128, 32)) * 0.5
    state = fp8.init_fp8_state()
    # warm the amax histories so the delayed scales match the data
    for _ in range(2):
        g = jax.grad(
            lambda x, w, s: jnp.sum(fp8.fp8_dot(x, w, s) ** 2),
            argnums=(0, 1, 2),
        )(x, w, state)
        state = g[2]
    out = fp8.fp8_dot(x, w, state)
    exact = x @ w
    # e4m3 has ~2 decimal digits; relative error stays in the few-% band
    rel = float(
        jnp.linalg.norm(out.astype(jnp.float32) - exact)
        / jnp.linalg.norm(exact)
    )
    assert rel < 0.05, rel


def test_fp8_state_rides_the_cotangent():
    from dlrover_tpu.ops import fp8

    x = jax.random.normal(jax.random.key(0), (16, 64)) * 3.0
    w = jax.random.normal(jax.random.key(1), (64, 16))
    state = fp8.init_fp8_state()
    dx, dw, new_state = jax.grad(
        lambda x, w, s: jnp.sum(fp8.fp8_dot(x, w, s)), argnums=(0, 1, 2)
    )(x, w, state)
    # the "state gradient" is the UPDATED state: histories rolled with
    # the observed amaxes, not derivatives
    assert float(new_state["amax_x"][-1]) == pytest.approx(
        float(jnp.max(jnp.abs(x))), rel=1e-6
    )
    assert float(new_state["amax_w"][-1]) == pytest.approx(
        float(jnp.max(jnp.abs(w))), rel=1e-6
    )
    assert float(new_state["amax_g"][-1]) == pytest.approx(1.0)  # dL/dy = 1
    # gradients exist and have the right shapes/dtypes
    assert dx.shape == x.shape and dw.shape == w.shape
    assert jnp.isfinite(dx).all() and jnp.isfinite(dw).all()


def test_fp8_gradients_approximate_exact():
    from dlrover_tpu.ops import fp8

    x = jax.random.normal(jax.random.key(2), (32, 64))
    w = jax.random.normal(jax.random.key(3), (64, 48))
    state = fp8.init_fp8_state()
    for _ in range(2):
        g = jax.grad(
            lambda x, w, s: jnp.sum(fp8.fp8_dot(x, w, s) ** 2),
            argnums=(0, 1, 2),
        )(x, w, state)
        state = g[2]
    dx8, dw8, _ = jax.grad(
        lambda x, w, s: jnp.sum(fp8.fp8_dot(x, w, s) ** 2),
        argnums=(0, 1, 2),
    )(x, w, state)
    dx, dw = jax.grad(
        lambda x, w: jnp.sum((x @ w) ** 2), argnums=(0, 1)
    )(x, w)
    for a, b in ((dx8, dx), (dw8, dw)):
        rel = float(
            jnp.linalg.norm(a.astype(jnp.float32) - b)
            / jnp.linalg.norm(b)
        )
        # e5m2 gradient quantization: coarser than e4m3
        assert rel < 0.15, rel


def test_fp8_strategy_gated_on_hardware():
    from dlrover_tpu.accelerate.device_context import (
        detect_device_context,
        fp8_supported,
    )
    from dlrover_tpu.accelerate.strategy import apply_strategy

    ctx = detect_device_context()
    assert ctx.n_devices >= 1
    assert not fp8_supported()  # CPU test platform has no native fp8
    with pytest.raises(ValueError, match="fp8"):
        apply_strategy([("fp8", {})])
    plan = apply_strategy([("fp8", {"force": True})])
    assert plan.fp8


def test_mixed_adamw_tracks_dense_adamw():
    """bf16 m + int8 nu must track dense AdamW step-for-step within
    quantization tolerance on a toy quadratic."""
    import optax

    from dlrover_tpu.ops.quant import mixed_adamw

    params = {"w": jnp.linspace(-1.0, 1.0, 4096).reshape(16, 256)}
    dense = optax.adamw(1e-2, b1=0.9, b2=0.99, weight_decay=0.01)
    mixed = mixed_adamw(1e-2, b1=0.9, b2=0.99, weight_decay=0.01)
    sd, sm = dense.init(params), mixed.init(params)
    pd = pm = params
    for i in range(5):
        g = jax.tree.map(
            lambda p: p + 0.1 * jnp.sin(i + jnp.arange(p.size, dtype=jnp.float32)).reshape(p.shape),
            pd,
        )
        ud, sd = dense.update(g, sd, pd)
        um, sm = mixed.update(g, sm, pm)
        pd = optax.apply_updates(pd, ud)
        pm = optax.apply_updates(pm, um)
    # blockwise-int8 nu leaves a small tail of outliers where a block's
    # absmax dwarfs an element's variance (known 8-bit-Adam behavior) —
    # require elementwise agreement for >=99.5% and a bounded drift
    close = np.isclose(pm["w"], pd["w"], rtol=0.05, atol=2e-3)
    assert close.mean() > 0.995, close.mean()
    assert float(jnp.abs(pm["w"] - pd["w"]).mean()) < 5e-3


def test_factored_adamw_matrix_and_vector_paths():
    """Factored nu (Adafactor estimator) approximates dense AdamW on
    matrices; vectors/scalars use EXACT nu and must match tightly."""
    import optax

    from dlrover_tpu.train.optimizer import factored_adamw

    params = {
        "w": jnp.ones((256, 512)) * 0.5,   # factored
        "b": jnp.ones((300,)) * 0.5,        # exact nu (vector)
    }
    dense = optax.adamw(1e-2, b1=0.9, b2=0.99, weight_decay=0.0)
    fact = factored_adamw(1e-2, b1=0.9, b2=0.99)
    sd, sf = dense.init(params), fact.init(params)
    pd = pf = params
    rng = np.random.RandomState(0)
    for _ in range(5):
        g = {
            # rank-1-ish gradient so the factored estimator is near-exact
            "w": jnp.asarray(
                np.outer(rng.rand(256) + 0.5, rng.rand(512) + 0.5),
                jnp.float32,
            ),
            "b": jnp.asarray(rng.rand(300) + 0.5, jnp.float32),
        }
        ud, sd = dense.update(g, sd, pd)
        uf, sf = fact.update(g, sf, pf)
        pd = optax.apply_updates(pd, ud)
        pf = optax.apply_updates(pf, uf)
    # vector path: bf16-m noise only
    np.testing.assert_allclose(pf["b"], pd["b"], rtol=2e-2, atol=1e-3)
    # matrix path: factored estimator tolerance
    np.testing.assert_allclose(pf["w"], pd["w"], rtol=0.1, atol=5e-3)
    # state size: factored nu is O(rows+cols), not O(rows*cols)
    v_w = sf[0]["v"]["w"] if isinstance(sf, tuple) else sf["v"]["w"]
    assert v_w["r"].size + v_w["c"].size == 256 + 512


def test_factored_adamw_trains_tiny_model():
    """End-to-end: make_optimizer(state_dtype='factored') drives the
    decoder loss down (the bench recipe's optimizer actually learns)."""
    from dlrover_tpu.models import decoder, get_config
    from dlrover_tpu.train import make_optimizer
    import optax

    cfg = get_config("tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
                     vocab_size=128, max_seq=32)
    opt = make_optimizer(
        learning_rate=3e-3, warmup_steps=2, decay_steps=200,
        state_dtype="factored",
    )
    params = decoder.init(jax.random.key(0), cfg)
    opt_state = opt.init(params)
    base = np.random.RandomState(0).randint(0, 8, size=(8, 33))
    batch = {
        "tokens": jnp.asarray(base[:, :-1], jnp.int32),
        "targets": jnp.asarray(base[:, 1:], jnp.int32),
    }

    @jax.jit
    def step(params, opt_state):
        (loss, _), g = jax.value_and_grad(
            lambda p: decoder.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        upd, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, upd), opt_state, loss

    first = None
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.7, (first, float(loss))


# -- narrow-head packing (pallas_attention head_pack) -----------------------


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("d,pack", [(64, 2), (32, 4)])
def test_flash_fwd_packed_matches_unpacked(causal, d, pack):
    """Packed forward is the SAME online-softmax math per head, so it
    must be bitwise-identical to the unpacked kernel (and close to the
    reference)."""
    from dlrover_tpu.ops import pallas_attention as pa

    q, k, v = _qkv(jax.random.key(20), s=256, h=pack, d=d)
    scale = d ** -0.5
    out_p, lse_p = pa._flash_fwd(
        q, k, v, causal, scale, block_q=128, block_k=128,
        interpret=True, head_pack=pack,
    )
    out_u, lse_u = pa._flash_fwd(
        q, k, v, causal, scale, block_q=128, block_k=128, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_u))
    np.testing.assert_array_equal(np.asarray(lse_p), np.asarray(lse_u))
    ref = mha_reference(q, k, v, causal=causal, softmax_scale=scale)
    np.testing.assert_allclose(
        np.asarray(out_p), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_flash_fwd_packed_prefix():
    """Prefix-LM masking under packing: the SMEM prefix ref is indexed
    by grid entry (h // pack per batch), a different stride than the
    unpacked kernel's."""
    from dlrover_tpu.ops import pallas_attention as pa

    q, k, v = _qkv(jax.random.key(21), s=256, h=4, d=64)
    scale = 64 ** -0.5
    pref = jnp.array([17, 100], jnp.int32)
    out_p, _ = pa._flash_fwd(
        q, k, v, True, scale, block_q=128, block_k=128, prefix=pref,
        interpret=True, head_pack=2,
    )
    ref = mha_reference(
        q, k, v, causal=True, softmax_scale=scale, prefix_len=pref
    )
    np.testing.assert_allclose(
        np.asarray(out_p), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("d,pack", [(64, 2), (32, 4)])
def test_pallas_backward_packed_matches_reference(causal, d, pack):
    from dlrover_tpu.ops import pallas_attention as pa

    q, k, v = _qkv(jax.random.key(22), s=256, h=pack, d=d)
    scale = d ** -0.5
    out, lse = pa._flash_fwd(
        q, k, v, causal, scale, block_q=128, block_k=128, interpret=True
    )
    g = jax.random.normal(jax.random.key(23), out.shape)
    dq, dk, dv = pa._pallas_backward(
        q, k, v, out, lse, g, causal, scale, 128, 128, interpret=True,
        head_pack=pack,
    )
    # bitwise vs the unpacked kernel: same math, different grid layout
    uq, uk, uv = pa._pallas_backward(
        q, k, v, out, lse, g, causal, scale, 128, 128, interpret=True
    )
    for a, u in zip((dq, dk, dv), (uq, uk, uv)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(u))
    ref = lambda q, k, v: jnp.vdot(  # noqa: E731
        mha_reference(q, k, v, causal=causal, softmax_scale=scale), g
    )
    rq, rk, rv = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip((dq, dk, dv), (rq, rk, rv)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-3, atol=2e-3
        )


@pytest.mark.parametrize("h", [5, 4])
def test_flash_attention_autopack_end_to_end(monkeypatch, h):
    """Public flash_attention with head_pack=0 (auto) at d=64: packs 2
    heads per program, zero-padding the odd h=5 (gpt2-1.5b has 25);
    fwd AND grads must match the reference, including the pad slice."""
    from dlrover_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "INTERPRET", True)
    q, k, v = _qkv(jax.random.key(24), s=128, h=h, d=64)
    scale = 64 ** -0.5
    g = jax.random.normal(jax.random.key(25), q.shape)
    f = lambda q, k, v: jnp.vdot(  # noqa: E731
        pa.flash_attention(q, k, v, causal=True, block_q=128,
                           block_k=128), g
    )
    fr = lambda q, k, v: jnp.vdot(  # noqa: E731
        mha_reference(q, k, v, causal=True, softmax_scale=scale), g
    )
    (lo, go) = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
    (lr, gr) = jax.value_and_grad(fr, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(lo), float(lr), rtol=2e-3)
    for a, r in zip(go, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-3, atol=2e-3
        )


def test_flash_attention_gqa_demotes_head_pack(monkeypatch):
    """GQA layouts run unpacked even when head_pack is forced: numerics
    must still match the reference (the demotion, not a crash)."""
    from dlrover_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "INTERPRET", True)
    q, k, v = _qkv(jax.random.key(26), s=128, h=4, hkv=2, d=64)
    scale = 64 ** -0.5
    out = pa.flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, head_pack=2
    )
    ref = mha_reference(q, k, v, causal=True, softmax_scale=scale)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )
