"""Bayesian-optimization HP search tests.

Reference behavior: brain/hpsearch/bo.py BayesianOptimizer — suggest/observe
over a mixed space, converging faster than random search.
"""

import math

import numpy as np
import pytest

from dlrover_tpu.accelerate.hpsearch import (
    BayesianOptimizer,
    Choice,
    Float,
    GaussianProcess,
    Int,
    SearchSpace,
    expected_improvement,
)


def _space2d():
    return SearchSpace({"x": Float(-2.0, 2.0), "y": Float(-2.0, 2.0)})


def test_encode_decode_roundtrip():
    space = SearchSpace(
        {
            "lr": Float(1e-5, 1e-1, log=True),
            "layers": Int(1, 12),
            "accum": Int(1, 64, log=True),
            "remat": Choice(["none", "full", "selective"]),
        }
    )
    conf = {"lr": 3e-4, "layers": 7, "accum": 8, "remat": "full"}
    out = space.decode(space.encode(conf))
    assert out["layers"] == 7
    assert out["accum"] == 8
    assert out["remat"] == "full"
    assert math.isclose(out["lr"], 3e-4, rel_tol=1e-6)


def test_decode_respects_bounds():
    space = SearchSpace({"n": Int(2, 5), "c": Choice([10, 20])})
    lo = space.decode(np.zeros(space.dim()))
    hi = space.decode(np.ones(space.dim()))
    assert lo["n"] == 2 and hi["n"] == 5
    assert lo["c"] in (10, 20) and hi["c"] in (10, 20)


def test_gp_interpolates_training_points():
    rng = np.random.default_rng(0)
    x = rng.random((12, 2))
    y = np.sin(3 * x[:, 0]) + x[:, 1] ** 2
    gp = GaussianProcess()
    gp.fit(x, y)
    mean, std = gp.predict(x)
    np.testing.assert_allclose(mean, y, atol=1e-3)
    assert (std < 0.1).all()


def test_gp_uncertainty_grows_off_data():
    x = np.array([[0.1, 0.1], [0.2, 0.2]])
    gp = GaussianProcess()
    gp.fit(x, np.array([1.0, 2.0]))
    _, std_near = gp.predict(np.array([[0.15, 0.15]]))
    _, std_far = gp.predict(np.array([[0.9, 0.9]]))
    assert std_far[0] > std_near[0]


def test_ei_prefers_high_mean_and_high_std():
    mean = np.array([0.0, 1.0, 0.0])
    std = np.array([0.1, 0.1, 1.0])
    ei = expected_improvement(mean, std, best=0.5)
    assert ei[1] > ei[0]
    assert ei[2] > ei[0]


def _objective(conf):
    # maximum at (0.5, -0.3); categorical bonus for "b"
    base = -((conf["x"] - 0.5) ** 2) - (conf["y"] + 0.3) ** 2
    return base + (0.5 if conf.get("kind") == "b" else 0.0)


def test_bo_beats_random_search():
    space = SearchSpace(
        {
            "x": Float(-2.0, 2.0),
            "y": Float(-2.0, 2.0),
            "kind": Choice(["a", "b", "c"]),
        }
    )
    budget = 30
    bo_bests, rnd_bests = [], []
    for seed in range(3):
        opt = BayesianOptimizer(space, seed=seed, n_init=8)
        for _ in range(budget):
            conf = opt.suggest()
            opt.observe(conf, _objective(conf))
        bo_bests.append(opt.best()[1])
        rng = np.random.default_rng(1000 + seed)
        rnd_bests.append(
            max(_objective(space.sample(rng)) for _ in range(budget))
        )
    assert np.mean(bo_bests) >= np.mean(rnd_bests) - 1e-9
    assert np.mean(bo_bests) > 0.2  # near the optimum (max 0.5)


def test_bo_best_raises_without_observations():
    opt = BayesianOptimizer(_space2d())
    with pytest.raises(RuntimeError):
        opt.best()


@pytest.mark.slow
def test_engine_bo_mode_returns_feasible():
    from dlrover_tpu.accelerate.engine import search_strategy
    from dlrover_tpu.models import get_config

    cfg = get_config(
        "tiny", n_layer=2, d_model=64, n_head=4, vocab_size=256, max_seq=128
    )
    strat, plan = search_strategy(
        cfg, 8, global_batch=8, seq=128, mode="bo", max_measured=3
    )
    sizes = plan.mesh.resolved_sizes(8)
    assert np.prod(list(sizes.values())) == 8
