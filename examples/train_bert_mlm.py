"""BERT-family masked-LM pretraining on a sharded mesh (synthetic data).

Run (8-device virtual CPU mesh):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_bert_mlm.py --steps 10

The encoder family needs no separate model: ``causal=False`` turns the
shared trunk bidirectional, and ``decoder.loss_fn`` already scores
arbitrary (tokens, targets, mask) triples — MLM is corrupted tokens in,
original tokens as targets, loss masked to the corrupted positions
(reference: atorch's TP BERT blocks, distributed_modules/transformer.py:45;
here the same weights/sharding machinery as GPT, different mask).
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
)

MASK_ID = 3  # [MASK] sentinel in the synthetic vocab


def mlm_batch(rng, b, s, vocab, mask_rate=0.15):
    """BERT recipe: of the selected positions, 80% → [MASK], 10% →
    random token, 10% unchanged; loss only on selected positions."""
    original = rng.integers(4, vocab, size=(b, s)).astype(np.int32)
    selected = rng.random((b, s)) < mask_rate
    roll = rng.random((b, s))
    corrupted = original.copy()
    corrupted[selected & (roll < 0.8)] = MASK_ID
    rand_pos = selected & (roll >= 0.8) & (roll < 0.9)
    corrupted[rand_pos] = rng.integers(
        4, vocab, size=int(rand_pos.sum())
    ).astype(np.int32)
    return {
        "tokens": jnp.asarray(corrupted),
        "targets": jnp.asarray(original),
        "mask": jnp.asarray(selected.astype(np.float32)),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=64)
    args = p.parse_args()

    n_dev = jax.device_count()
    mesh = build_mesh(MeshConfig(dp=n_dev))
    cfg = get_config("tiny-bert", max_seq=args.seq)
    opt = make_optimizer(
        learning_rate=1e-3, warmup_steps=5, decay_steps=500
    )
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    bsh = batch_sharding(mesh)

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    for i in range(1, args.steps + 1):
        batch = jax.device_put(
            mlm_batch(rng, args.batch, args.seq, cfg.vocab_size), bsh
        )
        state, m = step(state, batch)
        print(
            f"[bert-mlm] step={i} loss={float(m['loss']):.4f} "
            f"masked_acc={float(m['accuracy']):.3f}"
        )
    print(
        f"[bert-mlm] done at step {args.steps} "
        f"({time.perf_counter() - t0:.1f}s, dp={n_dev})"
    )


if __name__ == "__main__":
    main()
