"""Platform-binding demo: the full K8s reconcile loop on an API double.

Runs the production wiring — PodWatcher list-watch → NodeEvents →
JobManager relaunch decisions → SliceScaler pod creates — against
FakeKubeApi (an in-process API-server double with resourceVersion'd
watch streams), and injects the failures a real cluster throws:

  1. master creates the worker pods and they come up
  2. one pod is OOM-killed → watch event → relaunch (budget consumed)
  3. one pod is evicted → relaunch WITHOUT consuming budget
  4. the relaunches' own predecessor deletions arrive as stale
     watch events (old incarnation) and are suppressed — no cascade
  5. the job scales in → released pods' deletions are expected

Usage:  python examples/run_kube_reconcile.py
"""

import sys
import time

sys.path.insert(0, ".")  # repo-root run: `python examples/...`

from dlrover_tpu.cluster.crd import (  # noqa: E402
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    TPUSliceSpec,
)
from dlrover_tpu.cluster.kube import (  # noqa: E402
    JOB_LABEL,
    FakeKubeApi,
    PodWatcher,
)
from dlrover_tpu.cluster.scaler import SliceScaler  # noqa: E402
from dlrover_tpu.master.node_manager import (  # noqa: E402
    JobManager,
    ScalePlan,
)


def wait_for(cond, timeout=5.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(what)


def pods(api):
    return sorted(
        (
            p["metadata"]["name"],
            p.get("status", {}).get("phase", "?"),
        )
        for p in api.list("Pod", label_selector={JOB_LABEL: "demo"})
    )


def main():
    api = FakeKubeApi()
    job = ElasticJob(
        "demo",
        spec=ElasticJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=3, slice=TPUSliceSpec(hosts_per_slice=1)
                )
            },
            min_hosts=1,
            max_hosts=4,
        ),
    )
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
        master_addr="10.0.0.1:8000",
    )
    jm = JobManager(num_workers=3, relaunch_budget=2, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)

    print("== 1. create worker pods")
    plan = ScalePlan()
    plan.worker_num = 3
    scaler.scale(plan)
    watcher.start()
    for i in range(3):
        api.set_pod_phase(f"demo-worker-{i}", "Running")
    wait_for(
        lambda: all(
            jm.get_node(i).status == "running" for i in range(3)
        ),
        what="pods running",
    )
    print("   pods:", pods(api))

    print("== 2. worker-0 OOM-killed → relaunch (budget consumed)")
    api.set_pod_phase("demo-worker-0", "Failed", reason="OOMKilled")
    wait_for(
        lambda: api.get("Pod", "demo-worker-0-r1") is not None,
        what="replacement for worker-0",
    )
    api.set_pod_phase("demo-worker-0-r1", "Running")
    wait_for(lambda: jm.get_node(0).status == "running")
    print(
        f"   node 0: relaunch_count={jm.get_node(0).relaunch_count} "
        f"incarnation={jm.get_node(0).incarnation}"
    )

    print("== 3. worker-1 evicted → relaunch WITHOUT consuming budget")
    api.set_pod_phase("demo-worker-1", "Failed", reason="Evicted")
    wait_for(
        lambda: api.get("Pod", "demo-worker-1-r1") is not None,
        what="replacement for worker-1",
    )
    api.set_pod_phase("demo-worker-1-r1", "Running")
    wait_for(lambda: jm.get_node(1).status == "running")
    print(
        f"   node 1: relaunch_count={jm.get_node(1).relaunch_count} "
        f"(eviction is budget-free), incarnation="
        f"{jm.get_node(1).incarnation}"
    )

    print("== 4. stale-event suppression")
    # each relaunch above DELETED its predecessor pod; those DELETED
    # watch events carry the old incarnation label and the master drops
    # them — otherwise every relaunch would cascade into another one.
    # Proof: no -r2 replacements exist and the nodes stay running.
    time.sleep(0.3)
    assert api.get("Pod", "demo-worker-0-r2") is None
    assert api.get("Pod", "demo-worker-1-r2") is None
    assert jm.get_node(0).status == "running"
    assert jm.get_node(1).status == "running"
    assert jm.get_node(0).incarnation == 1
    print("   no relaunch cascade:", pods(api))

    print("== 5. scale in to 1 worker (released pods are not failures)")
    jm.set_worker_num(1)
    plan = ScalePlan()
    plan.worker_num = 1
    scaler.scale(plan)
    time.sleep(0.3)
    live = [n for n, ph in pods(api) if ph != "Failed"]
    print("   live pods:", live)
    assert live == ["demo-worker-0-r1"], live

    watcher.stop()
    jm.stop()
    print("[kube-reconcile] done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
