"""Deployable control-plane demo: operator + brain + CRDs, end to end.

Runs the production control-plane wiring on the in-process API double —
everything the k8s deployment (deploy/) would run, minus the cluster:

  1. a brain service starts standalone (the shared cluster optimizer)
     and is seeded with a finished same-kind job's metrics
  2. the operator elects a leader (ConfigMap lease), then adopts an
     applied ElasticJob: wire-token Secret minted, master pod + Service
     first, worker pods with the master address injected
  3. pod phases flow into ElasticJob.status (the status subresource —
     what `kubectl get elasticjobs` shows) and the reconcile trail
     lands as k8s Events
  4. a ScalePlan scales the job and is marked Succeeded (replay-safe)
  5. job deletion tears everything down (pods, Service, Secret)

Usage:  python examples/run_operator_stack.py
Reference: dlrover/go/operator main.go + config/, go/brain.
"""

import sys
import threading
import time

sys.path.insert(0, ".")  # repo-root run: `python examples/...`

from dlrover_tpu.cluster.brain import (  # noqa: E402
    BrainClient,
    BrainService,
    BrainWireServer,
    JobMetrics,
)
from dlrover_tpu.cluster.crd import (  # noqa: E402
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    ScalePlanCRD,
)
from dlrover_tpu.cluster.kube import JOB_LABEL, FakeKubeApi  # noqa: E402
from dlrover_tpu.cluster.operator import (  # noqa: E402
    LeaderElector,
    OperatorController,
)


def wait_for(cond, timeout=20.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise SystemExit(f"timed out waiting for {what}")


def main() -> int:
    # 1. the cluster brain, standalone over the wire
    brain = BrainWireServer(BrainService(max_workers=8), port=0)
    client = BrainClient(f"127.0.0.1:{brain.port}")
    client.persist_metrics(
        JobMetrics(
            job_name="yesterday",
            job_kind="gpt",
            worker_num=4,
            samples_per_sec=900.0,
            finished=True,
        )
    )
    client.bind_job("demo", "gpt")
    plan = client.generate_plan("create", {})
    print(f"   brain first-allocation for kind 'gpt': {plan.worker_num} workers")

    # 2. leader-elected operator adopts the job
    api = FakeKubeApi()
    elector = LeaderElector(api, ttl_s=5.0)
    assert elector.try_acquire()
    print(f"   leader: {elector.identity}")
    ctl = OperatorController(api, status_interval_s=0.2)
    ctl.start()
    api.create(
        ElasticJob(
            "demo",
            spec=ElasticJobSpec(
                replica_specs={"worker": ReplicaSpec(replicas=2)},
                min_hosts=1,
                max_hosts=8,
            ),
        ).to_manifest()
    )
    wait_for(
        lambda: api.get("Pod", "demo-worker-1") is not None, what="workers"
    )
    assert api.get("Pod", "demo-master") is not None
    assert api.get("Service", "demo-master") is not None
    assert api.get("Secret", "demo-wire-token") is not None
    print("   adopted: master + 2 workers + Service + wire-token Secret")

    # 3. pod phases → status subresource + events
    api.set_pod_phase("demo-worker-0", "Running")
    wait_for(
        lambda: (api.get("ElasticJob", "demo") or {})
        .get("status", {})
        .get("phase")
        == "Running",
        what="Running status",
    )
    events = [
        e["reason"] for e in api.list("Event", label_selector={JOB_LABEL: "demo"})
    ]
    print(f"   status: Running; events: {events}")

    # 4. ScalePlan → scale + terminal phase
    api.create(
        ScalePlanCRD(
            job_name="demo", name="grow", replica_counts={"worker": 4}
        ).to_manifest()
    )
    wait_for(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"})) == 5,
        what="scale to 4 workers (+master)",
    )
    wait_for(
        lambda: (api.get("ScalePlan", "grow") or {})
        .get("status", {})
        .get("phase")
        == "Succeeded",
        what="plan marked Succeeded",
    )
    print("   scaled to 4 via ScalePlan; plan Succeeded")

    # 5. teardown on delete
    api.delete("ElasticJob", "demo")
    wait_for(
        lambda: not api.list("Pod", label_selector={JOB_LABEL: "demo"}),
        what="teardown",
    )
    assert api.get("Secret", "demo-wire-token") is None
    print("   deleted: pods, Service and Secret removed")

    ctl.stop()
    client.close()
    brain.stop()
    print("[operator-stack] done")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
