"""Full-stack drill worker: DeepFM over a KvServer ring, fed over TCP.

The production composition the fault-tolerance story is about
(reference: docs/tech_report/fault_tolerance_exps.md — elastic worker
pool + elastic PS tier + data pipeline in ONE job): this worker

- serves a ``BatchFeedServer`` ingress (remote coworker producers push
  packed CTR batches into the host's shm ring; the port is printed for
  the producer pool to discover),
- trains a DeepFM whose sparse tier lives on a KvServer ring
  (``DistributedEmbedding``; addresses from ``--kv-addrs``),
- reports global steps to the job master when launched under the
  elastic agent (``DLROVER_TPU_MASTER_ADDR``),
- and self-heals a sparse-server death: on a wire error it probes the
  ring, adopts the survivors with ``migrate=False`` (availability over
  durability — lost rows re-initialize on touch) and keeps stepping.

Run by ``tests/test_fullstack_drill.py`` under a real master + two
launcher/agent process groups, with the test killing an agent AND a
sparse server mid-run.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from dlrover_tpu.common.constants import GraftEnv
from dlrover_tpu.data.coworker import BatchFeedServer, BatchRing
from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
from dlrover_tpu.observability.tracing import get_tracer
from dlrover_tpu.sparse import GroupAdam
from dlrover_tpu.sparse.embedding import EmbeddingSpec
from dlrover_tpu.sparse.server import DistributedEmbedding, KvClient


def _specs(emb_dim):
    return [
        EmbeddingSpec("emb", emb_dim, initializer="normal",
                      init_scale=0.01, seed=3),
        EmbeddingSpec("wide", 1, initializer="zeros"),
    ]


def _probe_survivors(servers, timeout=3.0):
    alive = {}
    for name, addr in servers.items():
        try:
            c = KvClient(tuple(addr), timeout=timeout)
            c.stats()
            c.close()
            alive[name] = tuple(addr)
        except Exception:  # noqa: BLE001 — dead/unreachable server
            continue
    return alive


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--kv-addrs", required=True,
                   help='JSON {"s0": ["127.0.0.1", port], ...}')
    p.add_argument("--emb-dim", type=int, default=8)
    p.add_argument("--fields", type=int, default=6)
    p.add_argument("--dense", type=int, default=4)
    args = p.parse_args()

    servers = {
        k: tuple(v) for k, v in json.loads(args.kv_addrs).items()
    }
    # tracer auto-enables from DLROVER_TPU_TRACE_DIR (role=worker comes
    # from the env the agent injected); restart>0 means this process is
    # the recovery — its model/sparse-tier re-setup is the restore phase
    tracer = get_tracer()
    restart = int(os.environ.get(GraftEnv.RESTART_COUNT, "0") or 0)
    restore_span = (
        tracer.span("failover.restore", tier="kv_ring") if restart > 0
        else None
    )
    cfg = DeepFMConfig(
        n_fields=args.fields, n_dense=args.dense,
        emb_dim=args.emb_dim, mlp_dims=(32,),
    )
    model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
    model.coll.close()
    demb = DistributedEmbedding(_specs(cfg.emb_dim), servers)
    model.coll = demb
    if restore_span is not None:
        restore_span.end(servers=len(servers))

    ring = BatchRing("drill", slots=4, slot_bytes=1 << 20, create=True)
    feed = BatchFeedServer(ring, host="127.0.0.1")
    # the producer pool (the test) scrapes this line for the ingress
    # port; printed twice because the merged worker pipe can interleave
    # one copy with logger output mid-line
    print(f"[fullstack] feed port {feed.address[1]}", flush=True)
    print(f"[fullstack] feed port {feed.address[1]}", flush=True)

    master = None
    try:
        addr = os.environ.get("DLROVER_TPU_MASTER_ADDR")
        if addr:
            from dlrover_tpu.agent.master_client import MasterClient

            master = MasterClient(addr)
    except Exception:  # noqa: BLE001 — drill runs standalone too
        master = None

    step = 0
    while step < args.steps:
        batch = ring.get(timeout=120.0)
        if batch is None:
            print("[fullstack] producers done early", flush=True)
            break
        try:
            loss = model.train_step(
                batch["cat"].astype(np.int64),
                batch["dense"].astype(np.float32),
                batch["labels"].astype(np.float32),
            )
        except Exception as e:  # noqa: BLE001 — sparse-tier wire error
            tracer.instant("failover.sparse_detect", step=step)
            with tracer.span(
                "failover.sparse_probe", servers=len(servers)
            ) as probe:
                survivors = _probe_survivors(servers)
                probe.args["alive"] = len(survivors)
            if not survivors:
                print(f"[fullstack] sparse ring gone: {e}", flush=True)
                raise
            servers = survivors
            with tracer.span(
                "failover.sparse_adopt", survivors=len(survivors)
            ):
                demb.set_servers(survivors, migrate=False)
            print(
                f"[fullstack] sparse failover to {sorted(survivors)}",
                flush=True,
            )
            continue
        step += 1
        if step == 1 and restart > 0:
            # recovery timeline closes: the respawned worker stepped
            tracer.instant("failover.first_step", step=step)
        print(f"[fullstack] step {step} loss {loss:.4f}", flush=True)
        if master is not None and step % 5 == 0:
            try:
                master.report_global_step(step)
            except Exception:  # noqa: BLE001
                master = None
    print("[fullstack] done", flush=True)
    feed.stop()
    ring.close()
    demb.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
