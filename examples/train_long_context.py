"""Long-context training with ring attention (context parallelism).

The sequence is sharded over the ``sp`` mesh axis; k/v blocks rotate the
ring via collective-permute over ICI while each device accumulates its
local q block's online-softmax — exact attention at O(S/sp) activation
memory per device. (The reference has no ring/context parallelism at all;
SURVEY.md §5.)

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_long_context.py --seq 2048 --steps 10
"""

import argparse
import sys

sys.path.insert(0, ".")  # repo-root run: `python examples/...`

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import Trainer, TrainerArgs, make_optimizer


def data_iter(batch, seq, vocab, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        b = rng.randint(0, vocab // 4, size=(batch, seq + 1))
        yield {
            "tokens": jnp.asarray(b[:, :-1], jnp.int32),
            "targets": jnp.asarray(b[:, 1:], jnp.int32),
        }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--sp", type=int, default=0,
                   help="ring size (0 = device_count // 4, min 2)")
    p.add_argument("--attn", choices=["ring", "ulysses"], default="ring")
    p.add_argument("--output", default="/tmp/dlrover_tpu_longctx")
    args = p.parse_args()

    n_dev = jax.device_count()
    sp = args.sp or max(2, n_dev // 4)
    assert n_dev % sp == 0 and args.seq % sp == 0
    dp = n_dev // sp
    assert args.batch % dp == 0, (
        f"--batch {args.batch} must be divisible by dp={dp} "
        f"(= devices {n_dev} / sp {sp})"
    )
    mesh = build_mesh(MeshConfig(sp=sp, dp=dp))
    cfg = get_config(
        "tiny",
        n_layer=2,
        d_model=128,
        d_ff=256,
        n_head=8,
        max_seq=args.seq,
    )
    trainer = Trainer(
        cfg,
        TrainerArgs(
            output_dir=args.output,
            max_steps=args.steps,
            log_interval=5,
            save_interval=0,
            report_to_master=False,
            resume=False,
            attn_impl=args.attn,
        ),
        data_iter(args.batch, args.seq, cfg.vocab_size),
        make_optimizer(learning_rate=1e-3, warmup_steps=5, decay_steps=1000),
        mesh=mesh,
    )
    state = trainer.train()
    print(
        f"[long-context] done at step {int(state['step'])} "
        f"(seq {args.seq}, {args.attn} over sp={sp})"
    )


if __name__ == "__main__":
    main()
