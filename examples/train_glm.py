"""GLM-style prefix-LM training (blank infilling) on a sharded mesh.

Run (8-device virtual CPU mesh):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_glm.py --steps 10

Demonstrates the prefix-LM family (models/config.py tiny-glm / glm-10b):
each sequence's prefix (the "part A" context) is bidirectionally visible
while the tail is generated causally — the mask rule runs inside the
flash kernel (per-batch prefix scalar in SMEM) and through ring/ulysses
sequence parallelism. The loss is masked to the causal tail, the GLM
objective.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
)


def infilling_batch(rng, b, s, vocab):
    """Synthetic GLM-shaped batch: random tokens with a per-sequence
    prefix/tail split — the prefix is bidirectionally visible context
    and the loss scores only the causal tail (the GLM objective shape;
    the data itself is random, this demonstrates plumbing not MLM)."""
    toks = rng.integers(4, vocab, size=(b, s)).astype(np.int32)
    prefix = rng.integers(s // 4, 3 * s // 4, size=(b,)).astype(np.int32)
    pos = np.arange(s)[None, :]
    mask = (pos >= prefix[:, None]).astype(np.float32)
    targets = np.roll(toks, -1, axis=1)
    return {
        "tokens": jnp.asarray(toks),
        "targets": jnp.asarray(targets),
        "mask": jnp.asarray(mask),           # score only the causal tail
        "prefix_len": jnp.asarray(prefix),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    args = p.parse_args()

    n_dev = jax.device_count()
    mesh = build_mesh(MeshConfig(dp=n_dev))
    cfg = get_config("tiny-glm", max_seq=args.seq, n_layer=2)
    opt = make_optimizer(
        learning_rate=1e-3, warmup_steps=5, decay_steps=500
    )
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    bsh = batch_sharding(mesh)
    psh = shd.shardings_for_tree(mesh, {"p": ("batch",)})["p"]

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    for i in range(1, args.steps + 1):
        batch = infilling_batch(rng, args.batch, args.seq, cfg.vocab_size)
        batch = {
            k: jax.device_put(v, psh if v.ndim == 1 else bsh)
            for k, v in batch.items()
        }
        state, m = step(state, batch)
        print(
            f"[glm] step={i} loss={float(m['loss']):.4f} "
            f"acc={float(m['accuracy']):.3f}"
        )
    print(
        f"[glm] done at step {args.steps} "
        f"({time.perf_counter() - t0:.1f}s, prefix-LM over dp={n_dev})"
    )


if __name__ == "__main__":
    main()
