"""The full stack in one script: auto_accelerate -> Trainer -> flash ckpt.

The L3+L4 story (reference: atorch auto_accelerate feeding AtorchTrainer):
strategy search picks the mesh/remat/state-dtype plan for the hardware,
the Trainer drives the loop with callbacks (loss-spike guard, LR log),
checkpoints stage to memory + persist async, and a re-run resumes.

Run standalone on the CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_auto_stack.py --steps 30

or under the elastic launcher (adds master, rendezvous, failover):

    python -m dlrover_tpu.agent.launcher --nnodes 1 -- \
        python examples/train_auto_stack.py --steps 30
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, ".")  # repo-root run: `python examples/...`


def batches(cfg, global_batch, seq, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        data = rng.randint(
            0, cfg.vocab_size, size=(global_batch, seq + 1)
        )
        yield {
            "tokens": data[:, :-1].astype(np.int32),
            "targets": data[:, 1:].astype(np.int32),
        }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--model", default="tiny")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--search", default="heuristic",
                   choices=["heuristic", "cost", "measure", "bo"])
    p.add_argument("--ckpt-dir", default="/tmp/dlrover_tpu_auto_ckpt")
    args = p.parse_args()

    from dlrover_tpu.accelerate.api import auto_accelerate
    from dlrover_tpu.models import get_config
    from dlrover_tpu.train.distributed import init_distributed
    from dlrover_tpu.train.trainer import Trainer, TrainerArgs
    from dlrover_tpu.train.callbacks import LRLoggingCallback

    init_distributed()
    client = None
    if os.environ.get("DLROVER_TPU_MASTER_ADDR"):
        from dlrover_tpu.agent.master_client import build_master_client

        client = build_master_client()

    cfg = get_config(args.model, max_seq=args.seq)
    res = auto_accelerate(
        cfg, global_batch=args.batch, seq=args.seq,
        search_mode=args.search,
    )
    print(f"[auto-stack] strategy: {res.strategy}")
    print(f"[auto-stack] mesh: {dict(res.mesh.shape)}")

    # step_builder/init_state_fn hand the trainer the PLAN's lowering
    # (sp attention override, offloaded opt state, grad accumulation) —
    # rebuilding from raw plan fields would silently drop those.
    # detect_loss_spikes=True (the default) already wires a spike
    # detector callback.
    trainer = Trainer(
        res.model_config,
        TrainerArgs(
            output_dir=args.ckpt_dir,
            max_steps=args.steps,
            log_interval=10,
            save_interval=10,
        ),
        batches(cfg, args.batch, args.seq),
        res.optimizer,
        mesh=res.mesh,
        master_client=client,
        callbacks=[LRLoggingCallback()],
        step_builder=res.step_builder,
        init_state_fn=res.init_state,
        eval_step_fn=res.eval_step,
    )
    state = trainer.train()
    print(f"[auto-stack] done at step {int(state['step'])}", flush=True)


if __name__ == "__main__":
    main()
