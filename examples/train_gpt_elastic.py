"""End-to-end elastic training example.

Run under the elastic launcher (single host spawns a local master):

    python -m dlrover_tpu.agent.launcher --nnodes 1 -- \
        python examples/train_gpt_elastic.py --steps 50

Exercises: master rendezvous → jax.distributed bootstrap → device mesh →
dynamic data sharding from the master's TaskManager → jitted sharded train
step → flash checkpoint (memory stage + async disk persist) → resume after
restart.
"""

import argparse
import sys

sys.path.insert(0, ".")  # repo-root run: `python examples/...`
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.agent.sharding_client import ShardingClient
from dlrover_tpu.checkpoint import Checkpointer, StorageType
from dlrover_tpu.checkpoint.checkpointer import state_template
from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
)
from dlrover_tpu.train.data_utils import form_global_batch, iter_shards_spmd
from dlrover_tpu.train.distributed import init_distributed


def synthetic_batch(start: int, end: int, batch: int, seq: int, vocab: int):
    rng = np.random.RandomState(start)
    n = batch * (seq + 1)
    data = rng.randint(0, vocab, size=n).reshape(batch, seq + 1)
    return {
        "tokens": jnp.asarray(data[:, :-1], jnp.int32),
        "targets": jnp.asarray(data[:, 1:], jnp.int32),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--model", default="tiny")
    p.add_argument("--ckpt-dir", default="/tmp/dlrover_tpu_example_ckpt")
    p.add_argument("--ckpt-every", type=int, default=5)
    p.add_argument("--crash-at", type=int, default=-1,
                   help="deliberately crash at this step (failover demo)")
    p.add_argument(
        "--hosts-per-slice", type=int, default=0,
        help="build a hybrid multi-slice mesh: every hosts-per-slice "
        "processes form one emulated ICI slice, dp rides DCN across "
        "slices (num_slices = process_count // hosts_per_slice)",
    )
    args = p.parse_args()

    init_distributed()
    client = build_master_client()
    if args.hosts_per_slice > 0:
        # slice-grain elasticity: the mesh is rebuilt from the CURRENT
        # world every (re)start, so a world that shrank by a whole slice
        # re-meshes to fewer slices (dp shrinks, fsdp stays intra-slice)
        num_slices = max(1, jax.process_count() // args.hosts_per_slice)
        mesh = build_mesh(
            MeshConfig(dp=num_slices, fsdp=-1, num_slices=num_slices)
        )
        print(
            f"[worker] slice mesh: num_slices={num_slices} "
            f"dp={mesh.shape['dp']} fsdp={mesh.shape['fsdp']}",
            flush=True,
        )
    else:
        mesh = build_mesh(MeshConfig(dp=-1))
    cfg = get_config(args.model, max_seq=args.seq)
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=5, decay_steps=1000)

    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    ckpt = Checkpointer(args.ckpt_dir, master_client=client)
    restored = ckpt.load_checkpoint(
        state_template(state),
        shardings=jax.tree.map(lambda x: x.sharding, state),
    )
    if restored is not None:
        state = restored
        print(f"[worker] resumed from step {int(state['step'])}", flush=True)

    step_fn = TrainStepBuilder(cfg, mesh, opt).build()
    # SPMD: one shard = one GLOBAL step (batch rows × processes); rank 0
    # fetches from the master and broadcasts so all processes stay in
    # lockstep; each process slices its own rows out of the shard.
    nproc = jax.process_count()
    sharding = ShardingClient(
        client,
        "train",
        dataset_size=args.steps * args.batch * nproc,
        shard_size=args.batch * nproc,
    )

    bsh = batch_sharding(mesh)
    t0 = time.time()
    for start, end in iter_shards_spmd(sharding):
        local_start = start + jax.process_index() * args.batch
        step = int(state["step"])
        if (
            args.crash_at >= 0
            and step >= args.crash_at
            and int(os.environ.get("DLROVER_TPU_RESTART_COUNT", "0")) == 0
        ):
            print(f"[worker] simulating crash at step {step}", flush=True)
            os._exit(17)
        batch = form_global_batch(
            synthetic_batch(
                local_start,
                local_start + args.batch,
                args.batch,
                args.seq,
                cfg.vocab_size,
            ),
            bsh,
        )
        state, metrics = step_fn(state, batch)
        step = int(state["step"])
        client.report_global_step(step)
        if step % args.ckpt_every == 0:
            kind = (
                StorageType.DISK
                if step % (2 * args.ckpt_every) == 0
                else StorageType.MEMORY
            )
            ckpt.save_checkpoint(step, state, kind)
        print(
            f"[worker] step={step} loss={float(metrics['loss']):.4f} "
            f"({(time.time() - t0):.1f}s)",
            flush=True,
        )
    ckpt.save_checkpoint(int(state["step"]), state, StorageType.DISK)
    ckpt.wait_for_persist(30)
    print(f"[worker] done at step {int(state['step'])}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
