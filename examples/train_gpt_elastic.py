"""End-to-end elastic training example.

Run under the elastic launcher (single host spawns a local master):

    python -m dlrover_tpu.agent.launcher --nnodes 1 -- \
        python examples/train_gpt_elastic.py --steps 50

Exercises: master rendezvous → jax.distributed bootstrap → device mesh →
dynamic data sharding from the master's TaskManager → jitted sharded train
step → flash checkpoint (memory stage + async disk persist) → resume after
restart.
"""

import argparse
import json
import sys

sys.path.insert(0, ".")  # repo-root run: `python examples/...`
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.agent.sharding_client import ShardingClient
from dlrover_tpu.checkpoint import Checkpointer, StorageType
from dlrover_tpu.checkpoint.checkpointer import state_template
from dlrover_tpu.elastic import (
    ElasticTrainer,
    LiveResharder,
    PhaseBudgets,
    get_injector,
    reshard_train_state,
)
from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
    state_shardings,
)
from dlrover_tpu.train.data_utils import form_global_batch, iter_shards_spmd
from dlrover_tpu.train.distributed import init_distributed


def synthetic_batch(start: int, end: int, batch: int, seq: int, vocab: int):
    rng = np.random.RandomState(start)
    n = batch * (seq + 1)
    data = rng.randint(0, vocab, size=n).reshape(batch, seq + 1)
    return {
        "tokens": jnp.asarray(data[:, :-1], jnp.int32),
        "targets": jnp.asarray(data[:, 1:], jnp.int32),
    }


def _live_reshard(args, client, ckpt, cfg, opt, comm, ctx, trainer, state):
    """Graceful host eviction: survivors keep their in-HBM state, the
    master issues a reshard directive, and training resumes at the new
    dp size without a restart or a disk restore. Every phase runs under
    a deadline budget; any failure degrades to the checkpoint ladder."""
    old_mesh = ctx["mesh"]
    old_dp = old_mesh.shape["dp"]
    lost = sorted(
        int(r) for r in args.evict_dp_ranks.split(",") if r.strip()
    )
    if not lost:
        lost = list(range(old_dp // 2, old_dp))
    old_plan = ctx["builder"]._plan
    old_shardings = jax.tree.map(lambda x: x.sharding, state)

    client.report_eviction(lost, dp_size=old_dp, reason="drill eviction")

    def detect(_):
        deadline = time.time() + 15.0
        while time.time() < deadline:
            directive = client.get_reshard_plan()
            if directive.version > 0:
                return directive
            time.sleep(0.05)
        raise RuntimeError("reshard directive never arrived")

    def replan(directive):
        lost_set = set(directive.lost_ranks if directive else lost)
        survivors = [
            d
            for i, d in enumerate(old_mesh.devices.flat)
            if i not in lost_set
        ]
        new_mesh = build_mesh(MeshConfig(dp=-1), devices=survivors)
        nb = TrainStepBuilder(cfg, new_mesh, opt, comm=comm)
        assert nb.update_sharding, nb.update_sharding_reason
        return {
            "mesh": new_mesh,
            "plan": nb._plan,
            "shardings": state_shardings(cfg, new_mesh, opt, comm=comm),
        }

    def migrate(rp):
        rp["state"] = reshard_train_state(
            state, old_plan, rp["plan"], rp["shardings"],
            faults=get_injector(),
        )
        return rp

    def rebuild(rp):
        ctx["mesh"] = rp["mesh"]
        trainer.on_membership_change()
        return rp

    def first_step(rp):
        batch = form_global_batch(
            synthetic_batch(
                int(rp["state"]["step"]) * args.batch,
                0,
                args.batch,
                args.seq,
                cfg.vocab_size,
            ),
            batch_sharding(rp["mesh"]),
        )
        rp["state"], metrics = trainer.step(rp["state"], batch)
        print(
            f"[reshard] first step loss={float(metrics['loss']):.4f}",
            flush=True,
        )
        return rp

    def fallback(exc):
        # tier ladder: restore at the OLD geometry from the checkpoint
        # stack, then repack to the survivor layout (no HBM donors
        # involved, so a dead donor cannot poison this path)
        print(
            f"[reshard] live path failed ({exc!r}); "
            "falling back to checkpoint ladder",
            flush=True,
        )
        restored = ckpt.load_checkpoint(
            state_template(state), shardings=old_shardings
        )
        if restored is None:
            raise RuntimeError("no checkpoint tier answered")
        rp = replan(None)
        rp["state"] = reshard_train_state(
            restored, old_plan, rp["plan"], rp["shardings"]
        )
        return first_step(rebuild(rp))

    out = LiveResharder(budgets=PhaseBudgets()).execute(
        [
            ("detect", detect),
            ("replan", replan),
            ("migrate", migrate),
            ("rebuild", rebuild),
            ("first_step", first_step),
        ],
        fallback=fallback,
    )
    print(
        "[reshard] done "
        + json.dumps(
            {
                "path": out.path,
                "recovery_s": round(out.recovery_s, 3),
                "dp": f"{old_dp}->{ctx['mesh'].shape['dp']}",
                "phases": {
                    k: round(v, 3) for k, v in out.phase_seconds.items()
                },
            }
        ),
        flush=True,
    )
    return out.result["state"]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--model", default="tiny")
    p.add_argument("--ckpt-dir", default="/tmp/dlrover_tpu_example_ckpt")
    p.add_argument("--ckpt-every", type=int, default=5)
    p.add_argument("--crash-at", type=int, default=-1,
                   help="deliberately crash at this step (failover demo)")
    p.add_argument(
        "--hosts-per-slice", type=int, default=0,
        help="build a hybrid multi-slice mesh: every hosts-per-slice "
        "processes form one emulated ICI slice, dp rides DCN across "
        "slices (num_slices = process_count // hosts_per_slice)",
    )
    p.add_argument(
        "--zero1", action="store_true",
        help="ZeRO-1 update sharding (bucketed flat optimizer state); "
        "required for --evict-at",
    )
    p.add_argument(
        "--evict-at", type=int, default=-1,
        help="at this step, simulate a graceful host eviction and "
        "live-reshard onto the survivors (no restart, no disk restore)",
    )
    p.add_argument(
        "--evict-dp-ranks", default="",
        help="comma-separated dp ranks lost at --evict-at "
        "(default: the top half of the mesh)",
    )
    args = p.parse_args()

    init_distributed()
    client = build_master_client()
    if args.hosts_per_slice > 0:
        # slice-grain elasticity: the mesh is rebuilt from the CURRENT
        # world every (re)start, so a world that shrank by a whole slice
        # re-meshes to fewer slices (dp shrinks, fsdp stays intra-slice)
        num_slices = max(1, jax.process_count() // args.hosts_per_slice)
        mesh = build_mesh(
            MeshConfig(dp=num_slices, fsdp=-1, num_slices=num_slices)
        )
        print(
            f"[worker] slice mesh: num_slices={num_slices} "
            f"dp={mesh.shape['dp']} fsdp={mesh.shape['fsdp']}",
            flush=True,
        )
    else:
        mesh = build_mesh(MeshConfig(dp=-1))
    cfg = get_config(args.model, max_seq=args.seq)
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=5, decay_steps=1000)

    # --zero1 routes stepping through ElasticTrainer so a live reshard
    # can rebuild the jitted step for the new (replicas, grad_accum)
    comm = (
        shd.CommConfig(update_sharding=True, bucket_mb=0.05)
        if args.zero1
        else None
    )
    ctx = {"mesh": mesh, "builder": None}

    def build_step(accum):
        b = TrainStepBuilder(
            cfg, ctx["mesh"], opt, grad_accum=accum, comm=comm
        )
        ctx["builder"] = b
        return b.build()

    trainer = None
    if args.zero1:
        micro = max(1, args.batch // mesh.shape["dp"])
        trainer = ElasticTrainer(
            args.batch,
            micro,
            build_step,
            data_replicas_fn=lambda: ctx["mesh"].shape["dp"],
        )
        run_step = trainer.step
        state = init_train_state(
            jax.random.key(0), cfg, mesh, opt,
            comm=ctx["builder"].comm_resolved,
        )
    else:
        run_step = build_step(1)
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    ckpt = Checkpointer(args.ckpt_dir, master_client=client)
    restored = ckpt.load_checkpoint(
        state_template(state),
        shardings=jax.tree.map(lambda x: x.sharding, state),
    )
    if restored is not None:
        state = restored
        print(f"[worker] resumed from step {int(state['step'])}", flush=True)
    # SPMD: one shard = one GLOBAL step (batch rows × processes); rank 0
    # fetches from the master and broadcasts so all processes stay in
    # lockstep; each process slices its own rows out of the shard.
    nproc = jax.process_count()
    sharding = ShardingClient(
        client,
        "train",
        dataset_size=args.steps * args.batch * nproc,
        shard_size=args.batch * nproc,
    )

    bsh = batch_sharding(mesh)
    t0 = time.time()
    evicted = False
    for start, end in iter_shards_spmd(sharding):
        local_start = start + jax.process_index() * args.batch
        step = int(state["step"])
        if (
            args.crash_at >= 0
            and step >= args.crash_at
            and int(os.environ.get("DLROVER_TPU_RESTART_COUNT", "0")) == 0
        ):
            print(f"[worker] simulating crash at step {step}", flush=True)
            os._exit(17)
        if (
            args.evict_at >= 0
            and trainer is not None
            and not evicted
            and step >= args.evict_at
        ):
            state = _live_reshard(
                args, client, ckpt, cfg, opt, comm, ctx, trainer, state
            )
            evicted = True
            bsh = batch_sharding(ctx["mesh"])
        batch = form_global_batch(
            synthetic_batch(
                local_start,
                local_start + args.batch,
                args.batch,
                args.seq,
                cfg.vocab_size,
            ),
            bsh,
        )
        state, metrics = run_step(state, batch)
        step = int(state["step"])
        client.report_global_step(step)
        if step % args.ckpt_every == 0:
            kind = (
                StorageType.DISK
                if step % (2 * args.ckpt_every) == 0
                else StorageType.MEMORY
            )
            ckpt.save_checkpoint(step, state, kind)
        print(
            f"[worker] step={step} loss={float(metrics['loss']):.4f} "
            f"({(time.time() - t0):.1f}s)",
            flush=True,
        )
    ckpt.save_checkpoint(int(state["step"]), state, StorageType.DISK)
    ckpt.wait_for_persist(30)
    print(f"[worker] done at step {int(state['step'])}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
