"""RLHF example: teach a tiny decoder to emit a target token.

``--algo ppo`` (default) runs the 4-role PPO path; ``--algo grpo`` runs
the critic-free group-relative path (rl/grpo.py — exceeds the
reference, whose RL stack is PPO-only).

The programmatic reward stands in for a learned reward model; swap in
``ModelEngine(init_reward=True)`` + no ``reward_fn`` for the learned path.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_rlhf.py --rounds 6
"""

import argparse
import sys

sys.path.insert(0, ".")  # repo-root run: `python examples/...`

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import get_config
from dlrover_tpu.rl import (
    GRPOConfig,
    GRPOTrainer,
    ModelEngine,
    PPOConfig,
    RLTrainer,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=6)
    p.add_argument("--target-token", type=int, default=7)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--algo", choices=["ppo", "grpo"], default="ppo")
    args = p.parse_args()

    cfg = get_config(
        "tiny", n_layer=1, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=32,
    )
    engine = ModelEngine(cfg, learning_rate=1e-2, init_reward=False)

    def reward_fn(tokens, mask):
        hit = (tokens[:, 1:] == args.target_token) * mask
        return hit.sum(-1) / np.maximum(mask.sum(-1), 1.0)

    if args.algo == "grpo":
        trainer = GRPOTrainer(
            engine,
            GRPOConfig(group_size=4, max_new_tokens=8, epochs=2,
                       kl_coef=0.01),
            reward_fn=reward_fn,
        )
    else:
        trainer = RLTrainer(
            engine,
            PPOConfig(max_new_tokens=8, ppo_epochs=2, kl_coef=0.01),
            reward_fn=reward_fn,
        )
    prompts = jnp.ones((args.batch, 2), jnp.int32)
    for i in range(args.rounds):
        stats = trainer.step(prompts, jax.random.key(i))
        print(
            f"[rlhf:{args.algo}] round {i}: score={stats['score_mean']:.3f} "
            f"kl={stats.get('approx_kl', 0):.4f} "
            f"clip={stats.get('clip_frac', 0):.3f}"
        )
    print("[rlhf] done")


if __name__ == "__main__":
    main()
