"""Train DeepFM on synthetic criteo-shaped CTR data.

TPU-native analog of the reference's criteo deepfm system test
(.github/actions/dlrover-system-test-deepfm): unbounded-vocabulary sparse
embeddings live in the C++ KvTable store; FM + MLP compute is jitted.

Run:  python examples/train_deepfm.py [--steps 200] [--ckpt DIR]
"""

import argparse
import sys

sys.path.insert(0, ".")  # repo-root run: `python examples/...`
import time

import numpy as np

from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
from dlrover_tpu.sparse import GroupAdam


def batches(rng, cfg, batch_size):
    while True:
        cat = rng.integers(0, 200_000, size=(batch_size, cfg.n_fields))
        dense = rng.normal(size=(batch_size, cfg.n_dense)).astype(np.float32)
        hot = (cat % 7 == 0).sum(axis=1) + dense[:, 0]
        p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
        labels = (rng.random(batch_size) < p).astype(np.float32)
        yield cat.astype(np.int64), dense, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=4096)
    ap.add_argument("--ckpt", type=str, default="")
    args = ap.parse_args()

    cfg = DeepFMConfig()
    model = DeepFM(cfg, optimizer=GroupAdam(lr=1e-3, l21=1e-6))
    rng = np.random.default_rng(0)
    data = batches(rng, cfg, args.batch_size)

    t0 = time.time()
    for step in range(1, args.steps + 1):
        cat, dense, labels = next(data)
        loss = model.train_step(cat, dense, labels)
        if step % 20 == 0:
            rate = step * args.batch_size / (time.time() - t0)
            print(
                f"step {step:5d}  loss {loss:.4f}  "
                f"{rate:,.0f} ex/s  vocab {len(model.coll.tables['emb']):,}"
            )
            if args.ckpt:
                model.save(args.ckpt, delta_only=step > 20)

    model.close()


if __name__ == "__main__":
    main()
