"""CLIP contrastive pretraining on a sharded mesh (synthetic data).

Run (8-device virtual CPU mesh):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_clip.py --steps 10

Demonstrates the vision family (models/vision.py): ViT image tower +
causal text tower, symmetric InfoNCE over the GLOBAL batch — under pjit
the [B,B] similarity matrix spans every device's samples, so SPMD
provides the global negatives the reference's torch towers need explicit
all_gathers for (SURVEY §2.3, atorch TP CLIP blocks).
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models.vision import (
    clip_tiny_test,
    clip_logical_axes,
    clip_loss,
    init_clip,
)
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel import sharding as shd


def synthetic_batch(rng, b=32):
    """Correlated (image, caption) pairs from 16 latent classes."""
    cls = rng.integers(0, 16, size=b)
    shades = np.random.default_rng(7).normal(size=(16, 3))
    imgs = np.broadcast_to(
        shades[cls][:, None, None, :], (b, 32, 32, 3)
    ).astype(np.float32)
    imgs = imgs + rng.normal(scale=0.05, size=imgs.shape)
    tokens = np.broadcast_to((cls + 1)[:, None], (b, 8)).astype(np.int32)
    return {
        "images": jnp.asarray(imgs, jnp.float32),
        "tokens": jnp.asarray(tokens),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--lr", type=float, default=3e-3)
    args = p.parse_args()

    n_dev = jax.device_count()
    mesh = build_mesh(MeshConfig(dp=n_dev))
    cfg = clip_tiny_test()
    params = jax.device_put(
        init_clip(jax.random.key(0), cfg),
        shd.shardings_for_tree(mesh, clip_logical_axes(cfg)),
    )
    opt = optax.adam(args.lr)
    opt_state = opt.init(params)
    bsh = shd.shardings_for_tree(
        mesh,
        {"images": ("batch", None, None, None), "tokens": ("batch", None)},
    )

    @jax.jit
    def step(params, opt_state, batch):
        (_, metrics), grads = jax.value_and_grad(
            clip_loss, has_aux=True
        )(params, batch, cfg, mesh=mesh)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, metrics

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    for i in range(1, args.steps + 1):
        batch = jax.device_put(synthetic_batch(rng, args.batch), bsh)
        params, opt_state, m = step(params, opt_state, batch)
        print(
            f"[clip] step={i} loss={float(m['loss']):.4f} "
            f"acc={float(m['accuracy']):.3f} "
            f"scale={float(m['logit_scale']):.2f}"
        )
    dt = time.perf_counter() - t0
    print(f"[clip] done at step {args.steps} ({dt:.1f}s, dp={n_dev})")


if __name__ == "__main__":
    main()
