"""Mixture-of-Experts training example.

Runs a switch-gated MoE decoder with expert parallelism over the ``ep``
mesh axis, router load-balancing + z-losses, and the high-level Trainer.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_moe.py --steps 20
"""

import argparse
import sys

sys.path.insert(0, ".")  # repo-root run: `python examples/...`

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import Trainer, TrainerArgs, make_optimizer


def data_iter(batch, seq, vocab, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        b = rng.randint(0, vocab // 4, size=(batch, seq + 1))
        yield {
            "tokens": jnp.asarray(b[:, :-1], jnp.int32),
            "targets": jnp.asarray(b[:, 1:], jnp.int32),
        }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--experts", type=int, default=4)
    p.add_argument("--gating", choices=["topk", "switch"], default="switch")
    p.add_argument("--alltoall", action="store_true",
                   help="explicit shard_map all-to-all EP dispatch")
    p.add_argument("--output", default="/tmp/dlrover_tpu_moe")
    args = p.parse_args()

    n_dev = jax.device_count()
    # ep must divide both the device count and the expert count
    ep = max(
        d
        for d in range(1, n_dev + 1)
        if n_dev % d == 0 and args.experts % d == 0
    )
    mesh = build_mesh(MeshConfig(dp=n_dev // ep, ep=ep))
    cfg = get_config(
        "tiny-moe",
        n_layer=2,
        d_model=128,
        d_ff=256,
        n_head=4,
        max_seq=args.seq,
        n_experts=args.experts,
        moe_gating=args.gating,
        moe_jitter=0.01 if args.gating == "switch" else 0.0,
        moe_aux_coef=0.01,
        moe_z_coef=0.001,
        moe_alltoall=args.alltoall,
    )
    trainer = Trainer(
        cfg,
        TrainerArgs(
            output_dir=args.output,
            max_steps=args.steps,
            log_interval=5,
            save_interval=args.steps,
            report_to_master=False,
            resume=False,  # demo always trains from scratch
        ),
        data_iter(args.batch, args.seq, cfg.vocab_size),
        make_optimizer(learning_rate=1e-3, warmup_steps=5, decay_steps=1000),
        mesh=mesh,
    )
    state = trainer.train()
    print(f"[moe] done at step {int(state['step'])}")


if __name__ == "__main__":
    main()
