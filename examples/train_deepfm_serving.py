"""DeepFM over the MULTI-HOST sparse serving ring, with a live rebalance.

    python examples/train_deepfm_serving.py --steps 40

Exercises: two KvServer processes serving the embedding tier over TCP →
DistributedEmbedding HRW routing (pull → jitted step → push) → a
mid-run scale-out to a third server with bounded key migration
(values + optimizer slots + admission state) → continued convergence.
This is the elastic-PS capability of the reference's TF PS jobs
(tensorflow_failover.py) on the TPU-native sparse tier.
"""

import argparse
import multiprocessing as mp
import sys
import threading

import numpy as np

sys.path.insert(0, ".")  # repo-root run: `python examples/...`


def _server_main(port_q, emb_dim, lr):
    from dlrover_tpu.sparse import GroupAdam
    from dlrover_tpu.sparse.embedding import EmbeddingSpec
    from dlrover_tpu.sparse.server import KvServer

    server = KvServer(
        [
            EmbeddingSpec("emb", emb_dim, initializer="normal",
                          init_scale=0.01, seed=3),
            EmbeddingSpec("wide", 1, initializer="zeros"),
        ],
        optimizer=GroupAdam(lr=lr),
    )
    port_q.put(server.address[1])
    threading.Event().wait()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=512)
    args = ap.parse_args()
    if args.steps < 2:
        ap.error("--steps must be >= 2 (train halves flank the rebalance)")

    from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
    from dlrover_tpu.sparse import GroupAdam
    from dlrover_tpu.sparse.embedding import EmbeddingSpec
    from dlrover_tpu.sparse.server import DistributedEmbedding

    cfg = DeepFMConfig(n_fields=6, n_dense=4, emb_dim=8, mlp_dims=(32,))
    ctx = mp.get_context("spawn")

    def spawn(name):
        q = ctx.Queue()
        p = ctx.Process(
            target=_server_main, args=(q, cfg.emb_dim, 5e-3), daemon=True
        )
        p.start()
        return p, ("127.0.0.1", q.get(timeout=60))

    procs, addrs = [], {}
    for name in ("s0", "s1"):
        p, addr = spawn(name)
        procs.append(p)
        addrs[name] = addr
    print(f"[deepfm-serving] 2 sparse servers up: {addrs}")

    specs = [
        EmbeddingSpec("emb", cfg.emb_dim, initializer="normal",
                      init_scale=0.01, seed=3),
        EmbeddingSpec("wide", 1, initializer="zeros"),
    ]
    model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
    model.coll.close()
    demb = DistributedEmbedding(specs, addrs)
    model.coll = demb

    rng = np.random.default_rng(0)
    cat = rng.integers(0, 50, size=(args.batch, cfg.n_fields)).astype(
        np.int64
    )
    dense = rng.normal(size=(args.batch, cfg.n_dense)).astype(np.float32)
    hot = (cat % 7 == 0).sum(axis=1) + dense[:, 0]
    labels = (
        rng.random(args.batch) < 1.0 / (1.0 + np.exp(-(hot - 2.0)))
    ).astype(np.float32)

    half = args.steps // 2
    first = None
    for step in range(1, half + 1):
        loss = model.train_step(cat, dense, labels)
        first = first if first is not None else loss
        if step % 10 == 0 or step == 1:
            print(f"[deepfm-serving] step {step} loss {loss:.4f}")

    p2, addr2 = spawn("s2")
    procs.append(p2)
    moved = demb.set_servers(dict(addrs, s2=addr2))
    stats = demb.stats()
    total = sum(s["emb"] for s in stats.values())
    print(
        f"[deepfm-serving] scaled 2->3 servers: {moved} keys migrated, "
        f"{total} emb rows now on "
        f"{ {s: c['emb'] for s, c in stats.items()} }"
    )

    for step in range(half + 1, args.steps + 1):
        loss = model.train_step(cat, dense, labels)
        if step % 10 == 0 or step == args.steps:
            print(f"[deepfm-serving] step {step} loss {loss:.4f}")

    ok = loss < first * 0.9
    print(
        f"[deepfm-serving] done: loss {first:.4f} -> {loss:.4f} "
        f"({'converging' if ok else 'NOT CONVERGING'})"
    )
    demb.close()
    for p in procs:
        p.terminate()
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
