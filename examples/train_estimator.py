"""Estimator-style training over the sparse serving ring.

    python examples/train_estimator.py --steps 40

The reference's TF estimator path (estimator_executor.py) on the
TPU-native tier: a schema'd FileReader feeds a DeepFM whose embeddings
live on two KvServer processes; train_and_evaluate checkpoints on a
cadence (keep-max pruning), exports the best eval snapshot, and a
second run resumes from the latest checkpoint — including the sparse
ring, restored via the ring-wide snapshot (DistributedEmbedding
save/restore).
"""

import argparse
import multiprocessing as mp
import os
import shutil
import sys
import threading

import numpy as np

sys.path.insert(0, ".")  # repo-root run: `python examples/...`


def _server_main(port_q, emb_dim, lr):
    from dlrover_tpu.sparse import GroupAdam
    from dlrover_tpu.sparse.embedding import EmbeddingSpec
    from dlrover_tpu.sparse.server import KvServer

    server = KvServer(
        [
            EmbeddingSpec("emb", emb_dim, initializer="normal",
                          init_scale=0.01, seed=3),
            EmbeddingSpec("wide", 1, initializer="zeros"),
        ],
        optimizer=GroupAdam(lr=lr),
    )
    port_q.put(server.address[1])
    threading.Event().wait()


def write_csv(path, n, n_fields, n_dense, seed=11):
    rng = np.random.default_rng(seed)
    with open(path, "w", encoding="utf-8") as f:
        for _ in range(n):
            cat = rng.integers(0, 50, n_fields)
            dense = rng.normal(size=n_dense)
            hot = (cat % 7 == 0).sum() + dense[0]
            p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
            label = int(rng.random() < p)
            f.write(
                ",".join(str(c) for c in cat)
                + ","
                + ",".join(f"{d:.5f}" for d in dense)
                + f",{label}\n"
            )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--model-dir", default="/tmp/dlrover_tpu_estimator_ex")
    args = ap.parse_args()

    from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
    from dlrover_tpu.sparse import GroupAdam
    from dlrover_tpu.sparse.embedding import EmbeddingSpec
    from dlrover_tpu.sparse.server import DistributedEmbedding
    from dlrover_tpu.train.estimator import (
        ColumnInfo,
        Estimator,
        EvalSpec,
        FileReader,
        RunConfig,
        TrainSpec,
        train_and_evaluate,
    )

    cfg = DeepFMConfig(n_fields=6, n_dense=4, emb_dim=8, mlp_dims=(32,))
    shutil.rmtree(args.model_dir, ignore_errors=True)
    os.makedirs(args.model_dir, exist_ok=True)
    csv_path = os.path.join(args.model_dir, "train.csv")
    write_csv(csv_path, 20_000, cfg.n_fields, cfg.n_dense)

    ctx = mp.get_context("spawn")
    procs, addrs = [], {}
    for name in ("s0", "s1"):
        q = ctx.Queue()
        p = ctx.Process(
            target=_server_main, args=(q, cfg.emb_dim, 5e-3), daemon=True
        )
        p.start()
        procs.append(p)
        addrs[name] = ("127.0.0.1", q.get(timeout=60))
    print(f"[estimator] 2 sparse servers up: {addrs}")

    columns = (
        [ColumnInfo(f"c{i}", "int64") for i in range(cfg.n_fields)]
        + [ColumnInfo(f"d{i}", "float32") for i in range(cfg.n_dense)]
        + [ColumnInfo("label", "float32", is_label=True)]
    )

    def specs():
        return [
            EmbeddingSpec("emb", cfg.emb_dim, initializer="normal",
                          init_scale=0.01, seed=3),
            EmbeddingSpec("wide", 1, initializer="zeros"),
        ]

    class Adapter:
        def __init__(self, model):
            self.model = model
            self.coll = model.coll

        def _unpack(self, features):
            cat = np.stack(
                [features[f"c{i}"] for i in range(cfg.n_fields)], axis=1
            )
            dense = np.stack(
                [features[f"d{i}"] for i in range(cfg.n_dense)], axis=1
            )
            return cat, dense

        def train_step(self, features, labels):
            cat, dense = self._unpack(features)
            return self.model.train_step(cat, dense, labels)

        def eval_metrics(self, features, labels):
            cat, dense = self._unpack(features)
            p = self.model.predict(cat, dense)
            eps = 1e-6
            loss = -np.mean(labels * np.log(p + eps)
                            + (1 - labels) * np.log(1 - p + eps))
            return {"loss": float(loss),
                    "accuracy": float(np.mean((p > 0.5) == (labels > 0.5)))}

        def save(self, d, delta_only=False):
            self.model.save(d, delta_only=delta_only)

        def restore(self, d):
            self.model.restore(d)

    def model_fn(mode, params, cluster):
        model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
        model.coll.close()
        model.coll = DistributedEmbedding(specs(), addrs)
        return Adapter(model)

    def input_fn():
        return iter(
            FileReader(csv_path, columns, batch_size=args.batch,
                       shuffle=True, seed=0)
        )

    run_cfg = RunConfig(
        model_dir=args.model_dir, save_steps=10,
        keep_checkpoint_max=2, log_steps=10,
    )
    est = Estimator(model_fn, config=run_cfg)
    metrics = train_and_evaluate(
        est,
        TrainSpec(input_fn, max_steps=args.steps),
        EvalSpec(input_fn, steps=8, every_steps=max(args.steps // 2, 1)),
    )
    print(f"[estimator] trained to step {est.global_step}: {metrics}")
    assert os.path.exists(
        os.path.join(args.model_dir, "export", "best", "metadata.json")
    ), "best export missing"

    # resume: a fresh Estimator (fresh DeepFM + ring restore) picks up
    # where the first stopped
    est2 = Estimator(model_fn, config=run_cfg)
    resumed = est2.restore_latest()
    assert resumed == est.global_step, (resumed, est.global_step)
    est2.global_step = resumed
    m2 = est2.evaluate(input_fn, steps=8)
    print(f"[estimator] resumed at step {resumed}: eval {m2}")
    assert abs(m2["loss"] - metrics["loss"]) < 0.05, (
        "restored eval diverges from pre-restart eval"
    )
    print("[estimator] done")


if __name__ == "__main__":
    main()
