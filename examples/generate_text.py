"""Sample continuations from a flash-checkpoint-trained model.

Completes the user loop the other examples start: train (any of the
training examples with --ckpt-dir) -> restore the latest committed
checkpoint -> KV-cache sampling (prefill + incremental decode). With no
checkpoint it samples from a fresh init, exercising the same path.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/generate_text.py --prompt-len 8 --new-tokens 24
"""

import argparse
import sys

sys.path.insert(0, ".")  # repo-root run: `python examples/...`

import jax
import jax.numpy as jnp


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="tiny")
    p.add_argument("--ckpt-dir", default="/tmp/dlrover_tpu_example_ckpt")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--new-tokens", type=int, default=24)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--no-cache", action="store_true",
                   help="full-prefix sampling instead of KV cache")
    args = p.parse_args()

    from dlrover_tpu.checkpoint import Checkpointer
    from dlrover_tpu.checkpoint.checkpointer import state_template
    from dlrover_tpu.models import generate, get_config
    from dlrover_tpu.parallel import MeshConfig, build_mesh
    from dlrover_tpu.train import init_train_state, make_optimizer

    cfg = get_config(args.model)
    mesh = build_mesh(MeshConfig(dp=-1))
    opt = make_optimizer(learning_rate=1e-3)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)

    ckpt = Checkpointer(args.ckpt_dir, use_agent=False)
    restored = ckpt.load_checkpoint(
        state_template(state),
        shardings=jax.tree.map(lambda x: x.sharding, state),
    )
    if restored is not None:
        state = restored
        print(f"[generate] restored step {int(state['step'])}")
    else:
        print("[generate] no checkpoint found; sampling from init")

    prompts = jax.random.randint(
        jax.random.key(1),
        (args.batch, args.prompt_len),
        0,
        cfg.vocab_size,
    )
    out = generate.sample(
        state["params"],
        cfg,
        prompts,
        max_new_tokens=args.new_tokens,
        rng=jax.random.key(2),
        temperature=args.temperature,
        mesh=mesh,
        use_cache=not args.no_cache,
    )
    assert out.shape == (
        args.batch, args.prompt_len + args.new_tokens
    )
    for i in range(args.batch):
        toks = [int(t) for t in out[i]]
        print(f"[generate] seq{i}: {toks[:args.prompt_len]} -> "
              f"{toks[args.prompt_len:]}")
    print("[generate] done", flush=True)


if __name__ == "__main__":
    main()
