"""Estimator worker for the elastic PS job (the reference's §3.5 call
stack: dlrover.trainer entry → EstimatorExecutor → TF_CONFIG from the
master → TensorflowFailover → ElasticDataShardReportHook → dynamic
shards from the TaskManager).

Run under a live master (env ``DLROVER_TPU_MASTER_ADDR``) with KvServer
processes registered as PS nodes:

- synthesizes its ClusterSpec from the master (waits for the PS ring),
- registers a dataset and reads it through a shard-fed FileReader
  (per-batch completion closes shards; a dead worker's shards re-queue),
- trains with periodic + incremental checkpoints,
- rides through PS failures: a wire error waits for the master to
  re-seal the ring, then restores the sparse tier from the latest
  checkpoint and keeps stepping (`tests/test_estimator_fullstack.py`
  kills a PS mid-run and asserts exactly this).
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def write_csv(path, n, n_fields, n_dense, seed=11):
    rng = np.random.default_rng(seed)
    with open(path, "w", encoding="utf-8") as f:
        for _ in range(n):
            cat = rng.integers(0, 50, n_fields)
            dense = rng.normal(size=n_dense)
            hot = (cat % 7 == 0).sum() + dense[0]
            p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
            label = int(rng.random() < p)
            f.write(
                ",".join(str(c) for c in cat)
                + ","
                + ",".join(f"{d:.5f}" for d in dense)
                + f",{label}\n"
            )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--model-dir", default="/tmp/dlrover_tpu_est_elastic")
    ap.add_argument("--ps-wait-s", type=float, default=60.0)
    args = ap.parse_args()

    from dlrover_tpu.agent.master_client import build_master_client
    from dlrover_tpu.agent.sharding_client import ShardingClient
    from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
    from dlrover_tpu.sparse import GroupAdam
    from dlrover_tpu.sparse.embedding import EmbeddingSpec
    from dlrover_tpu.sparse.server import DistributedEmbedding, resolve_ring
    from dlrover_tpu.train.estimator import (
        ColumnInfo,
        Estimator,
        FileReader,
        RunConfig,
        synthesize_cluster_spec,
    )

    client = build_master_client()
    if "DLROVER_TPU_RDZV_ROUND" not in os.environ:
        # standalone run: register ourselves; under the elastic agent
        # (which sets the rendezvous env) the node is already registered
        client.register_node()
    # the worker-kill drill (test_estimator_fullstack) targets this pid
    print(f"[est-worker] pid {os.getpid()}", flush=True)

    # wait for the PS ring: names from ElasticPsService, addresses from
    # the KV store (the reference's wait_for_tf_config analog)
    deadline = time.monotonic() + args.ps_wait_s
    addrs = None
    while time.monotonic() < deadline:
        spec = synthesize_cluster_spec(client)
        if spec.cluster.get("ps"):
            addrs = resolve_ring(client, spec.cluster["ps"])
            if addrs is not None:
                break
        time.sleep(1.0)
    if addrs is None:
        print("[est-worker] no PS ring appeared", flush=True)
        sys.exit(1)
    print(f"[est-worker] cluster: {spec.to_json()}", flush=True)

    cfg = DeepFMConfig(n_fields=6, n_dense=4, emb_dim=8, mlp_dims=(32,))
    os.makedirs(args.model_dir, exist_ok=True)
    csv_path = os.path.join(args.model_dir, "train.csv")
    if not os.path.exists(csv_path):
        write_csv(csv_path, args.rows, cfg.n_fields, cfg.n_dense)

    shard_client = ShardingClient(
        client, "est-ctr", dataset_size=args.rows,
        shard_size=max(args.batch * 4, 512), num_epochs=100,
    )
    columns = (
        [ColumnInfo(f"c{i}", "int64") for i in range(cfg.n_fields)]
        + [ColumnInfo(f"d{i}", "float32") for i in range(cfg.n_dense)]
        + [ColumnInfo("label", "float32", is_label=True)]
    )
    reader = FileReader(
        csv_path, columns, batch_size=args.batch,
        shard_client=shard_client, auto_report=True,
    )

    def specs():
        return [
            EmbeddingSpec("emb", cfg.emb_dim, initializer="normal",
                          init_scale=0.01, seed=3),
            EmbeddingSpec("wide", 1, initializer="zeros"),
        ]

    class Adapter:
        def __init__(self, model):
            self.model = model
            self.coll = model.coll

        def _unpack(self, features):
            cat = np.stack(
                [features[f"c{i}"] for i in range(cfg.n_fields)], axis=1
            )
            dense = np.stack(
                [features[f"d{i}"] for i in range(cfg.n_dense)], axis=1
            )
            return cat, dense

        def train_step(self, features, labels):
            cat, dense = self._unpack(features)
            return self.model.train_step(cat, dense, labels)

        def eval_metrics(self, features, labels):
            cat, dense = self._unpack(features)
            p = self.model.predict(cat, dense)
            eps = 1e-6
            return {"loss": float(-np.mean(
                labels * np.log(p + eps)
                + (1 - labels) * np.log(1 - p + eps)
            ))}

        def save(self, d, delta_only=False):
            self.model.save(d, delta_only=delta_only)

        def restore(self, d):
            self.model.restore(d)

    def model_fn(mode, params, cluster):
        model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
        model.coll.close()
        model.coll = DistributedEmbedding(specs(), addrs)
        return Adapter(model)

    est = Estimator(
        model_fn,
        config=RunConfig(
            model_dir=args.model_dir, save_steps=10,
            incremental_save_steps=5, keep_checkpoint_max=2,
            log_steps=5, ps_failure_grace_s=45.0,
        ),
        cluster=spec,
        master_client=client,
        shard_client=shard_client,
        reader=reader,
    )
    est.model.coll.version = client.get_ps_version().version
    est.failover._poll = 1.0

    resumed = est.restore_latest()
    if resumed is not None:
        est.global_step = resumed
        print(f"[est-worker] resumed from step {resumed}", flush=True)

    class StepPrinter:
        def begin(self, estimator):
            pass

        def after_run(self, estimator, step, loss):
            print(f"[est-worker] step {step} loss {loss:.4f}", flush=True)
            if estimator.failover and estimator.failover.changes:
                changes = estimator.failover.changes
                estimator.failover.changes = []
                print(f"[est-worker] ps change {changes}", flush=True)

        def end(self, estimator, step):
            pass

    loss = est.train(
        lambda: iter(reader), max_steps=args.steps, hooks=[StepPrinter()]
    )
    from dlrover_tpu.common.constants import NodeStatus

    client.report_node_status(NodeStatus.SUCCEEDED)
    print(
        f"[est-worker] done at step {est.global_step} loss {loss:.4f}",
        flush=True,
    )


if __name__ == "__main__":
    main()
