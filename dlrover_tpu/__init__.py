"""dlrover_tpu: a TPU-native elastic-training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of DLRover
(elastic job master, master-driven rendezvous, dynamic data sharding,
node health/straggler checks, flash checkpoint) and its acceleration
stack (ATorch-style ``auto_accelerate``; DP/FSDP/TP/SP/EP/PP and
ring-attention context parallelism over ICI/DCN device meshes).

Layer map (bottom-up), mirroring the reference's structure
(see SURVEY.md §1; reference: dlrover/python, atorch/atorch):

- ``dlrover_tpu.common``    — node model, typed messages, config, logging
- ``dlrover_tpu.parallel``  — device meshes, sharding rules, SP/EP/PP
- ``dlrover_tpu.ops``       — Pallas TPU kernels (flash/ring attention, quant)
- ``dlrover_tpu.models``    — flagship model zoo (GPT/LLaMA-style decoders)
- ``dlrover_tpu.train``     — train-step builder, optimizers
- ``dlrover_tpu.accelerate``— strategy engine (auto_accelerate equivalent)
- ``dlrover_tpu.checkpoint``— flash checkpoint (HBM→host shm→storage)
- ``dlrover_tpu.elastic``   — elastic sampler/dataloader/trainer
- ``dlrover_tpu.master``    — per-job master: rendezvous, sharding, scaling
- ``dlrover_tpu.agent``     — per-host elastic agent + launcher
"""

__version__ = "0.1.0"
