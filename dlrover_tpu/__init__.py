"""dlrover_tpu: a TPU-native elastic-training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of DLRover
(elastic job master, master-driven rendezvous, dynamic data sharding,
node health/straggler checks, flash checkpoint) and its acceleration
stack (ATorch-style ``auto_accelerate``; DP/FSDP/TP/SP/EP/PP and
ring-attention context parallelism over ICI/DCN device meshes).

Layer map (bottom-up), mirroring the reference's structure
(see SURVEY.md §1; reference: dlrover/python, atorch/atorch):

- ``dlrover_tpu.common``    — node model, typed messages, config, logging
- ``dlrover_tpu.parallel``  — device meshes, sharding rules, SP/EP/PP
- ``dlrover_tpu.ops``       — Pallas TPU kernels (flash/ring attention, quant)
- ``dlrover_tpu.models``    — flagship model zoo (GPT/LLaMA-style decoders)
- ``dlrover_tpu.train``     — train-step builder, optimizers
- ``dlrover_tpu.accelerate``— strategy engine (auto_accelerate equivalent)
- ``dlrover_tpu.checkpoint``— flash checkpoint (HBM→host shm→storage)
- ``dlrover_tpu.elastic``   — elastic sampler/dataloader/trainer
- ``dlrover_tpu.master``    — per-job master: rendezvous, sharding, scaling
- ``dlrover_tpu.agent``     — per-host elastic agent + launcher
"""

__version__ = "0.1.0"

# Workers launched by the elastic agent get a SIGUSR2 py-stack dumper so
# the agent's StackCollector can diagnose hangs (env set by the agent;
# see agent/collectors.py StackCollector).
import os as _os

if _os.environ.get("DLROVER_TPU_STACK_DUMP") == "1":
    try:
        from dlrover_tpu.agent.collectors import StackCollector

        StackCollector.install_in_worker()
    except Exception:  # noqa: BLE001 — diagnosis must never break startup
        pass
del _os
