"""DPO: direct preference optimization — offline alignment, no rollouts.

EXCEEDS the reference (atorch/rl has no offline-preference path):
DPO (Rafailov et al. 2023) trains the policy directly on preference
pairs (chosen, rejected) with the reference policy as the implicit
reward normalizer — no reward model, no rollouts, no critic, no replay
buffer; each update is one ordinary supervised-style jitted step, so it
rides the same MXU-dense forward the trainers already use.

    loss = −log σ( β·[(logπ(c) − logπ_ref(c)) − (logπ(r) − logπ_ref(r))] )

summed token logprobs over each sequence's response span. The implicit
per-pair rewards β·(logπ − logπ_ref) are emitted as stats: their
margin and sign-accuracy are the standard DPO training diagnostics.
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def sequence_logprob(
    logits: jax.Array,  # [B, T, V] — positions predicting tokens[:,1:]
    tokens: jax.Array,  # [B, T]
    mask: jax.Array,    # [B, T-1] response mask over shifted positions
) -> jax.Array:
    """Sum of response-token logprobs per sequence → [B]."""
    from dlrover_tpu.rl import ppo

    lp = ppo.token_logprobs(logits[:, :-1], tokens[:, 1:])
    return (lp * mask).sum(axis=1)


def dpo_loss(
    policy_chosen: jax.Array,    # [B] seq logprobs under the policy
    policy_rejected: jax.Array,  # [B]
    ref_chosen: jax.Array,       # [B] under the frozen reference
    ref_rejected: jax.Array,     # [B]
    beta: float,
) -> Tuple[jax.Array, Dict]:
    chosen_reward = beta * (policy_chosen - ref_chosen)
    rejected_reward = beta * (policy_rejected - ref_rejected)
    margin = chosen_reward - rejected_reward
    loss = -jax.nn.log_sigmoid(margin).mean()
    stats = {
        "reward_margin": margin.mean(),
        "reward_accuracy": (margin > 0).mean(),
        "chosen_reward": chosen_reward.mean(),
        "rejected_reward": rejected_reward.mean(),
    }
    return loss, stats
