"""PPO math: GAE, clipped policy/value losses, KL-shaped rewards.

Reference: atorch/atorch/rl/trainer/ppo_utils.py-style loss computation
(clipped surrogate + clipped value loss + entropy bonus, trlX lineage) —
re-derived here as pure jnp functions usable inside one jitted step.
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def last_valid_index(mask: jax.Array) -> jax.Array:
    """Index of each row's last set position in ``mask`` [B, T] → [B].

    Positional (argmax of position-weighted mask), so prefix and suffix
    masks both work; all-zero rows map to 0.
    """
    t = mask.shape[1]
    return jnp.argmax(
        mask * jnp.arange(1, t + 1, dtype=mask.dtype), axis=1
    ).astype(jnp.int32)


def gae_advantages(
    rewards: jax.Array,   # [B, T]
    values: jax.Array,    # [B, T]
    mask: jax.Array,      # [B, T] 1.0 on response tokens
    gamma: float,
    lam: float,
) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation over the response span.

    Bootstrap value after the last valid token is 0 (episodic: the
    response ends the episode). Returns (advantages, returns), both
    zeroed outside ``mask``.
    """
    b, t = rewards.shape
    # next-step values, masked so the bootstrap past the end is 0
    next_values = jnp.concatenate(
        [values[:, 1:], jnp.zeros((b, 1), values.dtype)], axis=1
    )
    next_mask = jnp.concatenate(
        [mask[:, 1:], jnp.zeros((b, 1), mask.dtype)], axis=1
    )
    deltas = rewards + gamma * next_values * next_mask - values

    def scan_back(carry, xs):
        delta, m = xs
        adv = delta + gamma * lam * carry * m
        return adv, adv

    # scan over time reversed; carry is [B]
    _, adv_rev = jax.lax.scan(
        scan_back,
        jnp.zeros((b,), values.dtype),
        (deltas.T[::-1], next_mask.T[::-1]),
    )
    advantages = adv_rev[::-1].T * mask
    returns = advantages + values * mask
    return advantages, returns


def masked_whiten(x: jax.Array, mask: jax.Array, eps: float = 1e-8):
    n = jnp.maximum(mask.sum(), 1.0)
    mean = (x * mask).sum() / n
    var = ((x - mean) ** 2 * mask).sum() / n
    return (x - mean) * jax.lax.rsqrt(var + eps) * mask


def ppo_policy_loss(
    logprobs: jax.Array,      # [B, T] new policy logprobs of taken actions
    old_logprobs: jax.Array,  # [B, T] behavior policy logprobs
    advantages: jax.Array,    # [B, T]
    mask: jax.Array,          # [B, T]
    clip_ratio: float,
) -> Tuple[jax.Array, Dict]:
    ratio = jnp.exp(logprobs - old_logprobs)
    clipped = jnp.clip(ratio, 1.0 - clip_ratio, 1.0 + clip_ratio)
    surrogate = jnp.minimum(ratio * advantages, clipped * advantages)
    n = jnp.maximum(mask.sum(), 1.0)
    loss = -(surrogate * mask).sum() / n
    clip_frac = ((jnp.abs(ratio - 1.0) > clip_ratio) * mask).sum() / n
    approx_kl = ((old_logprobs - logprobs) * mask).sum() / n
    return loss, {"clip_frac": clip_frac, "approx_kl": approx_kl}


def ppo_value_loss(
    values: jax.Array,      # [B, T] new value predictions
    old_values: jax.Array,  # [B, T] behavior-time values
    returns: jax.Array,     # [B, T]
    mask: jax.Array,
    value_clip: float,
) -> jax.Array:
    """Clipped value loss (PPO2 style)."""
    clipped = old_values + jnp.clip(
        values - old_values, -value_clip, value_clip
    )
    l1 = (values - returns) ** 2
    l2 = (clipped - returns) ** 2
    n = jnp.maximum(mask.sum(), 1.0)
    return 0.5 * (jnp.maximum(l1, l2) * mask).sum() / n


def shaped_rewards(
    score: jax.Array,        # [B] sequence-level reward-model score
    logprobs: jax.Array,     # [B, T] actor logprobs at rollout time
    ref_logprobs: jax.Array, # [B, T] frozen reference logprobs
    mask: jax.Array,         # [B, T]
    kl_coef: float,
) -> jax.Array:
    """Per-token rewards: −β·KL everywhere + score on the last token.

    The standard RLHF shaping: the sequence score lands on the final
    response token; every response token pays the per-token KL penalty
    against the reference policy.
    """
    kl = (logprobs - ref_logprobs) * mask
    rewards = -kl_coef * kl
    idx = last_valid_index(mask)
    last = jax.nn.one_hot(idx, mask.shape[1], dtype=rewards.dtype) * mask
    return rewards + last * score[:, None]


def token_logprobs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """[B,T,V] logits for positions predicting tokens[:, :] → [B,T]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]


def entropy(logits: jax.Array, mask: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ent = -(jnp.exp(logp) * logp).sum(-1)
    return (ent * mask).sum() / jnp.maximum(mask.sum(), 1.0)
