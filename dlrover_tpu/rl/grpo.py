"""GRPO math: group-relative advantages + unbiased KL, critic-free.

EXCEEDS the reference (atorch/rl carries only the PPO lineage,
atorch/rl/trainer/): GRPO (Shao et al. 2024, DeepSeekMath; the recipe
behind DeepSeek-R1) replaces the learned value function with the
group baseline — sample G completions per prompt, normalize each
completion's sequence score against its OWN group's mean/std, and apply
that one advantage uniformly over the completion's tokens. No critic
model, no GAE, no value loss: on the 4-role engine this frees the
critic's optimizer states entirely and removes half the update FLOPs,
which is exactly the memory/flops profile long-sample reasoning RL
wants on a 16 GiB chip.

The KL term uses the k3 estimator (Schulman's unbiased low-variance
form, the one GRPO prescribes): ``exp(Δ) − Δ − 1`` with
``Δ = ref_logprob − logprob`` — nonnegative, zero iff the policies
agree, added to the LOSS (not shaped into rewards like PPO's path).
The clipped surrogate itself is shared with PPO (``ppo.ppo_policy_loss``
— the per-token advantage is just the broadcast sequence advantage).
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def group_advantages(
    scores: jax.Array,  # [B] sequence scores; B = n_prompts * group_size
    group_size: int,
    eps: float = 1e-6,
) -> jax.Array:
    """Whiten scores within each prompt's G-completion group → [B].

    Rows are grouped CONTIGUOUSLY: completions [i*G, (i+1)*G) belong to
    prompt i (the trainer repeats prompts with ``jnp.repeat``, which
    produces exactly this layout). A group with zero variance (all
    completions scored equal) gets zero advantage — no gradient, which
    is correct: the group carries no preference signal.
    """
    b = scores.shape[0]
    if b % group_size:
        raise ValueError(
            f"batch {b} not divisible by group_size {group_size}"
        )
    grouped = scores.reshape(b // group_size, group_size)
    mean = grouped.mean(axis=1, keepdims=True)
    std = grouped.std(axis=1, keepdims=True)
    return ((grouped - mean) / (std + eps)).reshape(b)


def kl_k3(
    logprobs: jax.Array,      # [B, T] current policy
    ref_logprobs: jax.Array,  # [B, T] frozen reference
    mask: jax.Array,          # [B, T]
) -> jax.Array:
    """Unbiased nonnegative per-token KL estimate, masked mean → scalar.

    k3 = exp(Δ) − Δ − 1, Δ = ref − cur: ≥ 0 with equality iff the
    logprobs match; its gradient w.r.t. ``logprobs`` is 1 − exp(Δ),
    so minimizing it pushes cur UP where the policy undershoots the
    reference (Δ > 0) and down where it overshoots — toward the
    reference either way."""
    d = ref_logprobs - logprobs
    kl = jnp.exp(d) - d - 1.0
    return (kl * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def broadcast_advantages(
    seq_advantages: jax.Array,  # [B]
    mask: jax.Array,            # [B, T]
) -> jax.Array:
    """One advantage per completion, spread over its response tokens."""
    return seq_advantages[:, None] * mask


def grpo_loss(
    logprobs: jax.Array,
    old_logprobs: jax.Array,
    advantages: jax.Array,      # [B, T] (broadcast_advantages output)
    ref_logprobs: jax.Array,
    mask: jax.Array,
    clip_ratio: float,
    kl_coef: float,
) -> Tuple[jax.Array, dict]:
    """Clipped surrogate (shared with PPO) + β·k3 KL to the reference."""
    from dlrover_tpu.rl import ppo

    pg_loss, stats = ppo.ppo_policy_loss(
        logprobs, old_logprobs, advantages, mask, clip_ratio
    )
    kl = kl_k3(logprobs, ref_logprobs, mask)
    return pg_loss + kl_coef * kl, {**stats, "pg_loss": pg_loss, "kl": kl}
