"""PPO trainer: make-experience → replay buffer → clipped updates.

Reference: atorch/atorch/rl/trainer/rl_trainer.py + ppo_trainer lineage —
generate rollouts with the actor, score with the reward model, shape
per-token rewards with the KL-vs-reference penalty, then several PPO
epochs of clipped policy/value updates from the replay buffer.

The two update steps (actor, critic) are each one jitted function over
the shared mesh; experience generation reuses models/generate.sample.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.models import generate
from dlrover_tpu.rl import ppo
from dlrover_tpu.rl.config import PPOConfig
from dlrover_tpu.rl.model_engine import ModelEngine
from dlrover_tpu.rl.replay_buffer import ReplayBuffer

logger = get_logger(__name__)


def _response_mask(rows: int, prompt_len: int, t: int) -> jax.Array:
    """Shifted response mask [rows, T-1]: position i predicts token
    i+1, responses start at index ``prompt_len`` — the ONE place this
    subtle alignment rule lives for both trainers."""
    pos = jnp.arange(t - 1)
    return jnp.broadcast_to(
        (pos >= prompt_len - 1), (rows, t - 1)
    ).astype(jnp.float32)


def _sequence_scores(engine, reward_fn, tokens, mask) -> jax.Array:
    """Programmatic reward_fn if given, else the learned reward model."""
    if reward_fn is not None:
        return jnp.asarray(
            reward_fn(np.asarray(tokens), np.asarray(mask)),
            dtype=jnp.float32,
        )
    return engine.score(tokens, mask=None)


def _run_buffer_epochs(buffer, epochs, batch_size, np_rng, update_fn):
    """Minibatch-update loop shared by the trainers; returns the mean of
    every stat over all updates (not the last snapshot), clearing the
    buffer. ``update_fn(jbatch) -> stats`` applies one update in place."""
    sums: Dict[str, float] = {}
    n_updates = 0
    for _ in range(epochs):
        for batch in buffer.batches(batch_size, np_rng):
            jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
            stats = update_fn(jbatch)
            for k, v in stats.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            n_updates += 1
    buffer.clear()
    return {k: v / max(n_updates, 1) for k, v in sums.items()}


class RLTrainer:
    def __init__(
        self,
        engine: ModelEngine,
        config: Optional[PPOConfig] = None,
        reward_fn: Optional[Callable] = None,
    ):
        """``reward_fn(tokens [B,T] np, mask [B,T-1] np) -> [B] scores``
        overrides the learned reward model (programmatic rewards — the
        path toy tasks and unit tests use; reference analog: custom
        reward models plugged into ModelEngine). NOTE: ``mask`` is the
        shifted response mask aligned with ``tokens[:, 1:]`` — mask[i, j]
        flags tokens[i, j+1] as a response token."""
        self.engine = engine
        self.config = config or PPOConfig()
        self.reward_fn = reward_fn
        self.buffer = ReplayBuffer()
        self._np_rng = np.random.default_rng(0)
        cfg = self.config

        # the behavior policy samples at cfg.temperature, so every logprob
        # (rollout-time old_logprobs, update-time new logprobs, and the
        # ref policy for the KL penalty) must be of the SAME tempered
        # distribution, or the importance ratios are biased
        inv_temp = 1.0 / cfg.temperature

        @jax.jit
        def actor_step(params, opt_state, batch):
            def loss_fn(p):
                logits = self.engine.actor_logits(p, batch["tokens"]) * (
                    inv_temp
                )
                # logits at t predict token t+1: align to response tokens
                logprobs = ppo.token_logprobs(
                    logits[:, :-1], batch["tokens"][:, 1:]
                )
                pg_loss, stats = ppo.ppo_policy_loss(
                    logprobs,
                    batch["old_logprobs"],
                    batch["advantages"],
                    batch["mask"],
                    cfg.clip_ratio,
                )
                ent = ppo.entropy(logits[:, :-1], batch["mask"])
                loss = pg_loss - cfg.entropy_coef * ent
                return loss, {**stats, "pg_loss": pg_loss, "entropy": ent}

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = self.engine.optimizers["actor"].update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, {**stats, "actor_loss": loss}

        @jax.jit
        def critic_step(params, opt_state, batch):
            def loss_fn(p):
                values = self.engine.critic_values(p, batch["tokens"])[:, :-1]
                return ppo.ppo_value_loss(
                    values,
                    batch["old_values"],
                    batch["returns"],
                    batch["mask"],
                    cfg.value_clip,
                )

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.engine.optimizers["critic"].update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"value_loss": loss}

        self._actor_step = actor_step
        self._critic_step = critic_step

        @jax.jit
        def rollout_stats(actor_p, critic_p, ref_p, tokens):
            logits = self.engine.actor_logits(actor_p, tokens) * inv_temp
            logprobs = ppo.token_logprobs(logits[:, :-1], tokens[:, 1:])
            ref_logits = (
                self.engine.actor_logits(ref_p, tokens) * inv_temp
            )
            ref_logprobs = ppo.token_logprobs(
                ref_logits[:, :-1], tokens[:, 1:]
            )
            values = self.engine.critic_values(critic_p, tokens)[:, :-1]
            return logprobs, ref_logprobs, values

        @jax.jit
        def postprocess(score, logprobs, ref_logprobs, values, mask):
            rewards = ppo.shaped_rewards(
                score, logprobs, ref_logprobs, mask, cfg.kl_coef
            )
            advantages, returns = ppo.gae_advantages(
                rewards, values, mask, cfg.gamma, cfg.lam
            )
            return ppo.masked_whiten(advantages, mask), returns

        self._rollout_stats = rollout_stats
        self._postprocess = postprocess

    # ---- experience ------------------------------------------------------

    def make_experience(self, prompts: jax.Array, rng: jax.Array) -> Dict:
        """Roll out the actor on ``prompts`` [B,P]; fill the buffer."""
        eng, cfg = self.engine, self.config
        b, p = prompts.shape
        tokens = generate.sample(
            eng.params["actor"],
            eng.cfg,
            prompts,
            cfg.max_new_tokens,
            rng=rng,
            temperature=cfg.temperature,
            mesh=eng.mesh,
        )
        t = tokens.shape[1]
        mask = _response_mask(b, p, t)
        # one compiled pass for the three model forwards, one for the
        # reward shaping + GAE — no per-op dispatch in the rollout path
        logprobs, ref_logprobs, values = self._rollout_stats(
            eng.params["actor"],
            eng.params["critic"],
            eng.params["ref"],
            tokens,
        )
        score = _sequence_scores(eng, self.reward_fn, tokens, mask)
        advantages, returns = self._postprocess(
            score, logprobs, ref_logprobs, values, mask
        )
        exp = {
            "tokens": tokens,
            "old_logprobs": logprobs,
            "old_values": values,
            "advantages": advantages,
            "returns": returns,
            "mask": mask,
        }
        self.buffer.add(exp)
        return {"score_mean": float(score.mean())}

    # ---- updates ---------------------------------------------------------

    def train_on_buffer(self, batch_size: Optional[int] = None) -> Dict:
        eng, cfg = self.engine, self.config
        batch_size = batch_size or max(1, len(self.buffer) // cfg.minibatches)

        def update(jbatch):
            (
                eng.params["actor"],
                eng.opt_states["actor"],
                astats,
            ) = self._actor_step(
                eng.params["actor"], eng.opt_states["actor"], jbatch
            )
            (
                eng.params["critic"],
                eng.opt_states["critic"],
                cstats,
            ) = self._critic_step(
                eng.params["critic"], eng.opt_states["critic"], jbatch
            )
            return {**astats, **cstats}

        return _run_buffer_epochs(
            self.buffer, cfg.ppo_epochs, batch_size, self._np_rng, update
        )

    def step(self, prompts: jax.Array, rng: jax.Array) -> Dict:
        """One full PPO round: rollout + buffer train."""
        roll = self.make_experience(prompts, rng)
        stats = self.train_on_buffer()
        return {**roll, **stats}


class GRPOTrainer:
    """Critic-free RLHF: group-relative advantages (rl/grpo.py).

    EXCEEDS the reference (atorch/rl is PPO-only). Same ModelEngine,
    but only the actor trains — the critic role (and its optimizer
    state) is never touched, and rollouts skip the value forward
    entirely. Each prompt is repeated ``group_size`` times; the group's
    score statistics replace the learned baseline.
    """

    def __init__(
        self,
        engine: ModelEngine,
        config=None,
        reward_fn: Optional[Callable] = None,
    ):
        from dlrover_tpu.rl import grpo
        from dlrover_tpu.rl.config import GRPOConfig

        self.engine = engine
        self.config = config or GRPOConfig()
        self.reward_fn = reward_fn
        self.buffer = ReplayBuffer()
        self._np_rng = np.random.default_rng(0)
        cfg = self.config
        inv_temp = 1.0 / cfg.temperature  # same tempered-policy rule as PPO

        @jax.jit
        def actor_step(params, opt_state, batch):
            def loss_fn(p):
                logits = self.engine.actor_logits(p, batch["tokens"]) * (
                    inv_temp
                )
                logprobs = ppo.token_logprobs(
                    logits[:, :-1], batch["tokens"][:, 1:]
                )
                loss, stats = grpo.grpo_loss(
                    logprobs,
                    batch["old_logprobs"],
                    batch["advantages"],
                    batch["ref_logprobs"],
                    batch["mask"],
                    cfg.clip_ratio,
                    cfg.kl_coef,
                )
                return loss, stats

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = self.engine.optimizers["actor"].update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, {**stats, "actor_loss": loss}

        @jax.jit
        def rollout_stats(actor_p, ref_p, tokens):
            logits = self.engine.actor_logits(actor_p, tokens) * inv_temp
            logprobs = ppo.token_logprobs(logits[:, :-1], tokens[:, 1:])
            ref_logits = (
                self.engine.actor_logits(ref_p, tokens) * inv_temp
            )
            ref_logprobs = ppo.token_logprobs(
                ref_logits[:, :-1], tokens[:, 1:]
            )
            return logprobs, ref_logprobs

        self._actor_step = actor_step
        self._rollout_stats = rollout_stats
        self._grpo = grpo

    def make_experience(self, prompts: jax.Array, rng: jax.Array) -> Dict:
        """Sample ``group_size`` completions per prompt; fill the buffer."""
        eng, cfg = self.engine, self.config
        b, p = prompts.shape
        g = cfg.group_size
        # contiguous repeat: rows [i*G, (i+1)*G) share prompt i — the
        # layout group_advantages' reshape assumes
        rep = jnp.repeat(prompts, g, axis=0)
        tokens = generate.sample(
            eng.params["actor"],
            eng.cfg,
            rep,
            cfg.max_new_tokens,
            rng=rng,
            temperature=cfg.temperature,
            mesh=eng.mesh,
        )
        t = tokens.shape[1]
        mask = _response_mask(b * g, p, t)
        logprobs, ref_logprobs = self._rollout_stats(
            eng.params["actor"], eng.params["ref"], tokens
        )
        score = _sequence_scores(eng, self.reward_fn, tokens, mask)
        adv = self._grpo.broadcast_advantages(
            self._grpo.group_advantages(score, g), mask
        )
        self.buffer.add(
            {
                "tokens": tokens,
                "old_logprobs": logprobs,
                "ref_logprobs": ref_logprobs,
                "advantages": adv,
                "mask": mask,
            }
        )
        return {"score_mean": float(score.mean())}

    def train_on_buffer(self, batch_size: Optional[int] = None) -> Dict:
        eng, cfg = self.engine, self.config
        batch_size = batch_size or max(
            1, len(self.buffer) // cfg.minibatches
        )

        def update(jbatch):
            (
                eng.params["actor"],
                eng.opt_states["actor"],
                stats,
            ) = self._actor_step(
                eng.params["actor"], eng.opt_states["actor"], jbatch
            )
            return stats

        return _run_buffer_epochs(
            self.buffer, cfg.epochs, batch_size, self._np_rng, update
        )

    def step(self, prompts: jax.Array, rng: jax.Array) -> Dict:
        """One full GRPO round: grouped rollout + actor updates."""
        roll = self.make_experience(prompts, rng)
        stats = self.train_on_buffer()
        return {**roll, **stats}


class DPOTrainer:
    """Offline preference optimization (rl/dpo.py) — the third
    alignment algorithm on the shared engine (EXCEEDS the reference:
    atorch/rl has no offline path). Only actor + ref are used; there
    are no rollouts, so each call is one jitted supervised-style step
    over a batch of (chosen, rejected) token pairs."""

    def __init__(self, engine: ModelEngine, beta: float = 0.1):
        from dlrover_tpu.rl import dpo

        self.engine = engine
        self.beta = float(beta)
        if self.beta <= 0:
            raise ValueError("beta must be > 0")

        @jax.jit
        def ref_logprobs(ref_params, batch):
            rc = dpo.sequence_logprob(
                self.engine.actor_logits(ref_params, batch["chosen"]),
                batch["chosen"],
                batch["chosen_mask"],
            )
            rr = dpo.sequence_logprob(
                self.engine.actor_logits(ref_params, batch["rejected"]),
                batch["rejected"],
                batch["rejected_mask"],
            )
            return rc, rr

        @jax.jit
        def dpo_step(params, opt_state, batch):
            def loss_fn(p):
                pc = dpo.sequence_logprob(
                    self.engine.actor_logits(p, batch["chosen"]),
                    batch["chosen"],
                    batch["chosen_mask"],
                )
                pr = dpo.sequence_logprob(
                    self.engine.actor_logits(p, batch["rejected"]),
                    batch["rejected"],
                    batch["rejected_mask"],
                )
                return dpo.dpo_loss(
                    pc,
                    pr,
                    batch["ref_chosen"],
                    batch["ref_rejected"],
                    self.beta,
                )

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = self.engine.optimizers["actor"].update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, {**stats, "dpo_loss": loss}

        self._ref_logprobs = ref_logprobs
        self._dpo_step = dpo_step

    def prepare(self, batch: Dict) -> Dict:
        """Attach the frozen reference's sequence logprobs to a batch.

        The ref policy and the pairs are both fixed in offline DPO, so
        these are per-pair CONSTANTS — computing them once here (and
        reusing the prepared batch across epochs) halves the forwards
        per update step. ``step`` calls this lazily for unprepared
        batches."""
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        rc, rr = self._ref_logprobs(self.engine.params["ref"], jbatch)
        return {**jbatch, "ref_chosen": rc, "ref_rejected": rr}

    def step(self, batch: Dict) -> Dict:
        """``batch``: chosen/rejected [B,T] int32 + their [B,T-1]
        response masks (same shifted-mask rule as the other trainers —
        build with ``_response_mask`` when pairs share a prompt length).
        Pass a ``prepare``d batch when iterating epochs over a fixed
        set, or a raw one (prepared lazily). Updates the actor in
        place; returns the stats."""
        eng = self.engine
        if "ref_chosen" not in batch:
            batch = self.prepare(batch)
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        (
            eng.params["actor"],
            eng.opt_states["actor"],
            stats,
        ) = self._dpo_step(
            eng.params["actor"], eng.opt_states["actor"], jbatch
        )
        return {k: float(v) for k, v in stats.items()}
