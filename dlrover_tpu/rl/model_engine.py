"""RL model engine: actor / critic / reference / reward over one mesh.

Reference: atorch/atorch/rl/model_engine/model_engine.py (ModelEngine:35 —
builds the four models, applies per-model acceleration strategies, owns
optimizers and save/load). TPU version: all four share the decoder
architecture; actor+critic carry optax states, ref+reward are frozen; the
shared mesh means one set of shardings and no DeepSpeed hybrid-engine
module surgery — jit recompiles specialize train vs. rollout instead
(the role the ds_hybrid_engine/ directory plays in the reference).
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.models import decoder
from dlrover_tpu.models.config import ModelConfig

logger = get_logger(__name__)

ROLES = ("actor", "critic", "ref", "reward")
TRAINABLE = ("actor", "critic")


def init_value_head(rng, cfg: ModelConfig) -> Dict:
    w = jax.random.normal(rng, (cfg.d_model, 1)) * (cfg.d_model**-0.5)
    return {"w": w.astype(jnp.float32), "b": jnp.zeros((1,), jnp.float32)}


def value_forward(params: Dict, tokens, cfg, mesh=None) -> jax.Array:
    """Scalar-per-position head on the decoder trunk → [B, S]."""
    h = decoder.forward(
        params["backbone"], tokens, cfg, mesh=mesh, features_only=True
    )
    out = h.astype(jnp.float32) @ params["v_head"]["w"] + params["v_head"]["b"]
    return out[..., 0]


def reward_score(params: Dict, tokens, cfg, mesh=None, mask=None) -> jax.Array:
    """Sequence score = value head at each row's last valid token → [B].

    The index is positional (last set bit of ``mask``), so prefix and
    suffix masks both work.
    """
    values = value_forward(params, tokens, cfg, mesh=mesh)
    if mask is None:
        return values[:, -1]
    from dlrover_tpu.rl.ppo import last_valid_index

    idx = last_valid_index(mask)
    return jnp.take_along_axis(values, idx[:, None], axis=1)[:, 0]


class ModelEngine:
    """Holds params + optimizer states for the four PPO roles."""

    def __init__(
        self,
        cfg: ModelConfig,
        mesh=None,
        rng: Optional[jax.Array] = None,
        learning_rate: float = 1e-5,
        critic_learning_rate: float = 1e-5,
        grad_clip: float = 1.0,
        actor_params: Optional[Any] = None,
        reward_params: Optional[Any] = None,
        init_reward: bool = True,
        critic_from_reward: Any = "auto",
    ):
        """``init_reward=False`` skips the learned reward backbone — use
        it when RLTrainer gets a programmatic ``reward_fn``, so a full
        model's worth of HBM is not wasted on unread weights.

        Weight sharing (the hybrid-engine economy, reference:
        atorch/atorch/rl/ds_hybrid_engine/hybrid_engine.py — there the
        actor's training and inference modules share parameter storage):
        on TPU rollout jits read the SAME sharded actor buffers the
        train step updates, so the inference copy the reference works to
        eliminate never exists here. Within the engine, ref aliases the
        actor's initial arrays; with a SUPPLIED (trained) reward model
        the critic backbone warm-starts FROM it by alias (the TRL /
        InstructGPT recipe) — the production RLHF setup then holds TWO
        distinct full weight sets for four roles at init.
        ``critic_from_reward="auto"`` applies that alias exactly when
        ``reward_params`` were provided: warm-starting from a
        fresh-RANDOM reward backbone would couple two inits for no
        benefit (measurably hurts toy PPO). Arrays are immutable and
        updates rebind, so the aliases stay frozen and only *diverged*
        trainable weights ever cost extra HBM.
        """
        self.cfg = cfg
        self.mesh = mesh
        rng = rng if rng is not None else jax.random.key(0)
        keys = jax.random.split(rng, 6)
        actor = actor_params or decoder.init(keys[0], cfg)
        # ref aliases the actor's initial arrays (standard RLHF frozen
        # snapshot): jax arrays are immutable and optimizer updates rebind
        # rather than mutate, so no copy — no second weight set in HBM
        ref = actor
        reward = None
        if reward_params is not None:
            # supplied pretrained reward weights always win, regardless
            # of init_reward (which only gates FRESH initialization)
            reward = reward_params
        elif init_reward:
            reward = {
                "backbone": decoder.init(keys[3], cfg),
                "v_head": init_value_head(keys[4], cfg),
            }
        if critic_from_reward == "auto":
            critic_from_reward = reward_params is not None
        if critic_from_reward and reward is not None:
            # critic starts FROM the reward model (TRL-style warm start;
            # also how InstructGPT initializes the value function) — the
            # backbone is an alias, so only the critic's own training
            # divergence costs memory
            critic = {
                "backbone": reward["backbone"],
                "v_head": init_value_head(keys[2], cfg),
            }
        else:
            critic = {
                "backbone": decoder.init(keys[1], cfg),
                "v_head": init_value_head(keys[2], cfg),
            }
        self.params: Dict[str, Any] = {
            "actor": actor,
            "critic": critic,
            "ref": ref,
            "reward": reward,
        }
        self.optimizers = {
            "actor": optax.chain(
                optax.clip_by_global_norm(grad_clip),
                optax.adamw(learning_rate),
            ),
            "critic": optax.chain(
                optax.clip_by_global_norm(grad_clip),
                optax.adamw(critic_learning_rate),
            ),
        }
        self.opt_states = {
            role: self.optimizers[role].init(self.params[role])
            for role in TRAINABLE
        }

    # ---- role application ------------------------------------------------

    def actor_logits(self, params, tokens):
        return decoder.forward(params, tokens, self.cfg, mesh=self.mesh)

    def critic_values(self, params, tokens):
        return value_forward(params, tokens, self.cfg, mesh=self.mesh)

    def ref_logits(self, tokens):
        return decoder.forward(
            self.params["ref"], tokens, self.cfg, mesh=self.mesh
        )

    def score(self, tokens, mask=None):
        if self.params["reward"] is None:
            raise RuntimeError(
                "ModelEngine was built with init_reward=False; supply a "
                "reward_fn to RLTrainer or rebuild with init_reward=True"
            )
        return reward_score(
            self.params["reward"], tokens, self.cfg, mesh=self.mesh, mask=mask
        )

    # ---- memory accounting ----------------------------------------------

    def distinct_param_bytes(self) -> int:
        """Bytes of UNIQUE parameter arrays across all roles.

        Aliased subtrees (ref→actor, critic→reward backbones) count
        once: arrays are immutable, so object identity == storage
        identity. This is the accounting behind the "4 roles, ≤2 full
        weight sets at init" guarantee."""
        seen = {}
        for tree in self.params.values():
            if tree is None:
                continue
            for leaf in jax.tree.leaves(tree):
                seen[id(leaf)] = leaf.nbytes
        return sum(seen.values())

    def weight_sets(self) -> float:
        """distinct param bytes / one actor's bytes — 2.0 ≈ two full
        models resident (plus epsilon for the value heads)."""
        actor_bytes = sum(
            leaf.nbytes for leaf in jax.tree.leaves(self.params["actor"])
        )
        return self.distinct_param_bytes() / max(actor_bytes, 1)

    # ---- updates ---------------------------------------------------------

    def apply_gradients(self, role: str, grads) -> None:
        opt = self.optimizers[role]
        updates, self.opt_states[role] = opt.update(
            grads, self.opt_states[role], self.params[role]
        )
        self.params[role] = optax.apply_updates(self.params[role], updates)

    # ---- checkpoint ------------------------------------------------------

    def state_dict(self) -> Dict:
        return {
            "params": self.params,
            "opt_states": self.opt_states,
        }

    def load_state_dict(self, sd: Dict) -> None:
        self.params = sd["params"]
        self.opt_states = sd["opt_states"]
