"""Experience replay buffer for PPO.

Reference: atorch/atorch/rl/replay_buffer/replay_buffer.py — host-side
store of rollout elements, drained into training minibatches each PPO
round. Host numpy keeps HBM free for the four models.
"""

from typing import Dict, Iterator, List, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity
        self._items: List[Dict[str, np.ndarray]] = []

    def add(self, item: Dict) -> None:
        """item: dict of per-sequence arrays (tokens, logprobs, values,
        rewards, mask, ...), leading dim = batch."""
        arrays = {k: np.asarray(v) for k, v in item.items()}
        n = next(iter(arrays.values())).shape[0]
        for i in range(n):
            self._items.append({k: v[i] for k, v in arrays.items()})
        if self.capacity is not None and len(self._items) > self.capacity:
            self._items = self._items[-self.capacity:]

    def __len__(self) -> int:
        return len(self._items)

    def clear(self) -> None:
        self._items.clear()

    def batches(
        self, batch_size: int, rng: Optional[np.random.Generator] = None
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Shuffled full-coverage minibatches (drops the ragged tail)."""
        idx = np.arange(len(self._items))
        if rng is not None:
            rng.shuffle(idx)
        for lo in range(0, len(idx) - batch_size + 1, batch_size):
            sel = idx[lo : lo + batch_size]
            keys = self._items[0].keys()
            yield {
                k: np.stack([self._items[i][k] for i in sel]) for k in keys
            }
