"""PPO / RLHF configuration.

Reference: atorch/atorch/rl/config.py (AtorchRLConfig: model types,
generation, train, ppo_config sections driving ModelEngine + RLTrainer).
"""

from dataclasses import dataclass


@dataclass
class PPOConfig:
    # GAE
    gamma: float = 1.0
    lam: float = 0.95
    # PPO clipping
    clip_ratio: float = 0.2
    value_clip: float = 0.2
    # loss coefficients
    entropy_coef: float = 0.0
    # KL shaping against the frozen reference policy
    kl_coef: float = 0.1
    # optimisation (NOTE: optimizer hyperparameters — learning rates,
    # grad clip — live on ModelEngine, which owns the optimizers)
    ppo_epochs: int = 4
    minibatches: int = 1
    # generation; temperature must be > 0 (PPO needs a stochastic
    # behavior policy with well-defined logprobs)
    max_new_tokens: int = 16
    temperature: float = 1.0

    def __post_init__(self):
        if self.temperature <= 0.0:
            raise ValueError(
                "PPO requires temperature > 0: greedy rollouts have a "
                "degenerate behavior policy with undefined logprobs"
            )


@dataclass
class GRPOConfig:
    """GRPO hyperparameters (rl/grpo.py; DeepSeekMath recipe).

    No gamma/lam/value_clip: there is no critic. ``group_size`` is the
    number of completions sampled per prompt — the group IS the
    baseline."""

    group_size: int = 4
    clip_ratio: float = 0.2
    kl_coef: float = 0.05
    epochs: int = 2
    minibatches: int = 1
    max_new_tokens: int = 16
    temperature: float = 1.0

    def __post_init__(self):
        if self.temperature <= 0.0:
            raise ValueError(
                "GRPO requires temperature > 0: the group baseline "
                "needs diverse stochastic completions"
            )
        if self.group_size < 2:
            raise ValueError(
                "group_size must be >= 2: a single completion has no "
                "group to be relative to"
            )
