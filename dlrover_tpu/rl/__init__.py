from dlrover_tpu.rl.config import GRPOConfig, PPOConfig  # noqa: F401
from dlrover_tpu.rl.model_engine import ModelEngine  # noqa: F401
from dlrover_tpu.rl.replay_buffer import ReplayBuffer  # noqa: F401
from dlrover_tpu.rl.trainer import (  # noqa: F401
    DPOTrainer,
    GRPOTrainer,
    RLTrainer,
)
