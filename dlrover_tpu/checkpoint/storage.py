"""Checkpoint storage abstraction + Posix impl + deletion strategies.

Reference: dlrover/python/common/storage.py:24,128,203 (CheckpointStorage,
PosixDiskStorage, KeepLatestStepStrategy/KeepStepIntervalStrategy).
"""

import os
import re
import shutil
from typing import List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

STEP_DIR_RE = re.compile(r"^step_(\d+)$")


class CheckpointStorage:
    def write_bytes(self, data: memoryview, path: str):
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def makedirs(self, path: str):
        raise NotImplementedError

    def delete(self, path: str):
        raise NotImplementedError


class PosixStorage(CheckpointStorage):
    def write_bytes(self, data: memoryview, path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def mmap(self, path: str) -> memoryview:
        import mmap as mmap_mod

        with open(path, "rb") as f:
            mm = mmap_mod.mmap(f.fileno(), 0, access=mmap_mod.ACCESS_READ)
        return memoryview(mm)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path) if os.path.isdir(path) else []

    def makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)


class DeletionStrategy:
    def clean_up(self, ckpt_dir: str, storage: CheckpointStorage):
        raise NotImplementedError


class KeepLatestStepStrategy(DeletionStrategy):
    """Keep only the newest N committed step dirs."""

    def __init__(self, max_to_keep: int = 3):
        self.max_to_keep = max_to_keep

    def clean_up(self, ckpt_dir: str, storage: CheckpointStorage):
        latest = read_tracker(ckpt_dir, storage)
        steps = sorted(committed_steps(ckpt_dir, storage))
        for step in steps[: -self.max_to_keep]:
            if step == latest:
                continue  # never delete the tracker's target
            storage.delete(os.path.join(ckpt_dir, f"step_{step}"))
            logger.info("deleted old checkpoint step_%d", step)


class KeepStepIntervalStrategy(DeletionStrategy):
    """Keep steps that are multiples of ``interval``; delete the rest."""

    def __init__(self, interval: int = 1000):
        self.interval = interval

    def clean_up(self, ckpt_dir: str, storage: CheckpointStorage):
        latest = read_tracker(ckpt_dir, storage)
        for step in committed_steps(ckpt_dir, storage):
            if step % self.interval and step != latest:
                storage.delete(os.path.join(ckpt_dir, f"step_{step}"))


def committed_steps(ckpt_dir: str, storage: CheckpointStorage) -> List[int]:
    steps = []
    for name in storage.listdir(ckpt_dir):
        m = STEP_DIR_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return steps


def read_tracker(ckpt_dir: str, storage: CheckpointStorage) -> Optional[int]:
    path = os.path.join(ckpt_dir, "latest.txt")
    if not storage.exists(path):
        return None
    try:
        return int(storage.read_bytes(path).decode().strip())
    except (ValueError, OSError):
        return None


def write_tracker(ckpt_dir: str, step: int, storage: CheckpointStorage):
    storage.write_bytes(
        memoryview(str(step).encode()), os.path.join(ckpt_dir, "latest.txt")
    )
