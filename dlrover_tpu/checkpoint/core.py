"""Shard-pack format: sharded pytrees ⇄ one contiguous buffer per host.

The unit of checkpoint IO. Each host packs the *replica-0 addressable
shards* of every array in the state pytree into a single buffer:

    [u64 header_len][header JSON][shard payload | shard payload | ...]

The header records, per leaf: its pytree path, dtype, global shape, and the
global index (slice per dim) + offset of every shard in the payload. Because
indices are global, restore can assemble ANY target sharding from the union
of packs — the resharding path the reference implements by hand for each
framework (fsdp_save_util.py, megatron_dist_ckpt.py) falls out of the
format here.

Same bytes live in shared memory (staging) and on disk (persisted), so the
agent's async persist is a raw copy.
"""

import dataclasses
import json
import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from dlrover_tpu.observability.tracing import get_tracer

HEADER_LEN_BYTES = 8
ALIGN = 128

# module-level so the compiled copy is cached across leaves that share a
# shape/sharding (a fresh jax.jit per leaf would recompile every time)
_owned_copy = jax.jit(jax.numpy.copy)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _slice_to_json(s: slice, dim: int) -> List[int]:
    start = 0 if s.start is None else int(s.start)
    stop = dim if s.stop is None else int(s.stop)
    return [start, stop]


@dataclasses.dataclass
class ShardEntry:
    index: List[List[int]]  # [[start, stop], ...] per dim (global coords)
    offset: int
    nbytes: int


@dataclasses.dataclass
class LeafEntry:
    path: str
    dtype: str
    global_shape: List[int]
    shards: List[ShardEntry]


def plan_pack(state: Any) -> Tuple[List[LeafEntry], int]:
    """Compute the header + total payload size for a pytree of jax arrays."""
    leaves_with_path = jax.tree_util.tree_flatten_with_path(state)[0]
    entries: List[LeafEntry] = []
    offset = 0
    for path, leaf in leaves_with_path:
        arr = leaf
        dtype = np.dtype(arr.dtype)
        gshape = list(arr.shape)
        shards: List[ShardEntry] = []
        for shard in _replica0_shards(arr):
            idx = [
                _slice_to_json(s, d)
                for s, d in zip(shard.index, gshape)
            ] if gshape else []
            nbytes = int(
                dtype.itemsize
                * (math.prod(b - a for a, b in idx) if idx else 1)
            )
            offset = (offset + ALIGN - 1) // ALIGN * ALIGN
            shards.append(ShardEntry(index=idx, offset=offset, nbytes=nbytes))
            offset += nbytes
        entries.append(
            LeafEntry(
                path=_path_str(path),
                dtype=dtype.name,
                global_shape=gshape,
                shards=shards,
            )
        )
    return entries, offset


def _replica0_shards(arr):
    if hasattr(arr, "addressable_shards"):
        return [s for s in arr.addressable_shards if s.replica_id == 0]

    class _Whole:
        index = ()
        data = arr

    w = _Whole()
    w.index = tuple(slice(0, d) for d in np.shape(arr))
    w.data = np.asarray(arr)
    return [w]


def header_bytes(step: int, entries: List[LeafEntry], extra: Dict = None) -> bytes:
    doc = {
        "version": 1,
        "step": step,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "extra": extra or {},
        "leaves": [
            {
                "path": e.path,
                "dtype": e.dtype,
                "global_shape": e.global_shape,
                "shards": [dataclasses.asdict(s) for s in e.shards],
            }
            for e in entries
        ],
    }
    return json.dumps(doc).encode("utf-8")


def pack_size(header: bytes, payload_size: int) -> int:
    base = HEADER_LEN_BYTES + len(header)
    base = (base + ALIGN - 1) // ALIGN * ALIGN
    return base + payload_size


def payload_start(header: bytes) -> int:
    base = HEADER_LEN_BYTES + len(header)
    return (base + ALIGN - 1) // ALIGN * ALIGN


def write_pack(
    buf: memoryview,
    step: int,
    state: Any,
    entries: List[LeafEntry],
    extra: Dict = None,
    header: Optional[bytes] = None,
) -> int:
    """Write header + all shard payloads into ``buf``; returns bytes used.

    Device→host copies are started async for every shard first, then
    consumed — overlapping DMA with serialization. Pass the ``header``
    already computed for sizing to avoid re-serializing the (potentially
    large) leaf manifest under the checkpoint lock.
    """
    if header is None:
        header = header_bytes(step, entries, extra)
    n = len(header)
    buf[:HEADER_LEN_BYTES] = n.to_bytes(HEADER_LEN_BYTES, "little")
    buf[HEADER_LEN_BYTES : HEADER_LEN_BYTES + n] = header
    start = payload_start(header)

    leaves = [leaf for _, leaf in jax.tree_util.tree_flatten_with_path(state)[0]]
    with get_tracer().span("ckpt.write_pack", step=step, leaves=len(leaves)):
        # kick off async D2H for everything first
        for leaf in leaves:
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        used = start
        for leaf, entry in zip(leaves, entries):
            shards = _replica0_shards(leaf)
            for shard, sentry in zip(shards, entry.shards):
                data = np.asarray(shard.data)
                raw = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
                lo = start + sentry.offset
                hi = lo + sentry.nbytes
                # direct buffer-protocol assignment: .tobytes() would copy
                # through an intermediate bytes object (measured ~9x slower
                # for large shards — this is the staging hot loop)
                buf[lo:hi] = raw
                used = max(used, hi)
    return used


def read_header(buf: memoryview) -> Dict:
    n = int.from_bytes(buf[:HEADER_LEN_BYTES], "little")
    return json.loads(bytes(buf[HEADER_LEN_BYTES : HEADER_LEN_BYTES + n]))


class PackIndex:
    """Random access over one or more packs (shm buffers or mmapped files)."""

    def __init__(self):
        # path -> list of (index, np_view)
        self._shards: Dict[str, List[Tuple[Tuple[slice, ...], np.ndarray]]] = {}
        self._meta: Dict[str, Tuple[str, Tuple[int, ...]]] = {}
        self.step: Optional[int] = None
        self.process_count: int = 0

    def add_pack(self, buf: memoryview):
        n = int.from_bytes(buf[:HEADER_LEN_BYTES], "little")
        doc = json.loads(bytes(buf[HEADER_LEN_BYTES : HEADER_LEN_BYTES + n]))
        if self.step is None:
            self.step = doc["step"]
            self.process_count = doc.get("process_count", 1)
        base = HEADER_LEN_BYTES + n
        start = (base + ALIGN - 1) // ALIGN * ALIGN
        for leaf in doc["leaves"]:
            path = leaf["path"]
            dtype = np.dtype(leaf["dtype"])
            gshape = tuple(leaf["global_shape"])
            self._meta[path] = (leaf["dtype"], gshape)
            for s in leaf["shards"]:
                idx = tuple(slice(a, b) for a, b in s["index"])
                shape = tuple(b - a for a, b in s["index"])
                lo = start + s["offset"]
                view = np.frombuffer(
                    buf, dtype=dtype, count=max(1, math.prod(shape)) if shape else 1,
                    offset=lo,
                ).reshape(shape)
                self._shards.setdefault(path, []).append((idx, view))

    def close(self):
        """Drop all buffer views so the backing shm/mmap can close
        cleanly (numpy views pin the mapping; without this, SharedMemory
        teardown raises 'cannot close exported pointers exist')."""
        self._shards.clear()
        self._meta.clear()

    def paths(self) -> List[str]:
        return list(self._meta.keys())

    def global_shape(self, path: str) -> Tuple[int, ...]:
        return self._meta[path][1]

    def dtype(self, path: str) -> np.dtype:
        return np.dtype(self._meta[path][0])

    def read_slice(self, path: str, index: Tuple[slice, ...]) -> np.ndarray:
        """Assemble an arbitrary global slice from stored shards."""
        dtype, gshape = np.dtype(self._meta[path][0]), self._meta[path][1]
        want = tuple(
            slice(
                0 if s.start is None else s.start,
                dim if s.stop is None else s.stop,
            )
            for s, dim in zip(index, gshape)
        ) if gshape else ()
        if not gshape:
            shards = self._shards.get(path, [])
            if not shards:
                raise KeyError(f"no shards for {path}")
            # COPY, not a view: jax's CPU backend zero-copy aliases numpy
            # arrays, and a view would pin the backing shm mapping open
            return np.array(shards[0][1], copy=True).reshape(())
        shape = tuple(s.stop - s.start for s in want)
        out = np.empty(shape, dtype)
        filled = np.zeros(shape, bool) if not _covers(want, self._shards.get(path, [])) else None
        for idx, view in self._shards.get(path, []):
            inter = []
            ok = True
            for w, h in zip(want, idx):
                lo = max(w.start, h.start)
                hi = min(w.stop, h.stop)
                if lo >= hi:
                    ok = False
                    break
                inter.append((lo, hi))
            if not ok:
                continue
            dst = tuple(
                slice(lo - w.start, hi - w.start)
                for (lo, hi), w in zip(inter, want)
            )
            src = tuple(
                slice(lo - h.start, hi - h.start)
                for (lo, hi), h in zip(inter, idx)
            )
            out[dst] = view[src]
            if filled is not None:
                filled[dst] = True
        if filled is not None and not filled.all():
            raise KeyError(
                f"pack set does not cover requested slice of {path}"
            )
        return out


def _covers(want, shards) -> bool:
    # fast path: a single shard covering the whole request
    for idx, _ in shards:
        if all(
            h.start <= w.start and h.stop >= w.stop
            for w, h in zip(want, idx)
        ):
            return True
    return False


class RestoreMismatchError(Exception):
    """The checkpoint's leaf set does not satisfy the restore contract
    (missing leaves without ``partial``, missing PARAM leaves, or an
    abstract target that cannot supply fresh values). Deliberately NOT
    a KeyError: the engine's load fallbacks swallow KeyError as
    "no checkpoint here" — a contract violation must propagate loudly
    instead of silently restarting training from scratch."""


def restore_tree(
    target: Any,
    pack_index: PackIndex,
    shardings: Any = None,
    partial: bool = False,
) -> Any:
    """Build a pytree of (sharded) jax arrays matching ``target``'s structure.

    ``target`` is a pytree of ShapeDtypeStruct/arrays providing structure;
    ``shardings`` an optional matching pytree of NamedSharding for the NEW
    mesh — this is the resharded-restore path after an elastic re-election.

    ``partial=True``: leaves MISSING from the pack keep the target's
    value — the forward-compatibility path for state trees that grew
    since the checkpoint (new fp8 amax slots, new optimizer state).
    The target must then carry CONCRETE arrays (the freshly initialized
    live state, not a ShapeDtypeStruct template) so there is a value to
    keep; an abstract target with a missing leaf still raises.
    """
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(target)
    shard_leaves = (
        jax.tree_util.tree_flatten(shardings)[0]
        if shardings is not None
        else [None] * len(leaves_with_path)
    )
    restore_span = get_tracer().span(
        "ckpt.restore_tree",
        step=pack_index.step if pack_index.step is not None else -1,
        leaves=len(leaves_with_path),
        resharded=shardings is not None,
    )
    out = []
    kept = []
    for (path, leaf), sharding in zip(leaves_with_path, shard_leaves):
        pstr = _path_str(path)
        if pstr not in pack_index._meta:
            if not partial:
                raise RestoreMismatchError(
                    f"checkpoint has no leaf {pstr} (state tree grew "
                    "since the save?); pass partial=True with the live "
                    "state to keep fresh values for new leaves"
                )
            if pstr.startswith("params"):
                # a missing PARAM is never an upgrade — it is a rename
                # or corruption, and silently resuming with random
                # weights in one subtree is the worst failure mode
                raise RestoreMismatchError(
                    f"partial restore: param leaf {pstr} is missing "
                    "from the checkpoint — refusing to substitute "
                    "fresh weights"
                )
            if not isinstance(leaf, (np.ndarray, jax.Array)):
                raise RestoreMismatchError(
                    f"partial restore: {pstr} is missing from the "
                    "checkpoint and the target leaf is abstract — pass "
                    "the live initialized state as target"
                )
            kept.append(pstr)
            out.append(
                leaf
                if sharding is None
                else jax.device_put(leaf, sharding)
            )
            continue
        gshape = pack_index.global_shape(pstr)
        # restore into the TARGET's dtype: a precision change between
        # save and restore (bf16 run resumed in f32, or vice versa) must
        # not silently leak the pack dtype into the training state
        dtype = np.dtype(
            getattr(leaf, "dtype", None) or pack_index.dtype(pstr)
        )
        # Both branches must hand back jax-OWNED buffers, never a
        # zero-copy alias of the assembled numpy arrays: jax's CPU
        # backend aliases any 64-byte-aligned numpy buffer, and the
        # train step DONATES the restored state — XLA then releases
        # memory that numpy's allocator owns, which corrupts the glibc
        # heap a step or two after an in-place resume. Alignment of
        # np.empty is luck-of-the-malloc, so the crash is flaky.
        if sharding is None:
            full = pack_index.read_slice(
                pstr, tuple(slice(0, d) for d in gshape)
            )
            # astype copy=False: a no-op when the pack already matches
            # the target dtype; jnp.array then makes the owned copy
            out.append(jax.numpy.array(full.astype(dtype, copy=False)))
        else:
            arr = jax.make_array_from_callback(
                gshape,
                sharding,
                lambda idx, p=pstr, dt=dtype: pack_index.read_slice(
                    p, idx
                ).astype(dt, copy=False),
            )
            # device-to-device copy off the aliased callback shards;
            # jit keeps the sharding and works on multi-host globals
            out.append(_owned_copy(arr))
    if kept:
        from dlrover_tpu.common.log import get_logger

        get_logger(__name__).warning(
            "partial restore: %d leaves not in the checkpoint kept "
            "their fresh values (first: %s) — expected after a "
            "state-tree upgrade (e.g. new fp8 slots), NOT for params",
            len(kept),
            kept[0],
        )
    # mismatch raises above leave the span un-ended, which records
    # nothing — only completed restores land on the timeline
    restore_span.end(kept=len(kept))
    return jax.tree_util.tree_unflatten(treedef, out)
