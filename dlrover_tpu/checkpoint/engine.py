"""Worker-side checkpoint engine: HBM → host shared memory, async persist.

Reference: dlrover/python/elastic_agent/torch/ckpt_saver.py SharedMemoryHandler
(:209) + CheckpointEngine (flash_checkpoint/engine.py:136,297). The worker
blocks only for the device→host copy into shared memory (~HBM bandwidth);
persistence to storage happens in the *agent* process (or a background
thread in standalone mode), so a worker crash after staging never loses the
checkpoint — the agent still holds the bytes.
"""

import os
import threading
import time
from typing import Any, Optional

import jax

from dlrover_tpu.common.constants import GraftEnv
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import (
    SharedDictClient,
    SharedLockClient,
    SharedQueueClient,
    attach_shared_memory,
    create_shared_memory,
)
from dlrover_tpu.checkpoint import core
from dlrover_tpu.checkpoint.storage import PosixStorage
from dlrover_tpu.observability import telemetry
from dlrover_tpu.observability.tracing import get_tracer

logger = get_logger(__name__)


def shm_name(process_index: Optional[int] = None) -> str:
    run_id = os.environ.get(GraftEnv.RUN_ID, "default")
    pi = jax.process_index() if process_index is None else process_index
    return f"dlrover_tpu_ckpt_{run_id}_{pi}"


class CheckpointEngine:
    """Stages state pytrees into shm; delegates persist to the saver."""

    def __init__(
        self,
        ckpt_dir: str,
        master_client=None,
        use_agent: Optional[bool] = None,
        storage=None,
        replica=None,
    ):
        self.ckpt_dir = ckpt_dir
        self._client = master_client
        self._storage = storage or PosixStorage()
        self._replica = replica  # Optional[replica.ReplicaManager]
        self._shm = None
        self._local_step = -1
        if use_agent is None:
            from dlrover_tpu.common.multi_process import broker_alive

            use_agent = broker_alive("queue_ckpt")
        self._use_agent = use_agent
        if use_agent:
            self._queue = SharedQueueClient("ckpt")
            self._meta = SharedDictClient("ckpt_meta")
            self._lock = SharedLockClient("ckpt")
        else:
            self._queue = None
            self._meta = {}
            self._lock = threading.Lock()
            self._persist_thread: Optional[threading.Thread] = None

    # ---- save ------------------------------------------------------------

    def save_to_memory(self, step: int, state: Any) -> bool:
        """Stage ``state`` into shared memory. Returns False if skipped."""
        t0 = time.perf_counter()
        entries, payload = core.plan_pack(state)
        header = core.header_bytes(step, entries, {"dir": self.ckpt_dir})
        total = core.pack_size(header, payload)

        if not self._acquire(blocking=False):
            # saver busy persisting the previous step: skip this save
            # (reference: engine.py:53 check_all_rank_ready skip path)
            logger.warning("step %d: saver busy, skipping memory save", step)
            return False
        stage_span = get_tracer().span(
            "ckpt.save_memory", step=step, nbytes=total
        )
        try:
            if self._shm is None or self._shm.size < total:
                name = shm_name()
                self._shm = create_shared_memory(name, _round_up(total))
            used = core.write_pack(
                memoryview(self._shm.buf),
                step,
                state,
                entries,
                header=header,
            )
            meta = {
                "step": step,
                "used": used,
                "dir": self.ckpt_dir,
                "shm": self._shm.name,
                "process_index": jax.process_index(),
                "process_count": jax.process_count(),
                "time": time.time(),
            }
            if self._use_agent:
                self._meta.set("latest", meta)
            else:
                self._meta["latest"] = meta
            self._local_step = step
        finally:
            self._release()
            stage_span.end()
        hub = telemetry.get_hub()
        if hub.enabled:
            hub.publish(
                telemetry.CheckpointRecord(
                    kind="save_memory",
                    step=step,
                    seconds=stage_span.dur_us / 1e6,
                    nbytes=total,
                    tier="memory",
                )
            )
        if self._replica is not None:
            # stream the fresh pack to ring peers off the critical path
            # (reference: replica.py backup hooked at engine.py:328)
            self._replica.backup_async(meta, shm_lock=self._lock)
        if self._client is not None:
            try:
                self._client.report_ckpt_step(step)
            except Exception:  # noqa: BLE001
                logger.warning("ckpt step report failed", exc_info=True)
        logger.info(
            "staged step %d to shm in %.3fs (%.1f MB)",
            step,
            time.perf_counter() - t0,
            total / 1e6,
        )
        return True

    def save_to_storage(self, step: int, state: Any) -> bool:
        """Stage + trigger async persist."""
        if not self.save_to_memory(step, state):
            return False
        if self._use_agent:
            return self._queue.put({"type": "persist", "step": step})
        # standalone: persist on a background thread
        if self._persist_thread and self._persist_thread.is_alive():
            self._persist_thread.join()
        meta = dict(self._meta["latest"])
        self._persist_thread = threading.Thread(
            target=self._persist_standalone, args=(meta,), daemon=True
        )
        self._persist_thread.start()
        return True

    def wait_for_persist(self, timeout: float = 300.0) -> bool:
        """Block until the latest staged step is committed to storage.

        Returns False — and publishes a failed ``persist_wait``
        CheckpointRecord — when the commit does not land inside
        ``timeout``; a silent return here previously let callers tear
        down hosts believing the disk tier was durable."""
        ok = True
        if self._use_agent:
            from dlrover_tpu.checkpoint.storage import read_tracker

            deadline = time.time() + timeout
            while True:
                if read_tracker(self.ckpt_dir, self._storage) == (
                    self._local_step
                ):
                    break
                if time.time() >= deadline:
                    ok = False
                    break
                time.sleep(0.1)
        elif self._persist_thread:
            self._persist_thread.join(timeout)
            ok = not self._persist_thread.is_alive()
        if not ok:
            logger.error(
                "persist of step %d did not commit within %.0fs; the "
                "storage tier is STALE for this step",
                self._local_step,
                timeout,
            )
            hub = telemetry.get_hub()
            if hub.enabled:
                hub.publish(
                    telemetry.CheckpointRecord(
                        kind="persist_wait",
                        step=self._local_step,
                        seconds=timeout,
                        ok=False,
                        tier="storage",
                    )
                )
        return ok

    def _persist_standalone(self, meta):
        from dlrover_tpu.checkpoint.saver import persist_pack

        shm = attach_shared_memory(meta["shm"])
        try:
            persist_pack(
                memoryview(shm.buf)[: meta["used"]],
                meta["dir"],
                meta["step"],
                meta["process_index"],
                meta["process_count"],
                self._storage,
            )
        finally:
            shm.close()

    # ---- load ------------------------------------------------------------

    def load(
        self,
        target: Any,
        shardings: Any = None,
        step: Optional[int] = None,
        partial: bool = False,
    ) -> Optional[Any]:
        """Restore: shm if fresh, else committed storage. None if nothing.

        ``partial``: leaves absent from the checkpoint keep the
        target's (concrete) values — the state-tree-upgrade path
        (core.restore_tree). A tree-contract violation
        (core.RestoreMismatchError) in the memory/replica TIERS falls
        through (they are caches; storage is the source of truth), but
        if no tier produces a state the mismatch re-raises rather than
        masquerading as "no checkpoint" — a silent from-scratch restart
        is the worst outcome of a restore bug."""
        mismatch: Optional[core.RestoreMismatchError] = None
        # "failover." prefix: restore is a phase of the recovery timeline,
        # so the drill's phase extraction picks it up with the rest
        span = get_tracer().span("failover.restore")
        with span:
            tier = "none"
            try:
                state = self._load_from_memory(
                    target, shardings, step, partial
                )
                if state is not None:
                    tier = "memory"
            except core.RestoreMismatchError as e:
                mismatch = e
                state = None
            if state is None:
                try:
                    state = self._load_from_replica(
                        target, shardings, step, partial
                    )
                    if state is not None:
                        tier = "replica"
                except core.RestoreMismatchError as e:
                    mismatch = mismatch or e
                    state = None
            if state is None:
                state = self.load_from_storage(
                    target, shardings, step, partial
                )
                if state is not None:
                    tier = "storage"
            span.args["tier"] = tier
            if state is None and mismatch is not None:
                raise mismatch
        self._publish_restore(tier, span.end())
        return state

    def _publish_restore(self, tier: str, seconds: float):
        hub = telemetry.get_hub()
        if hub.enabled:
            hub.publish(
                telemetry.CheckpointRecord(
                    kind="restore",
                    step=self._local_step,
                    seconds=seconds,
                    ok=tier != "none",
                    tier=tier,
                )
            )

    def _load_from_memory(self, target, shardings, step, partial=False):
        try:
            meta = self._meta.get("latest")
            if not meta:
                return None
            if step is not None and meta["step"] != step:
                return None
            if self._client is not None:
                # all ranks must hold the same staged step
                min_step = self._client.get_min_ckpt_step()
                if min_step != meta["step"]:
                    logger.warning(
                        "staged step %s inconsistent with cluster min %s",
                        meta["step"],
                        min_step,
                    )
                    return None
            shm = attach_shared_memory(meta["shm"])
            idx = core.PackIndex()
            try:
                idx.add_pack(memoryview(shm.buf)[: meta["used"]])
                state = core.restore_tree(target, idx, shardings, partial=partial)
                step = idx.step
                # restore_tree copied everything to device
                state = jax.block_until_ready(state)
            finally:
                # release the views on every path so the segment can
                # close without 'exported pointers exist' GC noise
                idx.close()
                try:
                    shm.close()
                except BufferError:
                    pass
            logger.info("restored step %d from shared memory", step)
            return state
        except (FileNotFoundError, KeyError):
            return None
        except core.RestoreMismatchError:
            raise  # tree-contract violation: load() decides the fate
        except Exception:  # noqa: BLE001
            logger.warning("memory restore failed", exc_info=True)
            return None

    def _load_from_replica(self, target, shardings, step, partial=False):
        """Local shm lost (host replaced): pull our pack from a ring peer.

        Reference: engine.py:349 _restore_memory_from_replica.
        """
        if self._replica is None:
            return None
        try:
            if step is None and self._client is not None:
                # pin to the cluster-consistent step: a peer may hold a step
                # the other ranks skipped ("saver busy"), and restoring it
                # would silently diverge this rank from the rest
                min_step = self._client.get_min_ckpt_step()
                if min_step > 0:
                    step = min_step
            # one dead/corrupt donor must not abort the tier: exclude the
            # failing holder and ask the next ring peer for the same pack
            tried: set = set()
            while True:
                hit = self._replica.fetch(
                    step=step, exclude=tuple(tried), with_holder=True
                )
                if hit is None:
                    return None
                got_step, pack, holder = hit
                try:
                    idx = core.PackIndex()
                    idx.add_pack(memoryview(pack))
                    state = core.restore_tree(
                        target, idx, shardings, partial=partial
                    )
                except core.RestoreMismatchError:
                    raise  # tree-contract violation: load() decides the fate
                except Exception:  # noqa: BLE001
                    logger.warning(
                        "replica restore from holder rank %d failed; "
                        "trying next peer",
                        holder,
                        exc_info=True,
                    )
                    tried.add(holder)
                    continue
                logger.info(
                    "restored step %d from peer replica (holder rank %d)",
                    got_step,
                    holder,
                )
                return state
        except core.RestoreMismatchError:
            raise  # tree-contract violation: load() decides the fate
        except Exception:  # noqa: BLE001
            logger.warning("replica restore failed", exc_info=True)
            return None

    def load_from_storage(self, target, shardings=None, step=None, partial=False):
        from dlrover_tpu.checkpoint.storage import read_tracker

        step = step if step is not None else read_tracker(
            self.ckpt_dir, self._storage
        )
        if step is None:
            return None
        step_dir = os.path.join(self.ckpt_dir, f"step_{step}")
        idx = core.PackIndex()
        packs = [
            f
            for f in self._storage.listdir(step_dir)
            if f.endswith(".pack")
        ]
        if not packs:
            return None
        for name in packs:
            mv = self._storage.mmap(os.path.join(step_dir, name))
            idx.add_pack(mv)
        state = core.restore_tree(target, idx, shardings, partial=partial)
        logger.info("restored step %d from %s", step, step_dir)
        return state

    # ---- helpers ---------------------------------------------------------

    def _acquire(self, blocking=True) -> bool:
        return self._lock.acquire(blocking=blocking)

    def _release(self):
        self._lock.release()


def _round_up(n: int, unit: int = 1 << 20) -> int:
    return (n + unit - 1) // unit * unit
