"""Orbax interoperability for Flash Checkpoint.

The native format (core.py packs) is built for elastic restore speed:
shm-stageable, resharding-capable, one buffer per host. Orbax/TensorStore
is the JAX ecosystem's interchange format — this adapter converts both
ways so checkpoints flow to/from maxtext-style pipelines, model hubs, and
long-term storage (SURVEY.md §7: "TensorStore/OCDBT as the storage
backend (Orbax-compatible layout)").
"""

from typing import Any, Optional

import jax

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_orbax(path: str, state: Any) -> None:
    """Write a state pytree as an Orbax checkpoint directory."""
    _checkpointer().save(path, state)
    logger.info("wrote orbax checkpoint at %s", path)


def load_orbax(
    path: str,
    target: Optional[Any] = None,
    shardings: Optional[Any] = None,
) -> Any:
    """Read an Orbax checkpoint; optional target/shardings for restore
    onto a mesh (resharded restore works the same as the native path)."""
    import orbax.checkpoint as ocp

    ckptr = _checkpointer()
    if target is None:
        return ckptr.restore(path)
    if shardings is not None:
        args = jax.tree.map(
            lambda t, s: ocp.ArrayRestoreArgs(
                sharding=s, global_shape=t.shape, dtype=t.dtype
            ),
            target,
            shardings,
        )
        return ckptr.restore(path, restore_args=args)
    return ckptr.restore(path, item=target)


def pack_to_orbax(
    ckpt_dir: str,
    out_path: str,
    target: Any,
    step: Optional[int] = None,
    shardings: Optional[Any] = None,
) -> int:
    """Convert a committed native checkpoint into an Orbax directory.

    ``target`` provides the pytree structure (state_template of the live
    state). Returns the step converted.
    """
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.storage import PosixStorage, read_tracker

    engine = CheckpointEngine(ckpt_dir, use_agent=False)
    if step is None:
        step = read_tracker(ckpt_dir, PosixStorage())
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {ckpt_dir}"
            )
    state = engine.load_from_storage(target, shardings=shardings, step=step)
    if state is None:
        raise FileNotFoundError(
            f"no committed checkpoint under {ckpt_dir}"
        )
    save_orbax(out_path, state)
    return step


def orbax_to_pack(
    orbax_path: str,
    ckpt_dir: str,
    step: int,
    target: Optional[Any] = None,
    shardings: Optional[Any] = None,
) -> None:
    """Import an Orbax checkpoint into the native pack format (so an
    externally-produced model can enter the flash-checkpoint flow)."""
    from dlrover_tpu.checkpoint import core
    from dlrover_tpu.checkpoint.saver import persist_pack
    from dlrover_tpu.checkpoint.storage import PosixStorage

    state = load_orbax(orbax_path, target=target, shardings=shardings)
    entries, payload = core.plan_pack(state)
    header = core.header_bytes(step, entries, {"dir": ckpt_dir})
    buf = memoryview(bytearray(core.pack_size(header, payload)))
    used = core.write_pack(buf, step, state, entries, header=header)
    persist_pack(
        buf[:used],
        ckpt_dir,
        step,
        jax.process_index(),
        jax.process_count(),
        PosixStorage(),
    )
    logger.info("imported orbax checkpoint → %s step %d", ckpt_dir, step)
