"""User-facing Flash Checkpoint API.

Reference: trainer/torch/flash_checkpoint/checkpointer.py:18 —
``save_checkpoint(step, state, path, storage_type=MEMORY|DISK)`` — plus the
per-framework subclasses (ddp.py/fsdp.py/megatron.py). One class suffices
here: state is any pytree of (sharded) jax arrays, and the pack format is
sharding-aware, so DDP/FSDP/TP layouts are all "the same checkpoint".
"""

import os
from typing import Any, Optional

import jax

from dlrover_tpu.common.constants import CheckpointStorageType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.storage import read_tracker

logger = get_logger(__name__)


class StorageType:
    MEMORY = CheckpointStorageType.MEMORY
    DISK = CheckpointStorageType.DISK


class Checkpointer:
    def __init__(
        self,
        ckpt_dir: str,
        master_client=None,
        use_agent: Optional[bool] = None,
        replicate: bool = False,
        replica_config=None,
    ):
        self.ckpt_dir = ckpt_dir
        replica = None
        if replicate and jax.process_count() > 1:
            if master_client is None:
                # without the KV store there is no peer discovery: the
                # manager would silently replicate nothing
                raise ValueError(
                    "replicate=True requires a master_client for peer "
                    "discovery; pass one or construct the ReplicaManager "
                    "with an explicit peers map"
                )
            from dlrover_tpu.checkpoint.replica import ReplicaManager

            # peers resolve through the master KV store at first backup
            replica = ReplicaManager(
                jax.process_index(),
                jax.process_count(),
                master_client=master_client,
                config=replica_config,
            )
        self.engine = CheckpointEngine(
            ckpt_dir,
            master_client=master_client,
            use_agent=use_agent,
            replica=replica,
        )

    def save_checkpoint(
        self,
        step: int,
        state: Any,
        storage_type: str = StorageType.DISK,
    ) -> bool:
        """Stage to memory; DISK additionally persists asynchronously."""
        if storage_type == StorageType.MEMORY:
            return self.engine.save_to_memory(step, state)
        return self.engine.save_to_storage(step, state)

    def load_checkpoint(
        self,
        target: Any,
        shardings: Any = None,
        step: Optional[int] = None,
        partial: bool = False,
    ) -> Optional[Any]:
        """Restore into ``target``'s structure; shm-first, storage fallback.

        ``shardings`` may describe a *different* mesh than the one the
        checkpoint was saved under — the pack format reshard-restores.

        ``partial=True``: leaves missing from the checkpoint keep the
        target's values — pass the LIVE freshly-initialized state (not
        a template) as ``target``. This is the state-tree-upgrade path:
        e.g. resuming a pre-round-4 fp8 checkpoint whose state lacks
        the attention-projection amax histories re-initializes just
        those (they re-warm within AMAX_HISTORY steps) instead of
        failing the whole restore.
        """
        return self.engine.load(
            target, shardings=shardings, step=step, partial=partial
        )

    def latest_committed_step(self) -> Optional[int]:
        return read_tracker(self.ckpt_dir, self.engine._storage)

    def wait_for_persist(self, timeout: float = 300.0):
        self.engine.wait_for_persist(timeout)


def state_template(state: Any) -> Any:
    """Abstract (shape, dtype) template of a live state pytree."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )
