from dlrover_tpu.checkpoint.checkpointer import (  # noqa: F401
    Checkpointer,
    StorageType,
)
from dlrover_tpu.checkpoint.replica import (  # noqa: F401
    ReplicaConfig,
    ReplicaManager,
)
