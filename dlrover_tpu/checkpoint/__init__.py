from dlrover_tpu.checkpoint.checkpointer import (  # noqa: F401
    Checkpointer,
    StorageType,
)
