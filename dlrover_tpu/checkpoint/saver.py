"""Agent-side async checkpoint saver daemon.

Reference: AsyncCheckpointSaver (elastic_agent/torch/ckpt_saver.py:345-763):
a daemon in the agent process consuming checkpoint events from the worker,
persisting shared-memory packs to storage, committing with done-files +
tracker, and doing an emergency persist on worker failure or SIGTERM.
"""

import os
import signal
import threading
import time
from typing import Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedQueue,
    attach_shared_memory,
)
from dlrover_tpu.checkpoint.storage import (
    CheckpointStorage,
    DeletionStrategy,
    PosixStorage,
    write_tracker,
)
from dlrover_tpu.observability import telemetry
from dlrover_tpu.observability.tracing import get_tracer

logger = get_logger(__name__)


def persist_pack(
    buf: memoryview,
    ckpt_dir: str,
    step: int,
    process_index: int,
    process_count: int,
    storage: CheckpointStorage,
):
    """Write one host's pack + done marker; commit tracker when all done.

    Commit protocol (reference: ckpt_saver.py:864 commit_checkpoint): every
    host writes ``host_i.pack`` then ``done/host_i.done`` into the step dir
    on the shared filesystem; whichever host observes the full done set
    writes the tracker file. Idempotent across hosts.
    """
    span = get_tracer().span("ckpt.persist", step=step, nbytes=len(buf))
    with span:
        step_dir = os.path.join(ckpt_dir, f"step_{step}")
        storage.makedirs(step_dir)
        storage.write_bytes(
            buf, os.path.join(step_dir, f"host_{process_index}.pack")
        )
        done_dir = os.path.join(step_dir, "done")
        storage.makedirs(done_dir)
        storage.write_bytes(
            memoryview(b"1"),
            os.path.join(done_dir, f"host_{process_index}.done"),
        )
        done = len(
            [f for f in storage.listdir(done_dir) if f.endswith(".done")]
        )
        committed = done >= process_count
        if committed:
            write_tracker(ckpt_dir, step, storage)
            logger.info("committed checkpoint step %d (%d hosts)", step, done)
    hub = telemetry.get_hub()
    if hub.enabled:
        hub.publish(
            telemetry.CheckpointRecord(
                kind="persist",
                step=step,
                seconds=span.end(),
                nbytes=len(buf),
                tier="storage",
            )
        )


class AsyncCheckpointSaver:
    """Singleton daemon owning the ckpt IPC endpoints in the agent."""

    _instance: Optional["AsyncCheckpointSaver"] = None
    _lock = threading.Lock()

    def __init__(self, storage: Optional[CheckpointStorage] = None):
        self.storage = storage or PosixStorage()
        self.queue = SharedQueue("ckpt")
        self.meta = SharedDict("ckpt_meta")
        self.shm_lock = SharedLock("ckpt")
        self.deletion_strategy: Optional[DeletionStrategy] = None
        self._stop = threading.Event()
        self._last_persisted_step = -1
        self._thread = threading.Thread(
            target=self._persist_loop, name="ckpt-saver", daemon=True
        )
        self._thread.start()
        self._install_signal_handler()

    # ---- lifecycle -------------------------------------------------------

    @classmethod
    def start_async_saving_ckpt(cls) -> "AsyncCheckpointSaver":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
        return cls._instance

    @classmethod
    def get(cls) -> Optional["AsyncCheckpointSaver"]:
        return cls._instance

    def close(self):
        self._stop.set()
        self.queue.close()
        self.meta.close()
        self.shm_lock.close()
        with AsyncCheckpointSaver._lock:
            if AsyncCheckpointSaver._instance is self:
                AsyncCheckpointSaver._instance = None

    def _install_signal_handler(self):
        if threading.current_thread() is not threading.main_thread():
            return
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            logger.info("SIGTERM: persisting staged checkpoint before exit")
            try:
                self.save_shm_to_storage()
            finally:
                if callable(prev):
                    prev(signum, frame)

        try:
            signal.signal(signal.SIGTERM, handler)
        except ValueError:
            pass

    # ---- persist ---------------------------------------------------------

    def _persist_loop(self):
        while not self._stop.is_set():
            event = self.queue.get(timeout=1.0)
            if not event:
                continue
            if event.get("type") == "persist":
                try:
                    self._persist_latest()
                except Exception:  # noqa: BLE001
                    logger.exception("async persist failed")

    def _persist_latest(self) -> bool:
        meta = self.meta.get("latest")
        if not meta:
            return False
        step = meta["step"]
        if step <= self._last_persisted_step:
            return False
        # lock out the worker from re-staging while we read the segment
        self.shm_lock.acquire(owner="saver")
        try:
            shm = attach_shared_memory(meta["shm"])
            try:
                persist_pack(
                    memoryview(shm.buf)[: meta["used"]],
                    meta["dir"],
                    step,
                    meta["process_index"],
                    meta["process_count"],
                    self.storage,
                )
            finally:
                shm.close()
        finally:
            self.shm_lock.release(owner="saver")
        self._last_persisted_step = step
        if self.deletion_strategy is not None:
            try:
                self.deletion_strategy.clean_up(meta["dir"], self.storage)
            except Exception:  # noqa: BLE001
                logger.warning("checkpoint cleanup failed", exc_info=True)
        return True

    def save_shm_to_storage(self):
        """Emergency persist (worker died / SIGTERM / membership change)."""
        if self._persist_latest():
            logger.info("emergency checkpoint persist done")

    def wait_idle(self, timeout: float = 60.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            meta = self.meta.get("latest")
            if not meta or meta["step"] <= self._last_persisted_step:
                return
            time.sleep(0.05)
