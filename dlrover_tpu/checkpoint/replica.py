"""Cross-node in-memory checkpoint redundancy.

Reference: dlrover/python/trainer/torch/flash_checkpoint replica.py
(CkptReplicaManger:28, ShardCkptReplicaManager:73, FullCkptReplicaManager:245)
— each node backs up its staged in-memory checkpoint shard to a peer node, so
that when a node dies and its shared memory is lost, the relaunched
replacement restores the shard from the peer's RAM instead of falling back to
(slow) persistent storage.

TPU-native design: checkpoint staging is a *host-side* concern (the pack
bytes already live in host shared memory, see core.py), so replication is
plain host networking — a small TCP service in each agent holding the latest
pack per source rank, and a ring backup scheme (rank i backs up to
(i+1) mod n, fetches from any peer that has its rank). No device collectives
are spent on redundancy, unlike the reference's process-group broadcast
(replica.py:118) which burns NCCL bandwidth mid-training.

Peer discovery rides the master KV store (MasterClient.kv_store_set/get),
the same channel the reference uses to bootstrap process groups.
"""

import json
import os
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import attach_shared_memory

logger = get_logger(__name__)

_LEN_BYTES = 8
_CHUNK = 16 << 20
_KV_PREFIX = "ckpt_replica_addr_"


def _default_advertise_host() -> str:
    """Best-effort routable address for this host.

    ``gethostbyname(gethostname())`` resolves to 127.0.1.1 on stock
    Debian/Ubuntu (or raises), which would make every rank advertise
    loopback and silently void cross-node replication — so prefer the
    kernel's outbound-route source address.
    """
    env = os.environ.get("DLROVER_TPU_REPLICA_HOST")
    if env:
        return env
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packets sent
            return s.getsockname()[0]
    except OSError:
        pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _send_frame(sock: socket.socket, header: Dict, payload=None):
    raw = json.dumps(header).encode("utf-8")
    sock.sendall(len(raw).to_bytes(_LEN_BYTES, "little"))
    sock.sendall(raw)
    if payload is not None:
        mv = memoryview(payload)
        for lo in range(0, len(mv), _CHUNK):
            sock.sendall(mv[lo : lo + _CHUNK])


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    from dlrover_tpu.common.sockets import recv_exact

    return recv_exact(sock, n)


def _recv_header(sock: socket.socket) -> Dict:
    n = int.from_bytes(_recv_exact(sock, _LEN_BYTES), "little")
    if n > (1 << 20):
        raise ValueError(f"oversize frame header ({n} bytes)")
    return json.loads(bytes(_recv_exact(sock, n)))


def _recv_payload(
    sock: socket.socket, header: Dict, max_bytes: Optional[int] = None
) -> Optional[bytearray]:
    size = int(header.get("size", 0))
    if max_bytes is not None and size > max_bytes:
        # reject before allocating an attacker-controlled buffer
        raise ValueError(f"oversize payload ({size} > {max_bytes})")
    return _recv_exact(sock, size) if size else None


def _recv_frame(
    sock: socket.socket, max_bytes: Optional[int] = None
) -> Tuple[Dict, Optional[bytearray]]:
    header = _recv_header(sock)
    return header, _recv_payload(sock, header, max_bytes)


_MAX_STEP = 1 << 40


class _ReplicaStore:
    """Latest pack per source rank, with a byte budget."""

    def __init__(self, max_bytes: int):
        self._lock = threading.Lock()
        # src -> (step, pack); pack is any bytes-like, stored un-copied
        self._packs: Dict[int, Tuple[int, bytes]] = {}
        self._max_bytes = max_bytes

    def put(self, src: int, step: int, pack) -> bool:
        with self._lock:
            cur = self._packs.get(src)
            if cur and cur[0] >= step:
                return True  # stale resend
            other = sum(
                len(p) for s, (_, p) in self._packs.items() if s != src
            )
            if other + len(pack) > self._max_bytes:
                logger.warning(
                    "replica store over budget (%d + %d > %d), dropping "
                    "backup from rank %d",
                    other,
                    len(pack),
                    self._max_bytes,
                    src,
                )
                return False
            self._packs[src] = (step, pack)
            return True

    def get(self, src: int) -> Optional[Tuple[int, bytes]]:
        with self._lock:
            return self._packs.get(src)

    def steps(self) -> Dict[int, int]:
        with self._lock:
            return {s: step for s, (step, _) in self._packs.items()}

    def drop(self, src: int):
        with self._lock:
            self._packs.pop(src, None)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        store: _ReplicaStore = self.server.store  # type: ignore[attr-defined]
        token = self.server.token  # type: ignore[attr-defined]
        max_bytes = self.server.max_frame_bytes  # type: ignore[attr-defined]
        # authenticate BEFORE parsing any frame (shared preamble,
        # common/sockets.py): an unauthenticated 'put' must not be able
        # to force a multi-GB allocation, and the reject is silent —
        # closing without answering, same as every other data plane
        from dlrover_tpu.common.sockets import check_auth

        if not check_auth(self.request, token):
            return
        try:
            header = _recv_header(self.request)
            payload = _recv_payload(self.request, header, max_bytes)
        except (ConnectionError, json.JSONDecodeError, OSError, ValueError):
            return
        op = header.get("op")
        if op == "put":
            step = int(header["step"])
            if not (0 <= step < _MAX_STEP):
                _send_frame(self.request, {"ok": False, "error": "bad step"})
                return
            # payload (a bytearray) is stored as-is; a bytes() copy here
            # would transiently double host RAM for multi-GB packs
            ok = store.put(int(header["src"]), step, payload or bytearray())
            _send_frame(self.request, {"ok": ok})
        elif op == "get":
            hit = store.get(int(header["src"]))
            if hit is None:
                _send_frame(self.request, {"ok": False, "size": 0})
            else:
                step, pack = hit
                _send_frame(
                    self.request,
                    {"ok": True, "step": step, "size": len(pack)},
                    pack,
                )
        elif op == "steps":
            # JSON coerces int keys to strings; receiver decodes back
            _send_frame(self.request, {"ok": True, "steps": store.steps()})
        else:
            _send_frame(self.request, {"ok": False, "error": "bad op"})


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def _default_token() -> str:
    # every host of a run shares RUN_ID, so it doubles as a wire token
    # keeping strays (other runs, port scanners) out of the store (the
    # shared helper grew out of this: common/sockets.default_token)
    from dlrover_tpu.common.sockets import default_token

    return default_token()


@dataclass
class ReplicaConfig:
    """num_replicas: how many ring successors receive a copy (0 disables)."""

    num_replicas: int = 1
    bind_host: str = "0.0.0.0"
    advertise_host: str = field(default_factory=_default_advertise_host)
    port: int = 0  # 0 → ephemeral
    max_store_bytes: int = 8 << 30
    timeout: float = 60.0
    token: str = field(default_factory=_default_token)


class ReplicaManager:
    """Ring backup of staged checkpoint packs across hosts.

    ``peers`` maps node rank → "host:port" and may be given directly (tests,
    static clusters) or resolved lazily through the master KV store.
    """

    def __init__(
        self,
        process_index: int,
        process_count: int,
        peers: Optional[Dict[int, str]] = None,
        master_client=None,
        config: Optional[ReplicaConfig] = None,
    ):
        self.process_index = process_index
        self.process_count = process_count
        self.config = config or ReplicaConfig()
        self._peers = dict(peers or {})
        self._client = master_client
        self._store = _ReplicaStore(self.config.max_store_bytes)
        self._server = _Server(
            (self.config.bind_host, self.config.port), _Handler
        )
        self._server.store = self._store  # type: ignore[attr-defined]
        self._server.token = self.config.token  # type: ignore[attr-defined]
        self._server.max_frame_bytes = (  # type: ignore[attr-defined]
            self.config.max_store_bytes
        )
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="ckpt-replica",
            daemon=True,
        )
        self._thread.start()
        self._backup_thread: Optional[threading.Thread] = None
        self.register()

    # ---- discovery -------------------------------------------------------

    @property
    def addr(self) -> str:
        port = self._server.server_address[1]
        return f"{self.config.advertise_host}:{port}"

    def register(self):
        if self._client is None:
            return
        try:
            self._client.kv_store_set(
                f"{_KV_PREFIX}{self.process_index}", self.addr
            )
        except Exception:  # noqa: BLE001
            logger.warning("replica addr registration failed", exc_info=True)

    def _resolve(self, rank: int) -> Optional[str]:
        if rank in self._peers:
            return self._peers[rank]
        if self._client is None:
            return None
        try:
            addr = self._client.kv_store_get(f"{_KV_PREFIX}{rank}")
        except Exception:  # noqa: BLE001
            return None
        if addr:
            self._peers[rank] = addr
            return addr
        return None

    def _backup_targets(self):
        n = self.process_count
        r = min(self.config.num_replicas, n - 1)
        return [(self.process_index + i) % n for i in range(1, r + 1)]

    # ---- backup (sender side) --------------------------------------------

    def backup(self, meta: Dict, shm_lock=None) -> int:
        """Send this host's staged pack to its ring successors.

        ``meta`` is the engine's staging record ({shm, used, step}). One
        host copy of the pack is made under ``shm_lock`` (the engine's
        staging lock) so the slow network sends happen lock-free; the pack
        header's step is re-checked under the lock, so if the worker
        restaged a newer step before we got the lock, this (stale) backup
        aborts and the newer step's own backup supersedes it. Returns the
        number of peers updated.
        """
        from dlrover_tpu.checkpoint import core

        targets = self._backup_targets()
        if not targets:
            return 0
        # re-register each backup: one cheap KV set, and it heals a missed
        # registration (master briefly unreachable during our own relaunch)
        self.register()
        if shm_lock is not None and not shm_lock.acquire(blocking=True):
            return 0
        try:
            shm = attach_shared_memory(meta["shm"])
            try:
                view = memoryview(shm.buf)
                staged_step = core.read_header(view).get("step")
                if staged_step != meta["step"]:
                    logger.info(
                        "skipping replica backup of step %s: shm now holds "
                        "step %s",
                        meta["step"],
                        staged_step,
                    )
                    return 0
                pack = bytes(view[: meta["used"]])
            finally:
                del view
                shm.close()
        except FileNotFoundError:
            return 0
        finally:
            if shm_lock is not None:
                shm_lock.release()
        sent = 0
        for rank in targets:
            addr = self._resolve(rank)
            if addr is None:
                logger.warning("no replica addr for rank %d", rank)
                continue
            if self._put(addr, meta["step"], pack):
                sent += 1
        return sent

    def backup_async(self, meta: Dict, shm_lock=None):
        """Schedule a backup without ever blocking the caller.

        If the previous send is still in flight (slow or dead peer), this
        step's backup is skipped — the next checkpoint retries, and the
        stale-step guard in backup() keeps skipped steps from being
        mislabeled. Joining here would put a hung peer's 60s socket
        timeout on the training critical path.
        """
        if self._backup_thread and self._backup_thread.is_alive():
            logger.warning(
                "replica backup of step %s skipped: previous backup still "
                "in flight",
                meta.get("step"),
            )
            return
        self._backup_thread = threading.Thread(
            target=self._safe_backup, args=(meta, shm_lock), daemon=True
        )
        self._backup_thread.start()

    def _safe_backup(self, meta, shm_lock):
        try:
            self.backup(meta, shm_lock)
        except Exception:  # noqa: BLE001
            logger.warning("checkpoint replica backup failed", exc_info=True)

    def wait_backup(self, timeout: float = 120.0):
        if self._backup_thread:
            self._backup_thread.join(timeout)

    def _put(self, addr: str, step: int, pack: bytes) -> bool:
        try:
            with self._connect(addr) as sock:
                _send_frame(
                    sock,
                    {
                        "op": "put",
                        "src": self.process_index,
                        "step": step,
                        "size": len(pack),
                    },
                    pack,
                )
                resp, _ = _recv_frame(sock)
                return bool(resp.get("ok"))
        except OSError:
            logger.warning("replica backup to %s failed", addr, exc_info=True)
            self._forget(addr)
            return False

    def _forget(self, addr: str):
        """Drop a dead peer address so the next call re-resolves it.

        A relaunched peer binds a fresh ephemeral port and re-registers in
        the master KV store; without invalidation we would dial the stale
        addr forever. Static peer maps (no KV client) are kept — there is
        nothing to re-resolve from.
        """
        if self._client is None:
            return
        for rank, a in list(self._peers.items()):
            if a == addr:
                del self._peers[rank]

    # ---- restore (fetch side) --------------------------------------------

    def fetch(
        self,
        src: Optional[int] = None,
        step: Optional[int] = None,
        exclude: Tuple[int, ...] = (),
        with_holder: bool = False,
    ):
        """Recover rank ``src``'s pack from whichever ring peer holds it.

        The holders of rank i's pack are its ring successors, so a replaced
        host asks the nodes that rank i backed up onto. ``exclude`` skips
        holder ranks that already failed a restore attempt (the caller's
        next-peer retry); ``with_holder=True`` returns
        (step, pack bytes, holder_rank) instead of (step, pack bytes).
        Returns None when no usable holder remains.
        """
        src = self.process_index if src is None else src
        n = self.process_count
        r = min(self.config.num_replicas, n - 1)
        holders = [(src + i) % n for i in range(1, r + 1)]
        skip = frozenset(exclude)
        for rank in holders:
            if rank in skip:
                continue
            if rank == self.process_index:
                hit = self._store.get(src)
            else:
                addr = self._resolve(rank)
                if addr is None:
                    continue
                hit = self._get(addr, src)
            if hit is None:
                continue
            got_step, pack = hit
            if step is not None and got_step != step:
                continue
            logger.info(
                "recovered rank %d step %d pack (%.1f MB) from peer rank %d",
                src,
                got_step,
                len(pack) / 1e6,
                rank,
            )
            if with_holder:
                return got_step, pack, rank
            return got_step, pack
        return None

    def peer_steps(self, rank: int) -> Dict[int, int]:
        """{src: step} held by ``rank``'s store (diagnosis/monitoring)."""
        addr = self._resolve(rank)
        if addr is None:
            return {}
        try:
            with self._connect(addr) as sock:
                _send_frame(sock, {"op": "steps"})
                resp, _ = _recv_frame(sock)
                return {int(k): int(v) for k, v in resp.get("steps", {}).items()}
        except OSError:
            return {}

    def _get(self, addr: str, src: int) -> Optional[Tuple[int, bytes]]:
        try:
            with self._connect(addr) as sock:
                _send_frame(sock, {"op": "get", "src": src})
                resp, payload = _recv_frame(sock)
                if not resp.get("ok"):
                    return None
                return int(resp["step"]), payload or bytearray()
        except OSError:
            self._forget(addr)
            return None

    def _connect(self, addr: str) -> socket.socket:
        from dlrover_tpu.common.sockets import send_auth

        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection(
            (host, int(port)), timeout=self.config.timeout
        )
        # every connection on this plane speaks the shared auth preamble
        # before its first frame (common/sockets.py)
        send_auth(sock, self.config.token)
        return sock

    # ---- lifecycle -------------------------------------------------------

    def local_steps(self) -> Dict[int, int]:
        """Steps of packs this node holds for others (for tests/diagnosis)."""
        return self._store.steps()

    def close(self):
        self.wait_backup(timeout=5.0)
        self._server.shutdown()
        self._server.server_close()


def wait_peer_steps(
    manager: ReplicaManager, want: Dict[int, int], timeout: float = 30.0
) -> bool:
    """Block until this node's store holds at least ``want`` {src: step}."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        have = manager.local_steps()
        if all(have.get(s, -1) >= st for s, st in want.items()):
            return True
        time.sleep(0.02)
    return False
