"""Coworker data plane: CPU preprocessing processes → shm batch ring.

Reference: atorch's coworker subsystem — `data/shm_context.py:139`
(shared-memory tensor channel between preprocessing pods and trainers),
`service/coworker_data_service.py:43` (gRPC data plane) and
`data/shm_dataloader.py`. TPU framing: the host CPUs of a TPU VM are the
coworkers; N producer processes run the user's batch iterator and write
packed batches into a fixed-slot POSIX shared-memory ring, and the
training process drains the ring, overlapping host preprocessing with
device steps without the GIL or per-batch pickling through a pipe.

Control rides the framework's unix-socket SharedQueues (free-slot and
ready-slot queues); bulk bytes ride one shm segment, so a batch is
copied exactly once on each side.
"""

import io
import multiprocessing as mp
import os
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import (
    SharedQueue,
    SharedQueueClient,
    attach_shared_memory,
    create_shared_memory,
)

logger = get_logger(__name__)

_DONE = "__coworker_done__"


def _pack(batch: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in batch.items()})
    return buf.getvalue()


def _unpack(raw: memoryview) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(bytes(raw))) as z:
        # copy out: the shm slot is recycled as soon as we return
        return {k: np.array(z[k]) for k in z.files}


class BatchRing:
    """Fixed-slot shm ring. Create server-side once; attach elsewhere."""

    def __init__(
        self,
        name: str = "coworker",
        slots: int = 8,
        slot_bytes: int = 16 << 20,
        create: bool = False,
    ):
        self.name = name
        self.slots = slots
        self.slot_bytes = slot_bytes
        self._creator = create
        # run-id-scoped like the control sockets: two jobs sharing a host
        # (and the default ring name) must not map the same segment
        run_id = os.environ.get("DLROVER_TPU_RUN_ID", "default")
        shm_name = f"dlrover_tpu_ring_{run_id}_{name}"
        if create:
            self._shm = create_shared_memory(shm_name, slots * slot_bytes)
            self._free: Any = SharedQueue(f"{name}_free")
            self._ready: Any = SharedQueue(f"{name}_ready")
            for i in range(slots):
                self._free.put(i)
        else:
            self._shm = attach_shared_memory(shm_name)
            self._free = SharedQueueClient(f"{name}_free")
            self._ready = SharedQueueClient(f"{name}_ready")

    # ---- producer side ---------------------------------------------------

    def put(self, batch: Dict[str, np.ndarray], timeout: float = 60.0):
        self.put_bytes(_pack(batch), timeout=timeout)

    def put_bytes(self, raw: bytes, timeout: float = 60.0):
        """Deposit an already-packed batch (the TCP ingress path)."""
        if len(raw) > self.slot_bytes:
            raise ValueError(
                f"batch packs to {len(raw)} bytes > slot_bytes="
                f"{self.slot_bytes}; raise slot_bytes"
            )
        slot = self._wait(self._free, timeout)
        if slot is None:
            raise TimeoutError("no free slot (consumer stalled?)")
        lo = slot * self.slot_bytes
        self._shm.buf[lo : lo + len(raw)] = raw
        self._ready.put({"slot": slot, "used": len(raw)})

    def mark_done(self):
        self._ready.put(_DONE)

    # ---- consumer side ---------------------------------------------------

    def get(self, timeout: float = 60.0) -> Optional[Dict[str, np.ndarray]]:
        """Next batch, or None on a producer-done marker."""
        item = self._wait(self._ready, timeout)
        if item is None:
            raise TimeoutError("no ready batch (producers stalled?)")
        if item == _DONE:
            return None
        slot, used = item["slot"], item["used"]
        lo = slot * self.slot_bytes
        batch = _unpack(self._shm.buf[lo : lo + used])
        self._free.put(slot)
        return batch

    @staticmethod
    def _wait(queue, timeout: float):
        deadline = time.time() + timeout
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                return None
            item = queue.get(timeout=min(remaining, 1.0))
            if item is not None:
                return item

    def close(self):
        self._shm.close()
        if self._creator:
            # reclaim /dev/shm: the segments are resource-tracker-exempt,
            # so nothing else ever unlinks them
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
        for q in (self._free, self._ready):
            if isinstance(q, SharedQueue):
                q.close()


def _producer_main(
    name: str,
    slots: int,
    slot_bytes: int,
    worker_id: int,
    num_workers: int,
    producer_fn,
):
    # geometry must match the creator's: slot offsets are slot_bytes-strided
    ring = BatchRing(name, slots=slots, slot_bytes=slot_bytes, create=False)
    try:
        for batch in producer_fn(worker_id, num_workers):
            ring.put(batch)
    except Exception:  # noqa: BLE001
        logger.exception("coworker %d failed", worker_id)
    finally:
        ring.mark_done()


class CoworkerPool:
    """N producer processes feeding one shm ring.

    ``producer_fn(worker_id, num_workers) -> iterator of batch dicts``
    must be picklable (top-level function); shard your dataset by
    worker_id inside it. The consumer iterates ``batches()`` until every
    producer finished.
    """

    def __init__(
        self,
        producer_fn: Optional[Callable[[int, int], Iterator[Dict]]] = None,
        num_workers: int = 2,
        slots: int = 8,
        slot_bytes: int = 16 << 20,
        name: str = "coworker",
        remote_producers: int = 0,
        listen: bool = False,
        listen_host: str = "0.0.0.0",
        listen_port: int = 0,
    ):
        """``remote_producers``/``listen``: accept that many producers
        from other hosts over TCP (each sends one done marker, exactly
        like a local producer). ``producer_fn=None`` with ``listen=True``
        runs fully network-fed (num_workers is forced to 0)."""
        if producer_fn is None:
            num_workers = 0
        if remote_producers and not listen:
            raise ValueError("remote_producers > 0 requires listen=True")
        self.producer_fn = producer_fn
        self.num_workers = num_workers
        self.remote_producers = remote_producers
        self.name = name
        self.ring = BatchRing(
            name, slots=slots, slot_bytes=slot_bytes, create=True
        )
        self.feed_server: Optional["BatchFeedServer"] = None
        if listen:
            self.feed_server = BatchFeedServer(
                self.ring, host=listen_host, port=listen_port
            )
        self._procs: List[mp.Process] = []

    def start(self):
        ctx = mp.get_context("spawn")
        env_run = os.environ.get("DLROVER_TPU_RUN_ID")
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=_producer_main,
                args=(
                    self.name,
                    self.ring.slots,
                    self.ring.slot_bytes,
                    wid,
                    self.num_workers,
                    self.producer_fn,
                ),
                daemon=True,
            )
            p.start()
            self._procs.append(p)
        logger.info(
            "coworker pool: %d producers (run=%s)",
            self.num_workers,
            env_run,
        )
        return self

    def batches(self, timeout: float = 120.0) -> Iterator[Dict]:
        done = 0
        total = self.num_workers + self.remote_producers
        while done < total:
            batch = self.ring.get(timeout=timeout)
            if batch is None:
                done += 1
                continue
            yield batch

    def stop(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)
        if self.feed_server is not None:
            self.feed_server.stop()
        self.ring.close()


# ---------------------------------------------------------------------------
# cross-pod data plane (TCP)
# ---------------------------------------------------------------------------
#
# Reference: atorch's coworker gRPC tier — CPU pods run
# service/coworker_data_service.py:43 and trainers discover batches via
# data_info_service.py:32. On TPU pods (few host cores, fat chips) remote
# CPU feeding matters MORE, so the same ring gains a TCP ingress: remote
# producer pools push packed batches into the consumer host's shm ring;
# local producers keep the zero-hop shm path. Backpressure is the ring
# itself — the server acks a put only after a slot was claimed, so a
# fast producer blocks instead of ballooning the consumer's RAM.

import socket as _socket
import socketserver as _socketserver
import struct as _struct
import threading as _threading

_HDR = _struct.Struct("<cq")  # op byte + payload length
_OP_PUT = b"P"
_OP_DONE = b"D"
_OP_ACK = b"A"
_OP_ERR = b"E"


def _net_send(sock, op: bytes, payload: bytes = b""):
    sock.sendall(_HDR.pack(op, len(payload)))
    if payload:
        sock.sendall(payload)


def _net_recv(sock):
    from dlrover_tpu.common.sockets import recv_exact

    try:
        hdr = recv_exact(sock, _HDR.size)
    except ConnectionError:
        return None, None
    op, n = _HDR.unpack(hdr)
    # bound by the shared cap: a garbage length from a stray client is
    # a dead stream (ConnectionError), never an allocation request
    payload = recv_exact(sock, n)
    return op, payload


class BatchFeedServer:
    """Consumer-side TCP ingress depositing remote batches into a ring."""

    def __init__(
        self,
        ring: BatchRing,
        host: str = "0.0.0.0",
        port: int = 0,
        put_timeout: float = 600.0,
        token=None,
    ):
        from dlrover_tpu.common.sockets import default_token

        self.ring = ring
        self.put_timeout = put_timeout
        # this plane ACCEPTS TRAINING DATA: an unauthenticated producer
        # could poison the batch stream — require the run token at
        # connect (common/sockets.py preamble; None = run-id default)
        self._token = default_token() if token is None else token
        outer = self

        class Handler(_socketserver.BaseRequestHandler):
            def handle(self):
                from dlrover_tpu.common.sockets import check_auth

                if not check_auth(self.request, outer._token):
                    return  # close without answering; never mark_done
                saw_put = False
                while True:
                    try:
                        op, payload = _net_recv(self.request)
                    except (ConnectionError, OSError):
                        op = None
                    if op is None:
                        # abnormal disconnect (producer died / network
                        # partition): account its done marker so the
                        # consumer's producer-count still closes. Bare
                        # connect/disconnects (k8s TCP health probes)
                        # never sent a batch and must NOT count — a
                        # producer dying pre-first-put falls to the
                        # consumer's get-timeout backstop instead.
                        if saw_put:
                            outer.ring.mark_done()
                        return
                    if op == _OP_PUT:
                        try:
                            # generous slot wait: a consumer can stall
                            # for minutes (checkpoint persist, eval) —
                            # the TCP credit already bounds memory, so
                            # patience costs nothing
                            outer.ring.put_bytes(
                                bytes(payload), timeout=outer.put_timeout
                            )
                            saw_put = True
                            _net_send(self.request, _OP_ACK)
                        except Exception as e:  # noqa: BLE001
                            logger.exception("feed server put failed")
                            # this producer's stream is over: account
                            # its done marker so the consumer's
                            # producer-count still closes
                            outer.ring.mark_done()
                            try:
                                _net_send(
                                    self.request, _OP_ERR,
                                    str(e).encode()[:512],
                                )
                            except OSError:
                                pass
                            return
                    elif op == _OP_DONE:
                        outer.ring.mark_done()
                        _net_send(self.request, _OP_ACK)
                        return

        class Server(_socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = self._server.server_address
        self._thread = _threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        logger.info("batch feed server on %s:%d", *self.address)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class RemoteBatchWriter:
    """Producer-side client: pack and push batches to a BatchFeedServer.

    One TCP connection, strict put→ack credit: the writer cannot run
    ahead of the consumer's ring (its ack IS the free-slot claim)."""

    def __init__(self, addr, timeout: float = 900.0, token=None):
        from dlrover_tpu.common.sockets import default_token, send_auth

        # must exceed the server's ring-slot wait (put_timeout=600):
        # if the writer gave up first, the server's eventual ack would
        # desync the put/ack credit protocol
        self._sock = _socket.create_connection(addr, timeout=timeout)
        self._sock.settimeout(timeout)
        send_auth(
            self._sock, default_token() if token is None else token
        )

    def put(self, batch: Dict[str, np.ndarray]):
        self.put_bytes(_pack(batch))

    def put_bytes(self, raw: bytes):
        _net_send(self._sock, _OP_PUT, raw)
        op, payload = _net_recv(self._sock)
        if op != _OP_ACK:
            raise RuntimeError(
                f"feed server rejected batch: {bytes(payload or b'')!r}"
            )

    def done(self):
        try:
            _net_send(self._sock, _OP_DONE)
            _net_recv(self._sock)
        except OSError:
            # server already closed this stream (it then accounts the
            # done marker itself on the error path)
            pass
        finally:
            self._sock.close()


def _remote_producer_main(addr, worker_id, num_workers, producer_fn):
    writer = None
    try:
        # connect with retries: the feed server may come up after the
        # producer pool (e.g. trainer restarting). If every attempt
        # fails no marker can reach the consumer at all — batches()
        # then ends via its get-timeout backstop.
        for attempt in range(5):
            try:
                writer = RemoteBatchWriter(addr)
                break
            except OSError:
                if attempt == 4:
                    raise
                time.sleep(2.0 * (attempt + 1))
        for batch in producer_fn(worker_id, num_workers):
            writer.put(batch)
    except Exception:  # noqa: BLE001
        logger.exception("remote coworker %d failed", worker_id)
    finally:
        if writer is not None:
            writer.done()


class RemoteProducerPool:
    """N producer processes on a CPU host feeding a remote trainer.

    The cross-pod counterpart of CoworkerPool: run this on machines
    without chips, point it at the trainer's ``BatchFeedServer``
    address. The trainer counts each remote producer toward its
    done-marker total via ``CoworkerPool(remote_producers=...)``."""

    def __init__(
        self,
        addr,
        producer_fn: Callable[[int, int], Iterator[Dict]],
        num_workers: int = 2,
    ):
        self.addr = tuple(addr)
        self.producer_fn = producer_fn
        self.num_workers = num_workers
        self._procs: List[mp.Process] = []

    def start(self):
        ctx = mp.get_context("spawn")
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=_remote_producer_main,
                args=(self.addr, wid, self.num_workers, self.producer_fn),
                daemon=True,
            )
            p.start()
            self._procs.append(p)
        return self

    def join(self, timeout: float = 300.0):
        deadline = time.time() + timeout
        for p in self._procs:
            p.join(timeout=max(0.1, deadline - time.time()))

    def stop(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)
