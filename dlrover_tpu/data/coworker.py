"""Coworker data plane: CPU preprocessing processes → shm batch ring.

Reference: atorch's coworker subsystem — `data/shm_context.py:139`
(shared-memory tensor channel between preprocessing pods and trainers),
`service/coworker_data_service.py:43` (gRPC data plane) and
`data/shm_dataloader.py`. TPU framing: the host CPUs of a TPU VM are the
coworkers; N producer processes run the user's batch iterator and write
packed batches into a fixed-slot POSIX shared-memory ring, and the
training process drains the ring, overlapping host preprocessing with
device steps without the GIL or per-batch pickling through a pipe.

Control rides the framework's unix-socket SharedQueues (free-slot and
ready-slot queues); bulk bytes ride one shm segment, so a batch is
copied exactly once on each side.
"""

import io
import multiprocessing as mp
import os
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import (
    SharedQueue,
    SharedQueueClient,
    attach_shared_memory,
    create_shared_memory,
)

logger = get_logger(__name__)

_DONE = "__coworker_done__"


def _pack(batch: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in batch.items()})
    return buf.getvalue()


def _unpack(raw: memoryview) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(bytes(raw))) as z:
        # copy out: the shm slot is recycled as soon as we return
        return {k: np.array(z[k]) for k in z.files}


class BatchRing:
    """Fixed-slot shm ring. Create server-side once; attach elsewhere."""

    def __init__(
        self,
        name: str = "coworker",
        slots: int = 8,
        slot_bytes: int = 16 << 20,
        create: bool = False,
    ):
        self.name = name
        self.slots = slots
        self.slot_bytes = slot_bytes
        self._creator = create
        # run-id-scoped like the control sockets: two jobs sharing a host
        # (and the default ring name) must not map the same segment
        run_id = os.environ.get("DLROVER_TPU_RUN_ID", "default")
        shm_name = f"dlrover_tpu_ring_{run_id}_{name}"
        if create:
            self._shm = create_shared_memory(shm_name, slots * slot_bytes)
            self._free: Any = SharedQueue(f"{name}_free")
            self._ready: Any = SharedQueue(f"{name}_ready")
            for i in range(slots):
                self._free.put(i)
        else:
            self._shm = attach_shared_memory(shm_name)
            self._free = SharedQueueClient(f"{name}_free")
            self._ready = SharedQueueClient(f"{name}_ready")

    # ---- producer side ---------------------------------------------------

    def put(self, batch: Dict[str, np.ndarray], timeout: float = 60.0):
        raw = _pack(batch)
        if len(raw) > self.slot_bytes:
            raise ValueError(
                f"batch packs to {len(raw)} bytes > slot_bytes="
                f"{self.slot_bytes}; raise slot_bytes"
            )
        slot = self._wait(self._free, timeout)
        if slot is None:
            raise TimeoutError("no free slot (consumer stalled?)")
        lo = slot * self.slot_bytes
        self._shm.buf[lo : lo + len(raw)] = raw
        self._ready.put({"slot": slot, "used": len(raw)})

    def mark_done(self):
        self._ready.put(_DONE)

    # ---- consumer side ---------------------------------------------------

    def get(self, timeout: float = 60.0) -> Optional[Dict[str, np.ndarray]]:
        """Next batch, or None on a producer-done marker."""
        item = self._wait(self._ready, timeout)
        if item is None:
            raise TimeoutError("no ready batch (producers stalled?)")
        if item == _DONE:
            return None
        slot, used = item["slot"], item["used"]
        lo = slot * self.slot_bytes
        batch = _unpack(self._shm.buf[lo : lo + used])
        self._free.put(slot)
        return batch

    @staticmethod
    def _wait(queue, timeout: float):
        deadline = time.time() + timeout
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                return None
            item = queue.get(timeout=min(remaining, 1.0))
            if item is not None:
                return item

    def close(self):
        self._shm.close()
        if self._creator:
            # reclaim /dev/shm: the segments are resource-tracker-exempt,
            # so nothing else ever unlinks them
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
        for q in (self._free, self._ready):
            if isinstance(q, SharedQueue):
                q.close()


def _producer_main(
    name: str,
    slots: int,
    slot_bytes: int,
    worker_id: int,
    num_workers: int,
    producer_fn,
):
    # geometry must match the creator's: slot offsets are slot_bytes-strided
    ring = BatchRing(name, slots=slots, slot_bytes=slot_bytes, create=False)
    try:
        for batch in producer_fn(worker_id, num_workers):
            ring.put(batch)
    except Exception:  # noqa: BLE001
        logger.exception("coworker %d failed", worker_id)
    finally:
        ring.mark_done()


class CoworkerPool:
    """N producer processes feeding one shm ring.

    ``producer_fn(worker_id, num_workers) -> iterator of batch dicts``
    must be picklable (top-level function); shard your dataset by
    worker_id inside it. The consumer iterates ``batches()`` until every
    producer finished.
    """

    def __init__(
        self,
        producer_fn: Callable[[int, int], Iterator[Dict]],
        num_workers: int = 2,
        slots: int = 8,
        slot_bytes: int = 16 << 20,
        name: str = "coworker",
    ):
        self.producer_fn = producer_fn
        self.num_workers = num_workers
        self.name = name
        self.ring = BatchRing(
            name, slots=slots, slot_bytes=slot_bytes, create=True
        )
        self._procs: List[mp.Process] = []

    def start(self):
        ctx = mp.get_context("spawn")
        env_run = os.environ.get("DLROVER_TPU_RUN_ID")
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=_producer_main,
                args=(
                    self.name,
                    self.ring.slots,
                    self.ring.slot_bytes,
                    wid,
                    self.num_workers,
                    self.producer_fn,
                ),
                daemon=True,
            )
            p.start()
            self._procs.append(p)
        logger.info(
            "coworker pool: %d producers (run=%s)",
            self.num_workers,
            env_run,
        )
        return self

    def batches(self, timeout: float = 120.0) -> Iterator[Dict]:
        done = 0
        while done < self.num_workers:
            batch = self.ring.get(timeout=timeout)
            if batch is None:
                done += 1
                continue
            yield batch

    def stop(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)
        self.ring.close()
