from dlrover_tpu.data.coworker import (  # noqa: F401
    BatchRing,
    CoworkerPool,
)
