"""Multi-head attention ops.

TPU counterpart of the reference's flash-attention integrations
(atorch modules/transformer/layers.py:538 FlashMHA wrappers; tfplus
flash_attn C++/CUDA glue). Here the op surface is one function,
``mha(q, k, v, causal=...)``:

- ``mha_reference`` — plain jnp einsum softmax attention (always available;
  XLA already fuses it well on small/medium sequences).
- ``flash_attention`` — Pallas TPU kernel (ops/pallas_attention.py), used
  automatically on TPU backends for long sequences.

All inputs are ``[batch, seq, heads, head_dim]``; GQA is expressed by
passing k/v with fewer heads (they are repeated on the fly).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    prefix_len: Optional[jax.Array] = None,
    window: int = 0,
) -> jax.Array:
    """Plain attention. q:[B,S,H,D], k/v:[B,S,Hkv,D] → [B,S,H,D].

    ``prefix_len`` [B] int32 (causal only): GLM-style prefix-LM — keys at
    positions < prefix_len[b] are visible to every query (bidirectional
    prefix), the rest follow the causal mask. ``window`` (causal only):
    Mistral-style sliding window — each query sees the last ``window``
    positions only.
    """
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    if hkv != h:
        k = _repeat_kv(k, h // hkv)
        v = _repeat_kv(v, h // hkv)
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    if jax.default_backend() == "cpu":
        # explicit f32 upcast rather than preferred_element_type:
        # XLA:CPU's thunk runtime cannot execute a BF16xBF16=F32 dot
        # when a `name` barrier (remat checkpoint tags upstream) keeps
        # it from fusing the converts in; on CPU the extra precision is
        # free. TPU keeps bf16 operands + f32 accumulate — the native
        # MXU contract (this path serves prefill/generation there).
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk",
            q.astype(jnp.float32),
            k.astype(jnp.float32),
        )
    else:
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
    logits = logits * scale
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        mask = q_pos >= k_pos - (sk - sq)
        if window:
            if window < 0:
                raise ValueError(f"window must be >= 0, got {window}")
            if prefix_len is not None:
                raise ValueError(
                    "window and prefix_len are mutually exclusive"
                )
            mask = mask & ((k_pos - (sk - sq)) > q_pos - window)
        if prefix_len is not None:
            pmask = (
                mask[None]
                | (k_pos[None] < prefix_len[:, None, None])
            )  # [B, Sq, Sk]
            logits = jnp.where(pmask[:, None], logits, -1e30)
        else:
            logits = jnp.where(mask[None, None], logits, -1e30)
    elif prefix_len is not None:
        raise ValueError("prefix_len requires causal=True")
    elif window:
        raise ValueError("window requires causal=True")
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None, :sq, :sk], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.partial(
    jax.jit, static_argnames=("causal", "softmax_scale", "impl")
)
def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention entry point.

    ``impl``: "auto" picks the Pallas flash kernel on TPU for seq >= 1024,
    plain jnp otherwise. "reference" / "flash" force a path.
    """
    use_flash = False
    if impl == "flash":
        use_flash = True
    elif impl == "auto":
        on_tpu = jax.default_backend() not in ("cpu", "gpu")
        use_flash = on_tpu and q.shape[1] >= 1024 and segment_ids is None
    if use_flash:
        from dlrover_tpu.ops.pallas_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal, softmax_scale=softmax_scale
        )
    return mha_reference(
        q,
        k,
        v,
        causal=causal,
        segment_ids=segment_ids,
        softmax_scale=softmax_scale,
    )
