"""Pallas TPU flash attention.

The framework's hot-op showcase (reference analog: the flash-attention
CUDA glue in tfplus/flash_attn and atorch's FlashMHA wrappers,
modules/transformer/layers.py:538 — here it's a native TPU kernel, not a
vendored library binding).

Forward: classic FlashAttention-2 online-softmax over k/v blocks. Grid is
(batch*kv_head_groups, q_blocks, k_blocks) with the k dimension marked
"arbitrary" so the output block is revisited and carried in VMEM scratch
(m/l running stats + f32 accumulator). Causal blocks above the diagonal are
skipped entirely.

Backward: FlashAttention-2-style pallas kernels via custom_vjp — a dq pass
(k-blocks innermost, dq carried in VMEM scratch) and a dk/dv pass (q-blocks
innermost), both recomputing p from the saved lse; tiles capped by head
width (BWD_BLOCK=512 for head_dim 64, BWD_BLOCK_WIDE=1024 for head_dim
≥128 — both measured on v5e; the backward holds ~4 [bq,bk] f32
transients at whichever cap applies). The ring-attention variant's lse cotangent folds into the
per-row delta before the kernels, so the SAME kernels serve it. A
jnp-level chunked recompute remains as the off-TPU / untileable-shape
fallback.

Both paths support GLM-style prefix-LM masking (per-batch prefix scalar in
SMEM) and GQA (K/V shared across head groups via BlockSpec index maps, no
materialized repeats).

Narrow-head packing (``head_pack``): heads narrower than the 128-lane MXU
quantum (gpt2's head_dim=64) pack ``128 // head_dim`` heads into ONE grid
program along a leading block axis ([pack, block, d] tiles). The per-head
matmuls are unrolled inside the program with their m/l/acc/lse bookkeeping
kept per-head, so numerics are identical to the unpacked kernels. What the
packing buys is NOT more MXU lanes per matmul — the 128-lane quantum makes
a d=64 contraction cost the same executed MXU passes packed or not — it is
everything around the matmuls: the causal/prefix/window mask and its iotas
are computed once per program and shared by all packed heads (VPU work that
otherwise rivals the d<128 matmul cost), there are pack× fewer grid
programs/epilogues, and K/V tiles DMA in pack-head batches. Heads that
don't divide evenly are zero-padded at the jnp level (a zeroed q/k/v head
yields out=0 and a finite lse, sliced off after); GQA keeps the unpacked
path (every GQA config here runs full-width d=128 heads anyway).
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds of jaxlib
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
# cap on the backward recompute chunk: bounds the transient p/dp/ds
# tensors to [B,H,S,1024] f32 regardless of the forward tile choice,
# while leaving seq<=1024 single-chunk (measured fastest on v5e)
BACKWARD_CHUNK = 1024
NEG_INF = -1e30

# test hook: run every kernel in pallas interpret mode (CPU-executable);
# lets composition layers (ring attention) exercise the real kernel path
# on the virtual CPU mesh. Seeded from DLROVER_TPU_PALLAS_INTERPRET so
# a whole test run can flip every kernel module (this one and
# ops/pallas_norm.py) without per-module monkeypatching.
INTERPRET = os.environ.get(
    "DLROVER_TPU_PALLAS_INTERPRET", ""
).lower() in ("1", "true", "yes")

# pallas FA2 backward kernels (vs the jnp chunked recompute); tiles
# capped separately from the forward (see _bwd_rule)
USE_PALLAS_BWD = True
BWD_BLOCK = 512        # measured best for head_dim 64 (v5e)
BWD_BLOCK_WIDE = 1024  # measured best for head_dim >= 128 (v5e)


def _last_visible_k_block(i, block_q, block_k):
    """Highest k-block index the causal run gate admits for q block i —
    the DMA-clamp twin of _block_runs: index maps clamp to this so
    gate-skipped blocks are never fetched. Any change to the gate's
    geometry must land here too."""
    return ((i + 1) * block_q - 1) // block_k


def _first_window_k_block(i, block_q, block_k, window):
    """Lowest k-block index a sliding window admits for q block i:
    its oldest row sees back to q_start − window + 1."""
    return jnp.maximum(0, (i * block_q - window + 1) // block_k)


def _first_visible_q_block(j, n_q_blocks, block_q, block_k):
    """Lowest q-block index the causal run gate admits for k block j,
    clamped into range (causal with sk > sq can otherwise exceed it)."""
    return jnp.minimum((j * block_k) // block_q, n_q_blocks - 1)


def _last_window_q_block(j, n_q_blocks, block_q, block_k, window):
    """Highest q-block index a sliding window admits for k block j: its
    newest key is visible up to k_end + window − 1."""
    return jnp.minimum(
        ((j + 1) * block_k - 1 + window - 1) // block_q, n_q_blocks - 1
    )


def _block_runs(causal, has_prefix, pref, q_start, k_start, block_q,
                block_k=None, window=0):
    """Run-gate shared by all kernels: a (q,k) block pair participates
    unless it lies entirely above the causal diagonal or (with a
    sliding window) entirely below it — and with a prefix-LM prefix,
    k blocks inside the prefix always participate."""
    run = (not causal) or (k_start <= q_start + block_q - 1)
    if causal and window:
        # the OLDEST q row (q_start) sees back to q_start − window + 1;
        # a k block ending before that is outside every row's window
        run = jnp.logical_and(
            run, k_start + block_k - 1 >= q_start - window + 1
        )
    if causal and has_prefix:
        run = jnp.logical_or(run, k_start < pref)
    return run


# sentinel distinguishing "compute the mask here" from a precomputed
# mask (which may legitimately be None for non-causal attention)
_MASK_UNSET = object()


def _allowed_mask(q_start, k_start, block_q, block_k, causal, has_prefix,
                  pref, window=0):
    """The [block_q, block_k] visibility mask (None when unmasked) — the
    ONE place the mask rule's geometry lives; every kernel reaches it
    through ``_masked_scores`` so forward and backward cannot drift.
    Packed kernels call it directly ONCE per program and share the
    result across all packed heads (the mask depends only on positions,
    never on the head)."""
    if not causal:
        return None
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    allowed = q_pos >= k_pos
    if window:
        # Mistral-style sliding window: each query sees the last
        # `window` positions (itself included)
        allowed = jnp.logical_and(allowed, q_pos - k_pos < window)
    if has_prefix:
        # GLM-style prefix-LM: keys inside the prefix are visible
        # to every query (bidirectional prefix, causal tail)
        allowed = jnp.logical_or(allowed, k_pos < pref)
    return allowed


def _masked_scores(q, k, scale, q_start, k_start, block_q, block_k,
                   causal, has_prefix, pref, window=0,
                   allowed=_MASK_UNSET):
    """q @ kᵀ with the causal / prefix-LM / sliding-window mask.
    ``allowed`` short-circuits the mask computation with a precomputed
    ``_allowed_mask`` result (head-packed kernels build it once and
    apply it to every packed head)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if allowed is _MASK_UNSET:
        allowed = _allowed_mask(
            q_start, k_start, block_q, block_k, causal, has_prefix,
            pref, window=window,
        )
    if allowed is not None:
        s = jnp.where(allowed, s, NEG_INF)
    return s


def _p_and_ds(s, do, v, lse_col, delta_col, scale):
    """Backward-shared softmax recompute: p from the saved lse, then
    ds = p·(dp − delta)·scale."""
    p = jnp.exp(s - lse_col)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_col) * scale
    return p, ds


def _fwd_head_step(s, v, m_prev, l_prev, acc_prev):
    """One head's online-softmax update from masked scores ``s`` — the
    math shared verbatim by the unpacked and head-packed forward
    kernels. Returns (m_new [bq,1], l_new [bq,1], acc_new [bq,d])."""
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    acc_new = acc_prev * alpha + jax.lax.dot_general(
        p.astype(v.dtype),
        v,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _fwd_kernel(
    q_ref,  # [block_q, d]
    k_ref,  # [block_k, d]
    v_ref,  # [block_k, d]
    prefix_ref,  # [B, 1] int32, whole array in SMEM (None w/o prefix)
    offs_ref,  # [1, 2] int32 (q_off, k_off) in SMEM (None w/o offsets)
    o_ref,  # [block_q, d]
    lse_ref,  # [block_q, 8] f32 (8 lanes to satisfy TPU tiling; col 0 used)
    m_scratch,  # [block_q, 128] f32
    l_scratch,  # [block_q, 128] f32
    acc_scratch,  # [block_q, d] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    has_prefix: bool,
    has_offsets: bool = False,
    n_head: int = 1,
    window: int = 0,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    # grid dim 0 is batch·heads; the scalar prefix is per-batch
    pref = (
        prefix_ref[pl.program_id(0) // n_head, 0] if has_prefix else None
    )

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # global offsets (ring attention: this call's q/k blocks sit at
    # traced global positions) shift every position the mask rule sees
    q_start = qi * block_q + (offs_ref[0, 0] if has_offsets else 0)
    k_start = ki * block_k + (offs_ref[0, 1] if has_offsets else 0)

    @pl.when(_block_runs(causal, has_prefix, pref, q_start, k_start,
                         block_q, block_k, window))
    def _body():
        s = _masked_scores(
            q_ref[0], k_ref[0], scale, q_start, k_start,
            block_q, block_k, causal, has_prefix, pref, window=window,
        )
        m_new, l_new, acc_new = _fwd_head_step(
            s, v_ref[0], m_scratch[:, :1], l_scratch[:, :1], acc_scratch[:]
        )
        acc_scratch[:] = acc_new
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scratch[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)
        # log-sum-exp per row — the backward's only softmax residual
        lse_ref[0] = jnp.broadcast_to(
            m_scratch[:, :1] + jnp.log(l), lse_ref.shape[1:]
        )


def _fwd_kernel_packed(
    q_ref,  # [1, pack, block_q, d]
    k_ref,  # [1, pack, block_k, d]
    v_ref,  # [1, pack, block_k, d]
    prefix_ref,  # [B, 1] int32 in SMEM (None w/o prefix)
    offs_ref,  # [1, 2] int32 in SMEM (None w/o offsets)
    o_ref,  # [1, pack, block_q, d]
    lse_ref,  # [1, pack, block_q, 8] f32
    m_scratch,  # [pack, block_q, 128] f32
    l_scratch,  # [pack, block_q, 128] f32
    acc_scratch,  # [pack, block_q, d] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    has_prefix: bool,
    has_offsets: bool = False,
    n_head: int = 1,  # grid-dim-0 entries per batch = h // pack
    window: int = 0,
    pack: int = 2,
):
    """Head-packed forward: ``pack`` heads of the same batch share one
    grid program. The per-head online softmax is unrolled with m/l/acc
    kept per-head, so the results are identical to the unpacked kernel;
    the mask (the VPU-side cost that rivals a d<128 matmul) is computed
    ONCE and shared — that, the pack× fewer programs, and the batched
    K/V DMA are the whole point of packing."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    pref = (
        prefix_ref[pl.program_id(0) // n_head, 0] if has_prefix else None
    )

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q + (offs_ref[0, 0] if has_offsets else 0)
    k_start = ki * block_k + (offs_ref[0, 1] if has_offsets else 0)

    @pl.when(_block_runs(causal, has_prefix, pref, q_start, k_start,
                         block_q, block_k, window))
    def _body():
        allowed = _allowed_mask(
            q_start, k_start, block_q, block_k, causal, has_prefix,
            pref, window=window,
        )
        for p in range(pack):
            s = _masked_scores(
                q_ref[0, p], k_ref[0, p], scale, q_start, k_start,
                block_q, block_k, causal, has_prefix, pref,
                window=window, allowed=allowed,
            )
            m_new, l_new, acc_new = _fwd_head_step(
                s, v_ref[0, p],
                m_scratch[p, :, :1], l_scratch[p, :, :1], acc_scratch[p],
            )
            acc_scratch[p] = acc_new
            m_scratch[p] = jnp.broadcast_to(m_new, m_scratch.shape[1:])
            l_scratch[p] = jnp.broadcast_to(l_new, l_scratch.shape[1:])

    @pl.when(ki == nk - 1)
    def _finish():
        for p in range(pack):
            l = l_scratch[p, :, :1]
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, p] = (acc_scratch[p] / l).astype(o_ref.dtype)
            lse_ref[0, p] = jnp.broadcast_to(
                m_scratch[p, :, :1] + jnp.log(l), lse_ref.shape[2:]
            )


def _insert_none_args(kernel, idxs):
    """Adapter for optional SMEM args: the kernel signatures always have
    prefix_ref/offs_ref slots (at positional indices ``idxs``, sorted),
    but pallas passes inputs positionally — splice Nones in for the
    absent ones."""

    def call(*refs):
        refs = list(refs)
        for idx in idxs:
            refs.insert(idx, None)
        return kernel(*refs)

    return call


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, prefix_ref,
    offs_ref,
    dq_ref,
    acc_scratch,  # [block_q, d] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    has_prefix: bool,
    has_offsets: bool = False,
    n_head: int = 1,
    window: int = 0,
):
    """dq = Σ_k ds @ K with ds = p·(dp − delta)·scale, p recomputed from
    the saved lse — FlashAttention-2 backward, k-blocks innermost so dq
    stays resident in VMEM scratch."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    pref = (
        prefix_ref[pl.program_id(0) // n_head, 0] if has_prefix else None
    )

    @pl.when(ki == 0)
    def _init():
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q + (offs_ref[0, 0] if has_offsets else 0)
    k_start = ki * block_k + (offs_ref[0, 1] if has_offsets else 0)

    @pl.when(_block_runs(causal, has_prefix, pref, q_start, k_start,
                         block_q, block_k, window))
    def _body():
        k = k_ref[0]
        s = _masked_scores(
            q_ref[0], k, scale, q_start, k_start,
            block_q, block_k, causal, has_prefix, pref, window=window,
        )
        _, ds = _p_and_ds(
            s, do_ref[0], v_ref[0],
            lse_ref[0][:, :1], delta_ref[0][:, :1], scale,
        )
        acc_scratch[:] = acc_scratch[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_scratch[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, prefix_ref,
    offs_ref,
    dk_ref, dv_ref,
    dk_scratch,  # [block_k, d] f32
    dv_scratch,  # [block_k, d] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    has_prefix: bool,
    has_offsets: bool = False,
    n_head: int = 1,
    window: int = 0,
):
    """dk/dv accumulated per k-block with q-blocks innermost:
    dv = Σ_q pᵀ @ dO, dk = Σ_q dsᵀ @ Q."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    pref = (
        prefix_ref[pl.program_id(0) // n_head, 0] if has_prefix else None
    )

    @pl.when(qi == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    q_start = qi * block_q + (offs_ref[0, 0] if has_offsets else 0)
    k_start = ki * block_k + (offs_ref[0, 1] if has_offsets else 0)

    @pl.when(_block_runs(causal, has_prefix, pref, q_start, k_start,
                         block_q, block_k, window))
    def _body():
        q = q_ref[0]
        do = do_ref[0]
        s = _masked_scores(
            q, k_ref[0], scale, q_start, k_start,
            block_q, block_k, causal, has_prefix, pref, window=window,
        )
        p, ds = _p_and_ds(
            s, do, v_ref[0],
            lse_ref[0][:, :1], delta_ref[0][:, :1], scale,
        )
        dv_scratch[:] = dv_scratch[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scratch[:] = dk_scratch[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


def _bwd_dq_kernel_packed(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, prefix_ref,
    offs_ref,
    dq_ref,
    acc_scratch,  # [pack, block_q, d] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    has_prefix: bool,
    has_offsets: bool = False,
    n_head: int = 1,
    window: int = 0,
    pack: int = 2,
):
    """Head-packed dq pass: q/k/v/do/lse/delta blocks carry a leading
    ``pack`` head axis; the recomputed-p backward is unrolled per head
    under ONE shared mask (see _fwd_kernel_packed)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    pref = (
        prefix_ref[pl.program_id(0) // n_head, 0] if has_prefix else None
    )

    @pl.when(ki == 0)
    def _init():
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q + (offs_ref[0, 0] if has_offsets else 0)
    k_start = ki * block_k + (offs_ref[0, 1] if has_offsets else 0)

    @pl.when(_block_runs(causal, has_prefix, pref, q_start, k_start,
                         block_q, block_k, window))
    def _body():
        allowed = _allowed_mask(
            q_start, k_start, block_q, block_k, causal, has_prefix,
            pref, window=window,
        )
        for p in range(pack):
            k = k_ref[0, p]
            s = _masked_scores(
                q_ref[0, p], k, scale, q_start, k_start,
                block_q, block_k, causal, has_prefix, pref,
                window=window, allowed=allowed,
            )
            _, ds = _p_and_ds(
                s, do_ref[0, p], v_ref[0, p],
                lse_ref[0, p][:, :1], delta_ref[0, p][:, :1], scale,
            )
            acc_scratch[p] = acc_scratch[p] + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(ki == nk - 1)
    def _finish():
        for p in range(pack):
            dq_ref[0, p] = acc_scratch[p].astype(dq_ref.dtype)


def _bwd_dkv_kernel_packed(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, prefix_ref,
    offs_ref,
    dk_ref, dv_ref,
    dk_scratch,  # [pack, block_k, d] f32
    dv_scratch,  # [pack, block_k, d] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    has_prefix: bool,
    has_offsets: bool = False,
    n_head: int = 1,
    window: int = 0,
    pack: int = 2,
):
    """Head-packed dk/dv pass (q-blocks innermost), unrolled per head
    under one shared mask."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    pref = (
        prefix_ref[pl.program_id(0) // n_head, 0] if has_prefix else None
    )

    @pl.when(qi == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    q_start = qi * block_q + (offs_ref[0, 0] if has_offsets else 0)
    k_start = ki * block_k + (offs_ref[0, 1] if has_offsets else 0)

    @pl.when(_block_runs(causal, has_prefix, pref, q_start, k_start,
                         block_q, block_k, window))
    def _body():
        allowed = _allowed_mask(
            q_start, k_start, block_q, block_k, causal, has_prefix,
            pref, window=window,
        )
        for p in range(pack):
            q = q_ref[0, p]
            do = do_ref[0, p]
            s = _masked_scores(
                q, k_ref[0, p], scale, q_start, k_start,
                block_q, block_k, causal, has_prefix, pref,
                window=window, allowed=allowed,
            )
            pr, ds = _p_and_ds(
                s, do, v_ref[0, p],
                lse_ref[0, p][:, :1], delta_ref[0, p][:, :1], scale,
            )
            dv_scratch[p] = dv_scratch[p] + jax.lax.dot_general(
                pr.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dk_scratch[p] = dk_scratch[p] + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(qi == nq - 1)
    def _finish():
        for p in range(pack):
            dk_ref[0, p] = dk_scratch[p].astype(dk_ref.dtype)
            dv_ref[0, p] = dv_scratch[p].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, out, lse, g, causal, scale,
                     block_q, block_k, prefix=None,
                     interpret: Optional[bool] = None,
                     g_lse=None, window: int = 0, offsets=None,
                     head_pack: int = 1):
    """FA2-style pallas backward: returns (dq, dk, dv).

    All [B,S,H,D] layouts like the forward; GQA dk/dv are group-summed
    back to the kv head count. ``g_lse`` [B,H,S] (ring attention's lse
    cotangent) folds into the per-row delta — ∂lse/∂s_j = p_j, so it
    enters ds as an additive term and the kernels need no change.

    ``head_pack`` > 1 runs the head-packed kernel variants (MHA only;
    h must divide by the pack — the jnp wrapper pads heads first).
    """
    interpret = INTERPRET if interpret is None else interpret
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    groups = h // hkv
    pack = max(int(head_pack), 1)
    if pack > 1:
        assert h == hkv and h % pack == 0, (
            "head packing needs MHA with heads divisible by the pack"
        )
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0

    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    dot = g.transpose(0, 2, 1, 3).reshape(b * h, sq, d).astype(q.dtype)
    # K/V stay at hkv heads; the BlockSpec index_map shares them across
    # the head group (no jnp.repeat HBM copies). dk/dv are still written
    # per q-head and group-summed after — a transient the accumulate-in-
    # VMEM alternative would trade for an 'arbitrary' grid dim.
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    # per-row softmax residuals, broadcast to the 8-lane tile the kernels
    # read column 0 of
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, S, H]
    delta_bh = delta.transpose(0, 2, 1).reshape(b * h, sq)
    if g_lse is not None:
        # total ds = p·(dp − delta + g_lse): subtract here once
        delta_bh = delta_bh - g_lse.reshape(b * h, sq).astype(jnp.float32)
    delta8 = jnp.broadcast_to(
        delta_bh[..., None], (b * h, sq, 8)
    )
    lse8 = jnp.broadcast_to(
        lse.reshape(b * h, sq)[..., None], (b * h, sq, 8)
    )

    has_prefix = prefix is not None
    has_offsets = offsets is not None
    extra = ()
    extra_specs = []
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    none_idxs = []
    if has_prefix:
        extra += (prefix.astype(jnp.int32).reshape(b, 1),)
        extra_specs.append(smem_spec)
    else:
        none_idxs.append(6)
    if has_offsets:
        extra += (offsets.astype(jnp.int32).reshape(1, 2),)
        extra_specs.append(smem_spec)
    else:
        none_idxs.append(7)
    wrap = (
        functools.partial(_insert_none_args, idxs=none_idxs)
        if none_idxs
        else (lambda kern: kern)
    )

    common = dict(
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        has_prefix=has_prefix,
        has_offsets=has_offsets,
        # grid-dim-0 entries per batch (the prefix SMEM row index is
        # program_id(0) // n_head): h unpacked, h/pack packed
        n_head=h // pack,
        window=window,
    )
    # with traced global offsets the diagonal's grid position is unknown
    # at trace time — the run gate still compute-skips, but the DMA index
    # clamp below must not assume a block-local diagonal
    causal_clamp = causal and prefix is None and not has_offsets

    # dq grid (g, q-block i, k-block j): above-diagonal (and, windowed,
    # below-window) k blocks are compute-skipped; clamp their index so
    # pallas re-addresses (and skips refetching) the previous block
    # instead of DMAing dead data
    def k_idx(g_, i, j):
        if causal_clamp:
            j = jnp.minimum(
                j, _last_visible_k_block(i, block_q, block_k)
            )
            if window:
                j = jnp.maximum(
                    j, _first_window_k_block(i, block_q, block_k, window)
                )
        return (g_ // groups, j, 0)

    q_spec = pl.BlockSpec((1, block_q, d), lambda g_, i, j: (g_, i, 0))
    row8_spec = pl.BlockSpec((1, block_q, 8), lambda g_, i, j: (g_, i, 0))
    k_spec = pl.BlockSpec((1, block_k, d), k_idx)
    compiler_params = (
        None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        )
    )

    if pack > 1:
        # head-packed variants: same grids with dim 0 shrunk pack×, all
        # q/k/v/do/lse/delta blocks carrying a leading pack axis. groups
        # == 1 here (MHA only), so no GQA index sharing or group-sum.
        gp = b * h // pack
        qt4 = qt.reshape(gp, pack, sq, d)
        kt4 = kt.reshape(gp, pack, sk, d)
        vt4 = vt.reshape(gp, pack, sk, d)
        dot4 = dot.reshape(gp, pack, sq, d)
        delta84 = delta8.reshape(gp, pack, sq, 8)
        lse84 = lse8.reshape(gp, pack, sq, 8)
        common_p = dict(common, pack=pack)

        def k_idx4(g_, i, j):
            if causal_clamp:
                j = jnp.minimum(
                    j, _last_visible_k_block(i, block_q, block_k)
                )
                if window:
                    j = jnp.maximum(
                        j,
                        _first_window_k_block(i, block_q, block_k, window),
                    )
            return (g_, 0, j, 0)

        q_spec4 = pl.BlockSpec(
            (1, pack, block_q, d), lambda g_, i, j: (g_, 0, i, 0)
        )
        row8_spec4 = pl.BlockSpec(
            (1, pack, block_q, 8), lambda g_, i, j: (g_, 0, i, 0)
        )
        k_spec4 = pl.BlockSpec((1, pack, block_k, d), k_idx4)
        dq = pl.pallas_call(
            wrap(functools.partial(_bwd_dq_kernel_packed, **common_p)),
            grid=(gp, sq // block_q, sk // block_k),
            in_specs=[q_spec4, k_spec4, k_spec4, q_spec4, row8_spec4,
                      row8_spec4, *extra_specs],
            out_specs=q_spec4,
            out_shape=jax.ShapeDtypeStruct((gp, pack, sq, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((pack, block_q, d), jnp.float32)
            ],
            compiler_params=compiler_params,
            interpret=interpret,
        )(qt4, kt4, vt4, dot4, lse84, delta84, *extra)

        nq4 = sq // block_q

        def q_idx4(g_, j, i):
            if causal_clamp:
                i = jnp.maximum(
                    i, _first_visible_q_block(j, nq4, block_q, block_k)
                )
                if window:
                    i = jnp.minimum(
                        i,
                        _last_window_q_block(
                            j, nq4, block_q, block_k, window
                        ),
                    )
            return (g_, 0, i, 0)

        qkv_spec4 = pl.BlockSpec((1, pack, block_q, d), q_idx4)
        row8_spec42 = pl.BlockSpec((1, pack, block_q, 8), q_idx4)
        kv_spec4 = pl.BlockSpec(
            (1, pack, block_k, d), lambda g_, j, i: (g_, 0, j, 0)
        )
        dk, dv = pl.pallas_call(
            wrap(functools.partial(_bwd_dkv_kernel_packed, **common_p)),
            grid=(gp, sk // block_k, sq // block_q),
            in_specs=[qkv_spec4, kv_spec4, kv_spec4, qkv_spec4,
                      row8_spec42, row8_spec42, *extra_specs],
            out_specs=[kv_spec4, kv_spec4],
            out_shape=[
                jax.ShapeDtypeStruct((gp, pack, sk, d), k.dtype),
                jax.ShapeDtypeStruct((gp, pack, sk, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((pack, block_k, d), jnp.float32),
                pltpu.VMEM((pack, block_k, d), jnp.float32),
            ],
            compiler_params=compiler_params,
            interpret=interpret,
        )(qt4, kt4, vt4, dot4, lse84, delta84, *extra)
        dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
        dk = dk.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
        dv = dv.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
        return (
            dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)
        )

    dq = pl.pallas_call(
        wrap(functools.partial(_bwd_dq_kernel, **common)),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row8_spec, row8_spec,
                  *extra_specs],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=compiler_params,
        interpret=interpret,
    )(qt, kt, vt, dot, lse8, delta8, *extra)

    # dkv grid swaps the roles: k-blocks outer, q-blocks inner; q blocks
    # entirely above the diagonal contribute nothing — clamp their index
    nq = sq // block_q

    def q_idx(g_, j, i):
        if causal_clamp:
            i = jnp.maximum(
                i, _first_visible_q_block(j, nq, block_q, block_k)
            )
            if window:
                i = jnp.minimum(
                    i,
                    _last_window_q_block(
                        j, nq, block_q, block_k, window
                    ),
                )
        return (g_, i, 0)

    qkv_spec = pl.BlockSpec((1, block_q, d), q_idx)
    row8_spec2 = pl.BlockSpec((1, block_q, 8), q_idx)
    kv_in_spec = pl.BlockSpec(
        (1, block_k, d), lambda g_, j, i: (g_ // groups, j, 0)
    )
    kv_spec = pl.BlockSpec((1, block_k, d), lambda g_, j, i: (g_, j, 0))
    dk, dv = pl.pallas_call(
        wrap(functools.partial(_bwd_dkv_kernel, **common)),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[qkv_spec, kv_in_spec, kv_in_spec, qkv_spec, row8_spec2,
                  row8_spec2, *extra_specs],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(qt, kt, vt, dot, lse8, delta8, *extra)

    dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, hkv, groups, sk, d).sum(axis=2)
    dv = dv.reshape(b, hkv, groups, sk, d).sum(axis=2)
    return (
        dq.astype(q.dtype),
        dk.transpose(0, 2, 1, 3).astype(k.dtype),
        dv.transpose(0, 2, 1, 3).astype(v.dtype),
    )


def _flash_fwd(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: Optional[bool] = None,
    prefix: Optional[jax.Array] = None,  # [B] int32 prefix-LM lengths
    window: int = 0,  # sliding window (causal only; 0 = unlimited)
    offsets: Optional[jax.Array] = None,  # [2] int32 global (q_off, k_off)
    head_pack: int = 1,  # heads per grid program (MHA only; h % pack == 0)
) -> jax.Array:
    interpret = INTERPRET if interpret is None else interpret
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    assert h % hkv == 0
    groups = h // hkv
    pack = max(int(head_pack), 1)
    if pack > 1:
        assert h == hkv and h % pack == 0, (
            "head packing needs MHA with heads divisible by the pack"
        )
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (
        "sequence must be padded to the block size"
    )

    # layout: [B, H, S, D] so the matmul dims are the minor two. K/V stay
    # at hkv heads — GQA sharing happens in the BlockSpec index_map
    # (g // groups), never as a materialized jnp.repeat in HBM.
    # Packed: [B·H/pack, pack, S, D] — pack heads ride one grid program.
    if pack > 1:
        qt = q.transpose(0, 2, 1, 3).reshape(b * h // pack, pack, sq, d)
        kt = k.transpose(0, 2, 1, 3).reshape(b * h // pack, pack, sk, d)
        vt = v.transpose(0, 2, 1, 3).reshape(b * h // pack, pack, sk, d)
        grid = (b * h // pack, sq // block_q, sk // block_k)
    else:
        qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
        kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
        vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
        grid = (b * h, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _fwd_kernel_packed if pack > 1 else _fwd_kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        has_prefix=prefix is not None,
        has_offsets=offsets is not None,
        n_head=h // pack,
        window=window,
        **({"pack": pack} if pack > 1 else {}),
    )
    inputs = (qt, kt, vt)
    prefix_specs = []
    none_idxs = []
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    if prefix is not None:
        # the whole [B,1] scalar table lives in SMEM; the kernel indexes
        # its batch row from grid dim 0 (Mosaic rejects sub-8 sublane
        # blocking, so no per-step BlockSpec windowing here)
        inputs += (prefix.astype(jnp.int32).reshape(b, 1),)
        prefix_specs.append(smem_spec)
    else:
        none_idxs.append(3)
    if offsets is not None:
        inputs += (offsets.astype(jnp.int32).reshape(1, 2),)
        prefix_specs.append(smem_spec)
    else:
        none_idxs.append(4)
    kernel_fn = (
        _insert_none_args(kernel, none_idxs) if none_idxs else kernel
    )
    if causal and prefix is None and offsets is None:
        # above-diagonal (and, with a sliding window, below-window)
        # blocks are compute-skipped by the run gate, but a naive index
        # map still DMAs them; clamping j re-addresses the SAME block,
        # which pallas does not refetch — saves the dead K/V traffic.
        # (A prefix can make above-diagonal blocks live, so no clamp.)
        def _kv_j(i, j):
            j = jnp.minimum(j, _last_visible_k_block(i, block_q, block_k))
            if window:
                j = jnp.maximum(
                    j, _first_window_k_block(i, block_q, block_k, window)
                )
            return j
    else:
        def _kv_j(i, j):
            return j

    if pack > 1:
        in_specs = [
            pl.BlockSpec(
                (1, pack, block_q, d), lambda g, i, j: (g, 0, i, 0)
            ),
            pl.BlockSpec(
                (1, pack, block_k, d),
                lambda g, i, j: (g, 0, _kv_j(i, j), 0),
            ),
            pl.BlockSpec(
                (1, pack, block_k, d),
                lambda g, i, j: (g, 0, _kv_j(i, j), 0),
            ),
        ]
        out_specs = [
            pl.BlockSpec(
                (1, pack, block_q, d), lambda g, i, j: (g, 0, i, 0)
            ),
            pl.BlockSpec(
                (1, pack, block_q, 8), lambda g, i, j: (g, 0, i, 0)
            ),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((b * h // pack, pack, sq, d), q.dtype),
            jax.ShapeDtypeStruct(
                (b * h // pack, pack, sq, 8), jnp.float32
            ),
        ]
        scratch_shapes = [
            pltpu.VMEM((pack, block_q, 128), jnp.float32),
            pltpu.VMEM((pack, block_q, 128), jnp.float32),
            pltpu.VMEM((pack, block_q, d), jnp.float32),
        ]
    else:
        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec(
                (1, block_k, d),
                lambda g, i, j: (g // groups, _kv_j(i, j), 0),
            ),
            pl.BlockSpec(
                (1, block_k, d),
                lambda g, i, j: (g // groups, _kv_j(i, j), 0),
            ),
        ]
        out_specs = [
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda g, i, j: (g, i, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 8), jnp.float32),
        ]
        scratch_shapes = [
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ]

    out, lse = pl.pallas_call(
        kernel_fn,
        grid=grid,
        in_specs=[*in_specs, *prefix_specs],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = (
        lse[..., 0].reshape(b, h, sq)
        if pack > 1
        else lse[:, :, 0].reshape(b, h, sq)
    )  # [B, H, S]
    return out, lse


def _bwd_chunk(sk: int, block_k: int) -> int:
    """Largest chunk ≤ min(block_k, BACKWARD_CHUNK) that divides sk —
    the memory cap must never violate the sk % chunk == 0 invariant
    (e.g. block_k=1280 with sk=2560 must not cap to 1024)."""
    cap = max(1, min(block_k, BACKWARD_CHUNK, sk))
    for c in range(cap, 0, -1):
        if sk % c == 0:
            return c
    return 1


def _chunked_backward(q, k, v, out, lse, g, causal, scale, chunk,
                      g_lse=None, prefix=None, window=0, offsets=None):
    """True O(S·chunk) flash backward from saved (out, lse).

    ``g_lse`` [B,H,S]: optional cotangent of the lse output (ring
    attention's softmax-merge differentiates through lse). Since
    ∂lse/∂s_j = p_j, it enters ds as an additive per-row term.

    Recomputes p = exp(s − lse) one key-chunk at a time (lax.scan), never
    materialising the [S, S] attention matrix — the memory property the
    reference's CUDA flash-attention backward has and a plain vjp through
    a softmax attention lacks. GQA: kv heads are expanded for the compute
    and group-summed for dk/dv.

    Layout: [B, H, S, D] throughout; f32 accumulation.
    """
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    groups = h // hkv
    # GQA layout [B, Hkv, G, S, D]: K/V stay at hkv heads — expanding them
    # by jnp.repeat would multiply KV memory by `groups` for the whole
    # sequence, exactly the footprint flash attention exists to avoid
    qt = (
        q.transpose(0, 2, 1, 3)
        .reshape(b, hkv, groups, sq, d)
        .astype(jnp.float32)
    )
    gt = (
        g.transpose(0, 2, 1, 3)
        .reshape(b, hkv, groups, sq, d)
        .astype(jnp.float32)
    )
    ot = (
        out.transpose(0, 2, 1, 3)
        .reshape(b, hkv, groups, sq, d)
        .astype(jnp.float32)
    )
    kt = k.transpose(0, 2, 1, 3).astype(jnp.float32)   # [B,Hkv,Sk,D]
    vt = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    lse_g = lse.reshape(b, hkv, groups, sq)
    delta = jnp.sum(gt * ot, axis=-1)                  # [B,Hkv,G,Sq]
    if g_lse is not None:
        # fold the lse cotangent into the per-row correction: total
        # ds = p·(dp − delta + g_lse)
        delta = delta - g_lse.reshape(b, hkv, groups, sq).astype(
            jnp.float32
        )

    chunk = min(chunk, sk)
    n_chunks = sk // chunk
    assert sk % chunk == 0
    k_chunks = kt.reshape(b, hkv, n_chunks, chunk, d)
    v_chunks = vt.reshape(b, hkv, n_chunks, chunk, d)
    q_pos = jnp.arange(sq)
    if offsets is not None:
        q_pos = q_pos + offsets.reshape(-1)[0]

    def body(dq_acc, idx):
        kc = k_chunks[:, :, idx]                       # [B,Hkv,C,D]
        vc = v_chunks[:, :, idx]
        s = jnp.einsum("bkgqd,bkcd->bkgqc", qt, kc) * scale
        if causal:
            k_pos = idx * chunk + jnp.arange(chunk)
            if offsets is not None:
                k_pos = k_pos + offsets.reshape(-1)[1]
            mask = q_pos[:, None] >= k_pos[None, :]
            if window:
                mask = mask & (
                    q_pos[:, None] - k_pos[None, :] < window
                )
            if prefix is not None:
                # bidirectional prefix: [B,1,1,Q,C] per-batch mask
                pmask = (
                    mask[None]
                    | (k_pos[None, None, :] < prefix[:, None, None])
                )
                s = jnp.where(pmask[:, None, None], s, NEG_INF)
            else:
                s = jnp.where(mask[None, None, None], s, NEG_INF)
        p = jnp.exp(s - lse_g[..., None])              # [B,Hkv,G,Q,C]
        dv_c = jnp.einsum("bkgqc,bkgqd->bkcd", p, gt)
        dp = jnp.einsum("bkgqd,bkcd->bkgqc", gt, vc)
        ds = p * (dp - delta[..., None]) * scale
        dk_c = jnp.einsum("bkgqc,bkgqd->bkcd", ds, qt)
        dq_acc = dq_acc + jnp.einsum("bkgqc,bkcd->bkgqd", ds, kc)
        return dq_acc, (dk_c, dv_c)

    dq, (dk_chunks, dv_chunks) = jax.lax.scan(
        body, jnp.zeros_like(qt), jnp.arange(n_chunks)
    )
    # scan stacks on axis 0: [n_chunks, B, Hkv, C, D] → [B, Hkv, Sk, D]
    dk = dk_chunks.transpose(1, 2, 0, 3, 4).reshape(b, hkv, sk, d)
    dv = dv_chunks.transpose(1, 2, 0, 3, 4).reshape(b, hkv, sk, d)
    dq = dq.reshape(b, h, sq, d)
    return (
        dq.transpose(0, 2, 1, 3).astype(q.dtype),
        dk.transpose(0, 2, 1, 3).astype(k.dtype),
        dv.transpose(0, 2, 1, 3).astype(v.dtype),
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10)
)
def _flash_attention(q, k, v, prefix, offsets, causal, scale, block_q,
                     block_k, window=0, head_pack=1):
    out, _ = _flash_fwd(
        q, k, v, causal, scale, block_q, block_k, prefix=prefix,
        window=window, offsets=offsets, head_pack=head_pack,
    )
    return out


def _fwd_rule(q, k, v, prefix, offsets, causal, scale, block_q, block_k,
              window=0, head_pack=1):
    out, lse = _flash_fwd(
        q, k, v, causal, scale, block_q, block_k, prefix=prefix,
        window=window, offsets=offsets, head_pack=head_pack,
    )
    # named so remat policies can pin the kernel residuals in memory and
    # skip re-running the forward kernel in backward (decoder save_attn)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, prefix, offsets, out, lse)


def _bwd_rule(causal, scale, block_q, block_k, window, head_pack,
              residuals, g):
    # same dispatch as the lse-carrying variant, with no lse cotangent
    return _bwd_rule_lse(
        causal, scale, block_q, block_k, window, head_pack, residuals,
        (g, None),
    )


_flash_attention.defvjp(_fwd_rule, _bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def flash_attention_with_lse(q, k, v, prefix, offsets, causal, scale,
                             block_q, block_k, window=0, head_pack=1):
    """Flash attention returning (out, lse) with BOTH differentiable —
    the primitive ring attention composes (the lse feeds the cross-block
    softmax merge, so its gradient is load-bearing). ``prefix`` [B] int32
    adds the prefix-LM bidirectional-prefix mask (causal only).
    ``offsets`` [2] int32 (q_off, k_off) shifts the mask rule to global
    positions — ring attention passes the blocks' traced ring offsets so
    window-boundary and prefix-reach blocks run this kernel too."""
    return _flash_fwd(
        q, k, v, causal, scale, block_q, block_k, prefix=prefix,
        window=window, offsets=offsets, head_pack=head_pack,
    )


def _fwd_rule_lse(q, k, v, prefix, offsets, causal, scale, block_q,
                  block_k, window=0, head_pack=1):
    out, lse = _flash_fwd(
        q, k, v, causal, scale, block_q, block_k, prefix=prefix,
        window=window, offsets=offsets, head_pack=head_pack,
    )
    # same tags as _fwd_rule: lets remat policies (and the ring's scan
    # checkpoint) pin the residuals instead of re-running the kernel
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return (out, lse), (q, k, v, prefix, offsets, out, lse)


def _bwd_rule_lse(causal, scale, block_q, block_k, window, head_pack,
                  residuals, cot):
    """The ONE backward dispatch (plain _bwd_rule delegates here with a
    None lse cotangent): FA2 pallas kernels on TPU/interpret with tiles
    capped per head width (BWD_BLOCK / BWD_BLOCK_WIDE — ~4 [bq,bk] f32
    transients per grid step at the applied cap); jnp chunked recompute
    off-TPU or when the sequence doesn't tile to a lane-aligned block."""
    q, k, v, prefix, offsets, out, lse = residuals
    g_out, g_lse = cot
    # wider heads keep the MXU busier per tile, so bigger tiles win
    bwd_cap = BWD_BLOCK_WIDE if q.shape[-1] >= 128 else BWD_BLOCK
    bq = _fit_block(q.shape[1], min(block_q, bwd_cap))
    bk = _fit_block(k.shape[1], min(block_k, bwd_cap))
    if (
        USE_PALLAS_BWD
        and pltpu is not None
        and (_on_tpu() or INTERPRET)
        and bq is not None
        and bk is not None
    ):
        dq, dk, dv = _pallas_backward(
            q, k, v, out, lse, g_out, causal, scale, bq, bk,
            prefix=prefix, g_lse=g_lse, window=window, offsets=offsets,
            head_pack=head_pack,
        )
    else:
        dq, dk, dv = _chunked_backward(
            q, k, v, out, lse, g_out, causal, scale,
            chunk=_bwd_chunk(k.shape[1], block_k),
            g_lse=g_lse,
            prefix=prefix,
            window=window,
            offsets=offsets,
        )
    dprefix = (
        None
        if prefix is None
        else np.zeros(prefix.shape, dtype=jax.dtypes.float0)
    )
    doffsets = (
        None
        if offsets is None
        else np.zeros(offsets.shape, dtype=jax.dtypes.float0)
    )
    return dq, dk, dv, dprefix, doffsets


flash_attention_with_lse.defvjp(_fwd_rule_lse, _bwd_rule_lse)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    prefix_len: Optional[jax.Array] = None,  # [B] int32: prefix-LM
    window: int = 0,  # sliding window (causal only; 0 = unlimited)
    head_pack: int = 0,  # heads per kernel program (0 = auto)
) -> jax.Array:
    """Flash attention; falls back to the jnp path off-TPU.

    q: [B, S, H, D]; k/v: [B, S, Hkv, D] (GQA via fewer kv heads).
    ``prefix_len`` (causal only) makes keys at positions < prefix_len[b]
    visible to every query — GLM-style bidirectional-prefix attention.
    ``window`` (causal only) limits each query to the last ``window``
    positions — Mistral-style sliding-window attention.
    ``head_pack`` packs that many narrow heads into one kernel program
    (module docstring, "narrow-head packing"): 0 picks 128 // D when
    D < 128 divides the lane width and the layout is MHA, 1 disables.
    Head counts that don't divide the pack are zero-padded (a zero
    q/k/v head yields zero out and zero grads, so the slice is exact);
    GQA always runs unpacked — packing would replicate kv DMA per
    group and the kernels keep the simple grid//groups indexing.
    """
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    sq, sk = q.shape[1], k.shape[1]
    h, hkv, d = q.shape[2], k.shape[2], q.shape[-1]
    bq = _fit_block(sq, block_q)
    bk = _fit_block(sk, block_k)
    if prefix_len is not None and not causal:
        raise ValueError("prefix_len requires causal=True")
    if window:
        if window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        if not causal:
            raise ValueError("window requires causal=True")
        if prefix_len is not None:
            raise ValueError("window and prefix_len are mutually exclusive")
    if head_pack < 0:
        raise ValueError(f"head_pack must be >= 0, got {head_pack}")
    if pltpu is None or not (_on_tpu() or INTERPRET) or bq is None or bk is None:
        # off-TPU (incl. GPU — this is a Mosaic-TPU kernel), or seq not
        # tileable to a lane-aligned block: plain jnp, never a trace-time
        # crash
        from dlrover_tpu.ops.attention import mha_reference

        return mha_reference(
            q, k, v, causal=causal, softmax_scale=scale,
            prefix_len=prefix_len, window=window,
        )
    if head_pack == 0:
        pack = 128 // d if (d < 128 and 128 % d == 0 and h == hkv) else 1
    else:
        pack = head_pack
        if h != hkv or d * pack > 128 or 128 % d != 0:
            pack = 1  # demote: GQA or pack overflows the lane width
    if pack > 1 and h % pack:
        pad = -h % pack
        zpad = [(0, 0), (0, 0), (0, pad), (0, 0)]
        out = _flash_attention(
            jnp.pad(q, zpad), jnp.pad(k, zpad), jnp.pad(v, zpad),
            prefix_len, None, causal, scale, bq, bk, window, pack,
        )
        return out[:, :, :h]
    return _flash_attention(
        q, k, v, prefix_len, None, causal, scale, bq, bk, window, pack
    )


def _on_tpu() -> bool:
    """True for real TPU backends AND TPU relays whose platform name
    differs (the axon tunnel reports platform 'axon', device_kind
    'TPU v5 lite')."""
    try:
        d = jax.devices()[0]
    except RuntimeError:
        return False
    return (
        d.platform.lower() == "tpu"
        or "tpu" in getattr(d, "device_kind", "").lower()
    )


def _fit_block(s: int, prefer: int):
    """Largest 128-multiple block ≤ prefer that divides the sequence."""
    for b in (prefer, 1024, 512, 256, 128):
        if b <= prefer and b <= s and s % b == 0 and b % 128 == 0:
            return b
    return None
