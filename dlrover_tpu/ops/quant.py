"""Block-wise int8 quantization for optimizer state.

Reference: atorch's CUDA quantization kernels + low-bit optimizer
(atorch/ops/csrc/quantization/*.cu, optimizers/low_bit/functional.py:543L).
TPU-native: the quantize/dequantize math is plain jnp — XLA fuses it into
the optimizer update so there is no extra HBM round-trip, which is what the
hand-written CUDA kernels existed to avoid.

``quantize_optimizer_state(opt)`` wraps any optax transformation so its
large float32 state leaves (Adam moments etc.) live as int8 + per-block
scales — a ~3.5× optimizer-memory cut.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

BLOCK = 256
MIN_QUANT_SIZE = 4096  # leave small leaves (scalars, counts) untouched


class QuantizedArray(NamedTuple):
    """int8 payload + per-block scales; shape/dtype kept for dequant."""

    q: jax.Array          # int8 [n_blocks, BLOCK]
    scale: jax.Array      # f32 [n_blocks, 1]
    meta: Any             # jax.ShapeDtypeStruct of the original


def quantize(x: jax.Array) -> QuantizedArray:
    meta = jax.ShapeDtypeStruct(x.shape, x.dtype)
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % BLOCK
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    return QuantizedArray(q=q, scale=scale, meta=meta)


def dequantize(qa: QuantizedArray) -> jax.Array:
    flat = (qa.q.astype(jnp.float32) * qa.scale).reshape(-1)
    size = 1
    for d in qa.meta.shape:
        size *= d
    return flat[:size].reshape(qa.meta.shape).astype(qa.meta.dtype)


def _should_quantize(leaf) -> bool:
    return (
        isinstance(leaf, (jax.Array, jnp.ndarray))
        and jnp.issubdtype(leaf.dtype, jnp.floating)
        and leaf.size >= MIN_QUANT_SIZE
    )


def _quantize_tree(state):
    return jax.tree.map(
        lambda leaf: quantize(leaf) if _should_quantize(leaf) else leaf,
        state,
    )


def _dequantize_tree(state):
    return jax.tree.map(
        lambda leaf: dequantize(leaf)
        if isinstance(leaf, QuantizedArray)
        else leaf,
        state,
        is_leaf=lambda x: isinstance(x, QuantizedArray),
    )


def quantize_optimizer_state(
    inner: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Keep ``inner``'s large state leaves as block-quantized int8."""

    def init_fn(params):
        return _quantize_tree(inner.init(params))

    def update_fn(updates, state, params=None):
        full = _dequantize_tree(state)
        updates, new_state = inner.update(updates, full, params)
        return updates, _quantize_tree(new_state)

    return optax.GradientTransformation(init_fn, update_fn)
