"""Block-wise int8 quantization for optimizer state.

Reference: atorch's CUDA quantization kernels + low-bit optimizer
(atorch/ops/csrc/quantization/*.cu, optimizers/low_bit/functional.py:543L).
TPU-native: the quantize/dequantize math is plain jnp — XLA fuses it into
the optimizer update so there is no extra HBM round-trip, which is what the
hand-written CUDA kernels existed to avoid.

``quantize_optimizer_state(opt)`` wraps any optax transformation so its
large float32 state leaves (Adam moments etc.) live as int8 + per-block
scales — a ~3.5× optimizer-memory cut.
"""


import jax
import jax.numpy as jnp
import optax

BLOCK = 256
MIN_QUANT_SIZE = 4096  # leave small leaves (scalars, counts) untouched


@jax.tree_util.register_pytree_node_class
class QuantizedArray:
    """int payload + per-block scales; shape/dtype kept for dequant.

    ``bits=8``: one value per int8 byte. ``bits=4``: two values packed per
    byte (low/high nibble), halving state memory again — the reference's
    4-bit optimizer (low_bit/functional.py) packing scheme, minus the CUDA.

    Registered as a pytree whose children are only (q, scale); shape/dtype/
    bits are static aux data, so instances flow through jit/scan/pjit as
    optimizer-state leaves (a ShapeDtypeStruct leaf would not trace).
    """

    __slots__ = ("q", "scale", "shape", "dtype", "bits")

    def __init__(self, q, scale, shape, dtype, bits: int = 8):
        self.q = q
        self.scale = scale
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.bits = int(bits)

    @property
    def meta(self):
        return jax.ShapeDtypeStruct(self.shape, self.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.shape, str(self.dtype), self.bits)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        shape, dtype, bits = aux
        return cls(q, scale, shape, dtype, bits)

    def __repr__(self):
        return (
            f"QuantizedArray(shape={self.shape}, dtype={self.dtype}, "
            f"bits={self.bits})"
        )


def quantize(x: jax.Array, bits: int = 8) -> QuantizedArray:
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % BLOCK
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    qmax = 127.0 if bits == 8 else 7.0
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / qmax
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        # two's-complement nibbles packed pairwise into one byte
        lo = q[:, 0::2] & 0xF
        hi = (q[:, 1::2] & 0xF) << 4
        q = (lo | hi).astype(jnp.int8)
    return QuantizedArray(q=q, scale=scale, shape=shape, dtype=dtype, bits=bits)


def _unpack4(q: jax.Array) -> jax.Array:
    # sign-extend each nibble: shift into the high bits, arithmetic-shift back
    lo = (q.astype(jnp.int8) << 4) >> 4
    hi = q.astype(jnp.int8) >> 4
    return jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)


def dequantize(qa: QuantizedArray) -> jax.Array:
    q = _unpack4(qa.q) if qa.bits == 4 else qa.q
    flat = (q.astype(jnp.float32) * qa.scale).reshape(-1)
    size = 1
    for d in qa.shape:
        size *= d
    return flat[:size].reshape(qa.shape).astype(qa.dtype)


def _should_quantize(leaf) -> bool:
    return (
        isinstance(leaf, (jax.Array, jnp.ndarray))
        and jnp.issubdtype(leaf.dtype, jnp.floating)
        and leaf.size >= MIN_QUANT_SIZE
    )


def _quantize_tree(state, bits: int = 8):
    return jax.tree.map(
        lambda leaf: quantize(leaf, bits) if _should_quantize(leaf) else leaf,
        state,
    )


def _dequantize_tree(state):
    return jax.tree.map(
        lambda leaf: dequantize(leaf)
        if isinstance(leaf, QuantizedArray)
        else leaf,
        state,
        is_leaf=lambda x: isinstance(x, QuantizedArray),
    )


def quantize_optimizer_state(
    inner: optax.GradientTransformation,
    bits: int = 8,
) -> optax.GradientTransformation:
    """Keep ``inner``'s large state leaves as block-quantized int8/int4."""

    def init_fn(params):
        return _quantize_tree(inner.init(params), bits)

    def update_fn(updates, state, params=None):
        full = _dequantize_tree(state)
        updates, new_state = inner.update(updates, full, params)
        return updates, _quantize_tree(new_state, bits)

    return optax.GradientTransformation(init_fn, update_fn)
