"""Block-wise int8 quantization for optimizer state.

Reference: atorch's CUDA quantization kernels + low-bit optimizer
(atorch/ops/csrc/quantization/*.cu, optimizers/low_bit/functional.py:543L).
TPU-native: the quantize/dequantize math is plain jnp — XLA fuses it into
the optimizer update so there is no extra HBM round-trip, which is what the
hand-written CUDA kernels existed to avoid.

``quantize_optimizer_state(opt)`` wraps any optax transformation so its
large float32 state leaves (Adam moments etc.) live as int8 + per-block
scales — a ~3.5× optimizer-memory cut.
"""


import math

import jax
import jax.numpy as jnp
import numpy as np
import optax

BLOCK = 256
MIN_QUANT_SIZE = 4096  # leave small leaves (scalars, counts) untouched


@jax.tree_util.register_pytree_node_class
class QuantizedArray:
    """int payload + per-block scales; shape/dtype kept for dequant.

    ``bits=8``: one value per int8 byte. ``bits=4``: two values packed per
    byte (low/high nibble), halving state memory again — the reference's
    4-bit optimizer (low_bit/functional.py) packing scheme, minus the CUDA.

    Registered as a pytree whose children are only (q, scale); shape/dtype/
    bits are static aux data, so instances flow through jit/scan/pjit as
    optimizer-state leaves (a ShapeDtypeStruct leaf would not trace).
    """

    __slots__ = ("q", "scale", "shape", "dtype", "bits")

    def __init__(self, q, scale, shape, dtype, bits: int = 8):
        self.q = q
        self.scale = scale
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.bits = int(bits)

    @property
    def meta(self):
        return jax.ShapeDtypeStruct(self.shape, self.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.shape, str(self.dtype), self.bits)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        shape, dtype, bits = aux
        return cls(q, scale, shape, dtype, bits)

    def __repr__(self):
        return (
            f"QuantizedArray(shape={self.shape}, dtype={self.dtype}, "
            f"bits={self.bits})"
        )


def _quant_blocks(blocks: jax.Array, bits: int):
    """Quantize ``(..., BLOCK)`` float32 blocks → (packed int8, scale)."""
    qmax = 127.0 if bits == 8 else 7.0
    scale = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True) / qmax
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        # two's-complement nibbles packed pairwise into one byte
        lo = q[..., 0::2] & 0xF
        hi = (q[..., 1::2] & 0xF) << 4
        q = (lo | hi).astype(jnp.int8)
    return q, scale


def _dequant_blocks(q: jax.Array, scale: jax.Array, bits: int) -> jax.Array:
    """Inverse of ``_quant_blocks``: packed blocks → float32 ``(..., BLOCK)``."""
    if bits == 4:
        # sign-extend each nibble: shift into high bits, arithmetic-shift back
        lo = (q.astype(jnp.int8) << 4) >> 4
        hi = q.astype(jnp.int8) >> 4
        q = jnp.stack([lo, hi], axis=-1).reshape(*q.shape[:-1], -1)
    return q.astype(jnp.float32) * scale


def quantize(x: jax.Array, bits: int = 8) -> QuantizedArray:
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % BLOCK
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    q, scale = _quant_blocks(blocks, bits)
    return QuantizedArray(q=q, scale=scale, shape=shape, dtype=dtype, bits=bits)


def dequantize(qa: QuantizedArray) -> jax.Array:
    flat = _dequant_blocks(qa.q, qa.scale, qa.bits).reshape(-1)
    size = 1
    for d in qa.shape:
        size *= d
    return flat[:size].reshape(qa.shape).astype(qa.dtype)


def _should_quantize(leaf) -> bool:
    return (
        isinstance(leaf, (jax.Array, jnp.ndarray))
        and jnp.issubdtype(leaf.dtype, jnp.floating)
        and leaf.size >= MIN_QUANT_SIZE
    )


def quantize_tree(state, bits: int = 8):
    """Blockwise-quantize every large float leaf of a pytree (small
    leaves pass through untouched). Inverse: ``dequantize_tree``."""
    return jax.tree.map(
        lambda leaf: quantize(leaf, bits) if _should_quantize(leaf) else leaf,
        state,
    )


def dequantize_tree(state):
    return jax.tree.map(
        lambda leaf: dequantize(leaf)
        if isinstance(leaf, QuantizedArray)
        else leaf,
        state,
        is_leaf=lambda x: isinstance(x, QuantizedArray),
    )


# intra-module aliases (historical names)
_quantize_tree = quantize_tree
_dequantize_tree = dequantize_tree


# ---------------------------------------------------------------------------
# Bucketed wire format for gradient collectives
# ---------------------------------------------------------------------------
# One flat stream, fixed-size buckets, blockwise int8 scales. The
# update-sharding gradient exchange (parallel/sharding.py) rides the
# row-wise pair below inside its shard_map; local-SGD outer-group syncs
# (parallel/local_sgd.py) ship whole pseudo-gradient trees in the same
# encoding via the tree-level pair.


def wire_encode_rows(rows: jax.Array):
    """Encode ``[r, n]`` f32 (n a multiple of BLOCK) → (int8 ``[r, n]``,
    f32 scales ``[r, n // BLOCK]``), one scale per block per row."""
    r, n = rows.shape
    q, scale = _quant_blocks(rows.reshape(r, n // BLOCK, BLOCK), 8)
    return q.reshape(r, n), scale[..., 0]


def wire_decode_sum(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Decode ``wire_encode_rows`` output and sum the rows in f32 → ``[n]``."""
    r, n = q.shape
    blocks = _dequant_blocks(
        q.reshape(r, n // BLOCK, BLOCK), scale[..., None], 8
    )
    return jnp.sum(blocks.reshape(r, n), axis=0)


def kv_block_size(row_elems: int) -> int:
    """Scale-block width for one KV token row of ``row_elems`` floats.

    A token row is ``kv_heads * head_dim`` elements — often smaller than
    the optimizer-state ``BLOCK`` (256). ``_quant_blocks`` is generic
    over the trailing dim, so narrow rows get one scale per whole row
    instead of being padded out to 256 (which would inflate the int8
    cache by the pad and wreck the resident-bytes win)."""
    if row_elems <= 0:
        raise ValueError(f"row_elems must be positive, got {row_elems}")
    if row_elems <= BLOCK:
        return row_elems
    # wide rows: largest divisor of the row that fits in BLOCK keeps
    # blocks uniform (no ragged tail inside a row)
    for cand in range(BLOCK, 0, -1):
        if row_elems % cand == 0:
            return cand
    return 1


def kv_encode_rows(rows: jax.Array, block: int):
    """Encode KV token rows ``[..., n]`` (n % block == 0) → int8 blocks.

    Returns ``(q [..., n//block, block] int8, scale [..., n//block] f32)``
    — the serving tier's paged-cache storage encoding, the same
    EQuARX-style per-block max/127 scheme the gradient wire uses
    (``wire_encode_rows``), kept unflattened so page pools can index
    whole blocks."""
    *lead, n = rows.shape
    if n % block:
        raise ValueError(f"row width {n} not a multiple of block {block}")
    blocks = rows.astype(jnp.float32).reshape(*lead, n // block, block)
    q, scale = _quant_blocks(blocks, 8)
    return q, scale[..., 0]


def kv_decode_rows(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of ``kv_encode_rows``: ``[..., nb, block]`` → ``[..., n]``.

    Dequantizes in f32 then casts to ``dtype`` (the model compute dtype)
    — the per-page dequant that runs INSIDE the jitted decode step."""
    out = _dequant_blocks(q, scale[..., None], 8)
    *lead, nb, blk = out.shape
    return out.reshape(*lead, nb * blk).astype(dtype)


def kv_encode_rows_np(rows: np.ndarray, block: int):
    """Host-side ``kv_encode_rows``: numpy in, numpy out.

    Same per-block max/127 scheme, for row stores that live outside jit
    (the tiered cold/warm tier keeps resident rows in this encoding)."""
    rows = np.asarray(rows, np.float32)
    *lead, n = rows.shape
    if n % block:
        raise ValueError(f"row width {n} not a multiple of block {block}")
    blocks = rows.reshape(*lead, n // block, block)
    scale = np.max(np.abs(blocks), axis=-1, keepdims=True) / 127.0
    scale = np.maximum(scale, 1e-12)
    q = np.clip(np.rint(blocks / scale), -127, 127).astype(np.int8)
    return q, scale[..., 0].astype(np.float32)


def kv_decode_rows_np(q: np.ndarray, scale: np.ndarray,
                      dtype=np.float32) -> np.ndarray:
    """Inverse of ``kv_encode_rows_np``: ``[..., nb, block]`` → ``[..., n]``."""
    out = q.astype(np.float32) * scale[..., None]
    *lead, nb, blk = out.shape
    return out.reshape(*lead, nb * blk).astype(dtype)


def _wire_layout(like, bucket_bytes: int):
    sizes = [
        int(math.prod(l.shape)) for l in jax.tree.leaves(like)
    ]
    total = sum(sizes)
    bucket_elems = max(bucket_bytes // 4, BLOCK)
    bucket_elems = -(-bucket_elems // BLOCK) * BLOCK
    n_buckets = max(1, -(-total // bucket_elems))
    return sizes, total, bucket_elems, n_buckets


def wire_encode_tree(tree, bits: int = 8, bucket_bytes: int = 4 * 2**20):
    """Pytree of float arrays → ``{"q", "scale"}`` bucketed wire payload.

    Every leaf (small ones included, unlike ``quantize_tree``) joins one
    flat f32 stream, zero-padded to ``n_buckets`` fixed-size buckets;
    each bucket is quantized blockwise (``BLOCK``-sized scales). The
    payload is a plain pytree of two arrays, so it drops straight into
    npz/socket transports.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    _, total, bucket_elems, n_buckets = _wire_layout(tree, bucket_bytes)
    flat = jnp.concatenate(
        [jnp.asarray(l).astype(jnp.float32).reshape(-1) for l in jax.tree.leaves(tree)]
    )
    flat = jnp.pad(flat, (0, n_buckets * bucket_elems - total))
    blocks = flat.reshape(n_buckets, bucket_elems // BLOCK, BLOCK)
    q, scale = _quant_blocks(blocks, bits)
    return {"q": q.reshape(n_buckets, -1), "scale": scale[..., 0]}


def wire_decode_tree(payload, like, bits: int = 8,
                     bucket_bytes: int = 4 * 2**20):
    """Inverse of ``wire_encode_tree``: payload → pytree shaped like ``like``."""
    sizes, _, bucket_elems, n_buckets = _wire_layout(like, bucket_bytes)
    q, scale = payload["q"], payload["scale"]
    blocks = _dequant_blocks(
        jnp.asarray(q).reshape(n_buckets, bucket_elems // BLOCK, -1),
        jnp.asarray(scale)[..., None],
        bits,
    )
    stream = blocks.reshape(-1)
    leaves, off = [], 0
    for l, s in zip(jax.tree.leaves(like), sizes):
        leaves.append(stream[off : off + s].reshape(l.shape).astype(l.dtype))
        off += s
    return jax.tree.unflatten(jax.tree.structure(like), leaves)


def quantize_optimizer_state(
    inner: optax.GradientTransformation,
    bits: int = 8,
) -> optax.GradientTransformation:
    """Keep ``inner``'s large state leaves as block-quantized int8/int4.

    Generic wrapper for arbitrary ``inner`` transforms. NOTE: it
    round-trips the WHOLE state tree through float32 every update, so the
    step-time HBM peak is the same as unquantized state — only resident
    memory shrinks. For AdamW at billion-parameter scale use
    ``lowbit_adamw``, which streams the dequant–update–requant in bounded
    chunks and never materialises a full float32 moment tree.
    """

    def init_fn(params):
        return _quantize_tree(inner.init(params), bits)

    def update_fn(updates, state, params=None):
        full = _dequantize_tree(state)
        updates, new_state = inner.update(updates, full, params)
        return updates, _quantize_tree(new_state, bits)

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# Fused streaming low-bit AdamW
# ---------------------------------------------------------------------------

# Elements processed per scan iteration. 4Mi elems = 16 MB per f32 chunk
# buffer; ~6 live chunk buffers ≈ 100 MB transient regardless of leaf size.
CHUNK_ELEMS = 4 * 1024 * 1024


def _leaf_blocks(x: jax.Array) -> jax.Array:
    """Flatten + pad a leaf to ``(n_blocks, BLOCK)`` float32 blocks."""
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % BLOCK
    return jnp.pad(flat, (0, pad)).reshape(-1, BLOCK)


def _zero_quantized(x: jax.Array, bits: int) -> QuantizedArray:
    """All-zero quantized moment with the layout ``lowbit_adamw`` uses."""
    n_blocks = -(-x.size // BLOCK)
    cols = BLOCK if bits == 8 else BLOCK // 2
    return QuantizedArray(
        q=jnp.zeros((n_blocks, cols), jnp.int8),
        scale=jnp.full((n_blocks, 1), 1e-12, jnp.float32),
        shape=x.shape,
        dtype=jnp.float32,
        bits=bits,
    )


def adamw_m_ema(g32, m32, b1: float):
    """First-moment EMA step (f32 in/out) — shared by every optimizer
    variant regardless of how it encodes nu."""
    return b1 * m32 + (1 - b1) * g32


def adamw_moments(g32, m32, v32, b1: float, b2: float):
    """One EMA step of both AdamW moments (f32 in/out)."""
    return adamw_m_ema(g32, m32, b1), b2 * v32 + (1 - b2) * (g32 * g32)


def adamw_direction(m2, vhat2, bc1, bc2, eps: float,
                    weight_decay: float = 0.0, p32=None):
    """Bias-corrected AdamW update direction from moment estimates.

    The ONE copy of the update expression every state-compression
    variant in this codebase shares (lowbit_adamw, mixed_adamw,
    train/optimizer.py factored_adamw) — nu encodings differ per
    optimizer, the direction math must not drift."""
    upd = (m2 / bc1) / (jnp.sqrt(vhat2 / bc2) + eps)
    if weight_decay:
        upd = upd + weight_decay * p32
    return upd


def lowbit_adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    bits: int = 8,
    chunk_elems: int = CHUNK_ELEMS,
) -> optax.GradientTransformation:
    """AdamW with block-quantized int8/int4 moments and bounded transients.

    Reference capability: atorch's low-bit optimizer
    (atorch/optimizers/low_bit/functional.py:543L) backed by CUDA
    quantization kernels (ops/csrc/quantization/quantization_optimizer.cu).
    TPU-native design: per leaf, a ``lax.scan`` streams fixed-size chunks
    through dequant → moment update → requant → AdamW step, so the float32
    working set is O(chunk) rather than O(params) — the whole point of
    low-bit state, which the generic ``quantize_optimizer_state`` wrapper
    loses at step time.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    chunk_blocks = max(1, chunk_elems // BLOCK)

    def _lr(step):
        return learning_rate(step) if callable(learning_rate) else learning_rate

    def init_fn(params):
        def moment(p):
            if _should_quantize(p):
                return _zero_quantized(p, bits)
            return jnp.zeros_like(p, jnp.float32)

        return {
            "step": jnp.zeros([], jnp.int32),
            "m": jax.tree.map(moment, params),
            "v": jax.tree.map(moment, params),
        }

    def _dense_update(g, m, v, p, bc1, bc2):
        g = g.astype(jnp.float32)
        m2, v2 = adamw_moments(g, m, v, b1, b2)
        upd = adamw_direction(
            m2, v2, bc1, bc2, eps, weight_decay,
            p.astype(jnp.float32) if weight_decay else None,
        )
        return upd, m2, v2

    def _chunked_update(g, mq: QuantizedArray, vq: QuantizedArray, p, bc1, bc2):
        n_blocks = mq.q.shape[0]
        pad_blocks = (-n_blocks) % chunk_blocks
        n_chunks = (n_blocks + pad_blocks) // chunk_blocks

        def blocks_of(x):
            b = _leaf_blocks(x)
            b = jnp.pad(b, ((0, pad_blocks), (0, 0)))
            return b.reshape(n_chunks, chunk_blocks, BLOCK)

        def chunks_of(q, scale):
            q = jnp.pad(q, ((0, pad_blocks), (0, 0)))
            scale = jnp.pad(scale, ((0, pad_blocks), (0, 0)))
            return (
                q.reshape(n_chunks, chunk_blocks, -1),
                scale.reshape(n_chunks, chunk_blocks, 1),
            )

        xs = (
            blocks_of(g),
            blocks_of(p) if weight_decay else None,
            chunks_of(mq.q, mq.scale),
            chunks_of(vq.q, vq.scale),
        )

        def body(_, x):
            gc, pc, (mqc, msc), (vqc, vsc) = x
            m = _dequant_blocks(mqc, msc, bits)
            v = _dequant_blocks(vqc, vsc, bits)
            m2, v2 = adamw_moments(gc, m, v, b1, b2)
            upd = adamw_direction(m2, v2, bc1, bc2, eps, weight_decay, pc)
            mq2, ms2 = _quant_blocks(m2, bits)
            vq2, vs2 = _quant_blocks(v2, bits)
            return None, (upd, (mq2, ms2), (vq2, vs2))

        _, (upd, (mq2, ms2), (vq2, vs2)) = jax.lax.scan(body, None, xs)

        def unchunk(x, cols):
            return x.reshape(n_chunks * chunk_blocks, cols)[:n_blocks]

        upd = upd.reshape(-1)[: g.size].reshape(g.shape)
        cols = mq.q.shape[1]
        new_m = QuantizedArray(
            unchunk(mq2, cols), unchunk(ms2, 1), mq.shape, mq.dtype, bits
        )
        new_v = QuantizedArray(
            unchunk(vq2, cols), unchunk(vs2, 1), vq.shape, vq.dtype, bits
        )
        return upd, new_m, new_v

    def update_fn(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError("lowbit_adamw with weight_decay needs params")
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        bc1 = 1 - b1**t
        bc2 = 1 - b2**t
        # schedule parity with optax.scale_by_schedule: the lr for
        # update t reads schedule(count BEFORE increment) — bias
        # correction uses the incremented count
        lr = _lr(state["step"])
        p_tree = params if params is not None else updates

        def leaf(g, m, v, p):
            if isinstance(m, QuantizedArray):
                upd, m2, v2 = _chunked_update(g, m, v, p, bc1, bc2)
            else:
                upd, m2, v2 = _dense_update(g, m, v, p, bc1, bc2)
            return (-lr * upd).astype(g.dtype), m2, v2

        out = jax.tree.map(
            leaf,
            updates,
            state["m"],
            state["v"],
            p_tree,
            is_leaf=lambda x: isinstance(x, QuantizedArray),
        )
        unzip = lambda i: jax.tree.map(
            lambda x: x[i], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        return unzip(0), {"step": step, "m": unzip(1), "v": unzip(2)}

    return optax.GradientTransformation(init_fn, update_fn)


def mixed_adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    v_bits: int = 8,
    m_dtype=jnp.bfloat16,
) -> optax.GradientTransformation:
    """AdamW with bf16 first moment and block-quantized int8 second moment.

    The memory/fidelity middle ground between bf16 states and
    ``lowbit_adamw``: the momentum (whose sign structure steers the
    update) keeps bf16, while the variance — already a smooth, positive
    statistic that Adafactor famously rank-1-factorizes with no loss
    curve change — drops to int8 blocks. At 1.4B params this frees
    ~2 GiB of HBM versus bf16 nu, which is exactly what buys the
    ``save_qkv_gate`` remat tier on a 16 GiB chip (see bench.py).

    Unlike ``lowbit_adamw``'s chunk-streamed scan (bounded f32 working
    set, built for when BOTH moments are int8/int4 at >=1.5B), this is a
    plain vectorized leaf update: the f32 transient is one leaf's worth,
    XLA fuses dequant -> update -> requant into the optimizer pass, and
    the step-time cost is NEGATIVE versus bf16 nu (0.68 GiB of nu reads
    plus writes instead of 2.7 GiB each way).

    Reference capability: atorch low-bit optimizers
    (atorch/optimizers/low_bit/functional.py) — this variant's
    moment-asymmetric precision is TPU-motivated (HBM roofline), not a
    translation.
    """
    if v_bits not in (4, 8):
        raise ValueError(f"v_bits must be 4 or 8, got {v_bits}")

    def _lr(step):
        return learning_rate(step) if callable(learning_rate) else learning_rate

    def init_fn(params):
        def m0(p):
            return jnp.zeros_like(p, m_dtype if _should_quantize(p)
                                  else jnp.float32)

        def v0(p):
            if _should_quantize(p):
                return _zero_quantized(p, v_bits)
            return jnp.zeros_like(p, jnp.float32)

        return {
            "step": jnp.zeros([], jnp.int32),
            "m": jax.tree.map(m0, params),
            "v": jax.tree.map(v0, params),
        }

    def update_fn(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError("mixed_adamw with weight_decay needs params")
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        bc1 = 1 - b1**t
        bc2 = 1 - b2**t
        # schedule parity with optax.scale_by_schedule: the lr for
        # update t reads schedule(count BEFORE increment) — bias
        # correction uses the incremented count
        lr = _lr(state["step"])
        p_tree = params if params is not None else updates

        def leaf(g, m, v, p):
            g32 = g.astype(jnp.float32)
            m2 = adamw_m_ema(g32, m.astype(jnp.float32), b1)
            # nu is stored on SQRT scale: int8's ~2 decades of blockwise
            # dynamic range cover sqrt(nu)'s spread twice as well as
            # nu's, and sqrt(nu) is what the update actually consumes
            if isinstance(v, QuantizedArray):
                v32 = jnp.square(dequantize(v))
            else:
                v32 = v
            v2 = b2 * v32 + (1 - b2) * (g32 * g32)
            upd = adamw_direction(
                m2, v2, bc1, bc2, eps, weight_decay,
                p.astype(jnp.float32) if weight_decay else None,
            )
            new_v = (
                quantize(jnp.sqrt(v2), v_bits)
                if isinstance(v, QuantizedArray)
                else v2
            )
            return (-lr * upd).astype(g.dtype), m2.astype(m.dtype), new_v

        out = jax.tree.map(
            leaf,
            updates,
            state["m"],
            state["v"],
            p_tree,
            is_leaf=lambda x: isinstance(x, QuantizedArray),
        )
        unzip = lambda i: jax.tree.map(
            lambda x: x[i], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        return unzip(0), {"step": step, "m": unzip(1), "v": unzip(2)}

    return optax.GradientTransformation(init_fn, update_fn)
