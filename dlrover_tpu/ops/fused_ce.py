"""Fused lm-head + softmax cross-entropy: never materializes [N, V] logits.

The reference computes full logits then a separate CE (Megatron-style
vocab-parallel CE in atorch keeps the whole [B*S, vocab] tensor alive:
reference atorch/atorch/modules/distributed_modules/cross_entropy.py).
On TPU the f32 logits block for b8*s1024*v32000 is ~1 GiB of HBM that
the standard path writes in forward, re-reads for logsumexp / gather /
argmax, and re-materializes as softmax in backward — several GiB of
pure bandwidth plus ~2 GiB of peak memory.

This op chunks the vocab axis and keeps online max / log-sum-exp
statistics (the same trick as ops/pallas_attention.py, applied at the
XLA level where the chunk matmuls already hit the MXU): peak memory is
one [B, S, block_v] block, and backward recomputes each chunk's logits
instead of loading them. The extra recompute is one [N,D]x[D,V] matmul
pass; the savings are the logits round-trips and ~2 GiB of HBM, which
in turn buys a cheaper remat policy for the trunk.

Implemented as plain XLA (lax.scan over vocab chunks) rather than a
Pallas kernel: the hot op is a large matmul XLA already tiles onto the
MXU perfectly; a hand kernel could only lose.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_NEG_INF = float("-inf")


def _num_chunks(v: int, block_v: int) -> int:
    return max(1, math.ceil(v / block_v))


def _pad_w(w: jax.Array, block_v: int) -> jax.Array:
    v = w.shape[1]
    nc = _num_chunks(v, block_v)
    pad = nc * block_v - v
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return w


def _mm_f32(subscripts, a, b):
    """Matmul with f32 accumulation/output from (possibly) bf16 operands.

    On TPU: bf16 operands + preferred_element_type=f32 is the native
    MXU contract. On CPU (the test platform): XLA's thunk runtime
    cannot execute a BF16xBF16=F32 dot when remat name-barriers stop it
    fusing the converts, so upcast the operands explicitly — the
    fallback path's extra precision is free there.
    """
    if jax.default_backend() == "cpu":
        return jnp.einsum(
            subscripts, a.astype(jnp.float32), b.astype(jnp.float32)
        )
    return jnp.einsum(
        subscripts, a, b, preferred_element_type=jnp.float32
    )


def _chunk_logits(x, w_pad, start, block_v, v, scale):
    """One [B, S, block_v] f32 logits chunk; out-of-vocab lanes -> -inf."""
    w_c = lax.dynamic_slice_in_dim(w_pad, start, block_v, axis=1)
    logits = _mm_f32("bsd,dv->bsv", x, w_c.astype(x.dtype))
    if scale != 1.0:
        logits = logits * jnp.float32(scale)
    valid = (start + jnp.arange(block_v)) < v
    logits = jnp.where(valid[None, None, :], logits, _NEG_INF)
    return logits, w_c


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_ce(x, w, targets, scale=1.0, block_v=4096):
    """logz/target-logit/argmax of ``scale * (x @ w)`` without the logits.

    Args:
      x: [B, S, D] hidden states (any float dtype; matmuls run in this
        dtype with f32 accumulation, matching the unfused einsum path).
      w: [D, V] head weight (pass ``embed.T`` for tied embeddings — the
        transpose stays outside this op so its cotangent flows back).
      targets: [B, S] int32 target ids in [0, V).
      scale: static logit multiplier (muP readout).
      block_v: static vocab chunk width (MXU-friendly multiple of 128).

    Returns:
      (logz [B,S] f32, tgt_logit [B,S] f32, argmax [B,S] int32).
      NLL = logz - tgt_logit; z-loss reads logz; accuracy reads argmax.
      Differentiable w.r.t. x and w.
    """
    out, _ = _fused_fwd(x, w, targets, scale, block_v)
    return out


def _fused_fwd(x, w, targets, scale, block_v):
    b, s, _ = x.shape
    v = w.shape[1]
    nc = _num_chunks(v, block_v)
    w_pad = _pad_w(w, block_v)

    init = (
        jnp.full((b, s), _NEG_INF, jnp.float32),
        jnp.zeros((b, s), jnp.float32),
        jnp.zeros((b, s), jnp.float32),
        jnp.full((b, s), _NEG_INF, jnp.float32),
        jnp.zeros((b, s), jnp.int32),
    )

    def step(carry, i):
        m, se, tgt, av, ai = carry
        start = i * block_v
        logits, _ = _chunk_logits(x, w_pad, start, block_v, v, scale)
        cm = logits.max(-1)
        m_new = jnp.maximum(m, cm)
        se = se * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[..., None]
        ).sum(-1)
        rel = targets - start
        inb = (rel >= 0) & (rel < block_v)
        got = jnp.take_along_axis(
            logits, jnp.clip(rel, 0, block_v - 1)[..., None], axis=-1
        )[..., 0]
        tgt = jnp.where(inb, got, tgt)
        ci = logits.argmax(-1).astype(jnp.int32)
        upd = cm > av
        av = jnp.where(upd, cm, av)
        ai = jnp.where(upd, start + ci, ai)
        return (m_new, se, tgt, av, ai), None

    (m, se, tgt, _, ai), _ = lax.scan(
        step, init, jnp.arange(nc), unroll=False
    )
    logz = m + jnp.log(se)
    out = (logz, tgt, ai)
    return out, (x, w, targets, logz)


def _fused_bwd(scale, block_v, res, cots):
    x, w, targets, logz = res
    g_logz, g_tgt, _ = cots  # argmax cotangent is float0/zero: ignored
    v = w.shape[1]
    d = w.shape[0]
    nc = _num_chunks(v, block_v)
    w_pad = _pad_w(w, block_v)
    g_logz = g_logz.astype(jnp.float32)
    g_tgt = g_tgt.astype(jnp.float32)

    def step(carry, i):
        dx, dwp = carry
        start = i * block_v
        logits, w_c = _chunk_logits(x, w_pad, start, block_v, v, scale)
        # p has exact zeros on padded lanes: exp(-inf - logz) == 0
        p = jnp.exp(logits - logz[..., None])
        dlog = g_logz[..., None] * p
        rel = targets - start
        onehot = jnp.arange(block_v)[None, None, :] == rel[..., None]
        dlog = dlog + jnp.where(onehot, g_tgt[..., None], 0.0)
        dlog_c = dlog.astype(x.dtype)  # MXU dtype, matches fwd matmuls
        dx = dx + jnp.float32(scale) * _mm_f32(
            "bsv,dv->bsd", dlog_c, w_c.astype(x.dtype)
        )
        dw_c = jnp.float32(scale) * _mm_f32("bsd,bsv->dv", x, dlog_c)
        dwp = lax.dynamic_update_slice_in_dim(dwp, dw_c, start, axis=1)
        return (dx, dwp), None

    init = (
        jnp.zeros(x.shape, jnp.float32),
        jnp.zeros((d, nc * block_v), jnp.float32),
    )
    (dx, dwp), _ = lax.scan(step, init, jnp.arange(nc))
    d_targets = np.zeros(targets.shape, dtype=jax.dtypes.float0)
    return dx.astype(x.dtype), dwp[:, :v].astype(w.dtype), d_targets


fused_linear_ce.defvjp(_fused_fwd, _fused_bwd)
