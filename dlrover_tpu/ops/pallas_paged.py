"""Fused paged attention over the serving tier's block-table KV pools.

The serving engine (PR 11) stored KV state in fixed-size pages but
computed attention by materializing the full per-slot contiguous cache
every step — a ``kv_cache.gather`` producing an `[L, B, S_max, Hkv, D]`
copy per decode token, O(entire working set) HBM traffic, plus a full
bf16 dequant copy in int8 mode. This module is the paged decode path
that never builds that tensor:

- ``paged_attention`` — the dispatching op. On TPU (or in Pallas
  interpret mode) it runs a fused kernel whose grid walks each slot's
  block table one physical page at a time: K/V pages load straight from
  the layer-leading pools, int8 payloads dequantize **in-register**
  against their per-block f32 scales (bf16 pools load verbatim), and
  pages fold together with flash-style online softmax (running max/sum,
  f32 accumulation, the same ``-1e30`` masking as the dense cached
  attention). Per step it touches only the pages a slot actually holds.
- ``paged_attention_reference`` — the pure-jnp fallback with the same
  signature. It gathers ONLY the pages named by the block table (sliced
  to ``max_pages`` when the host knows how many are held) and then
  replicates ``decoder._cached_attention`` / ``_chunk_cached_attention``
  op for op, so in bf16 mode its output is **bitwise** equal to the
  dense gather path — the parity oracle for both the kernel and the
  engine's ``paged=True`` mode. Even as a fallback it beats the old
  full-pool gather: traffic scales with pages held, not table width.
- ``write_page_rows`` — the per-layer encode-on-write twin of
  ``kv_cache.write_rows`` (same phys/offset math, same trash-page
  routing) so the decoder's layer scan can commit each new token's K/V
  row straight into its page cell.

Both variants honor GQA (``kv_heads < n_head``) and sliding-window
masking (``window``), and the interpret-mode hook
(``DLROVER_TPU_PALLAS_INTERPRET``) makes the whole kernel CPU-testable,
following ``pallas_attention.py``/``pallas_norm.py``. Availability is
surfaced through the ``KernelCapabilities`` table
(``accelerate/device_context.py``) as ``paged_attention``.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds of jaxlib
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from dlrover_tpu.ops import quant
from dlrover_tpu.ops.attention import _repeat_kv
from dlrover_tpu.ops.pallas_attention import _on_tpu

NEG_INF = -1e30

# test hook: run every kernel in pallas interpret mode (CPU-executable).
INTERPRET = os.environ.get(
    "DLROVER_TPU_PALLAS_INTERPRET", ""
).lower() in ("1", "true", "yes")


def kernels_available(interpret=None) -> bool:
    """True when the fused paged kernel would actually run (real TPU or
    interpret mode) — what ``KernelCapabilities.paged_attention`` keys
    off. Everywhere else ``paged_attention`` silently runs the jnp
    reference, which is still a paged (pages-held-only) gather."""
    interpret = INTERPRET if interpret is None else interpret
    return pltpu is not None and (_on_tpu() or interpret)


# ---------------------------------------------------------------------------
# Page-level helpers shared by the reference, the kernel and the decoder
# ---------------------------------------------------------------------------


def _pool_info(pools, kv_heads):
    """(mode, page_size, kv_heads, head_dim) from a per-layer pool dict.

    bf16 pools carry the head split in their shape; int8 pools store
    flat quant blocks, so ``kv_heads`` must come from the caller."""
    if "k" in pools:
        _, ps, hkv, d = pools["k"].shape
        return "bf16", ps, hkv, d
    if kv_heads is None:
        raise ValueError(
            "int8 pools store flat quant blocks; pass kv_heads= so the "
            "row can be split back into heads"
        )
    _, ps, nb, blk = pools["k_q"].shape
    row = nb * blk
    if row % kv_heads:
        raise ValueError(f"row of {row} elems not divisible by "
                         f"kv_heads={kv_heads}")
    return "int8", ps, kv_heads, row // kv_heads


def gather_pages(pools, block_tables, *, kv_heads=None, max_pages=None,
                 dtype=None):
    """K/V for ONLY the pages the block table names.

    Per-layer pools (bf16 ``{"k","v"}`` `[n_pages, ps, Hkv, D]`, int8
    ``{"k_q","k_scale","v_q","v_scale"}``) → ``(k, v)`` each
    `[B, W·ps, Hkv, D]`, where ``W`` is ``max_pages`` (host-known pages
    held) or the full table width. Unassigned entries (-1) clamp onto
    the trash page — finite garbage the caller masks by position.
    int8 payloads dequantize to ``dtype`` (the model compute dtype),
    matching ``kv_cache.gather``'s output values exactly.
    """
    tables = block_tables if max_pages is None else block_tables[:, :max_pages]
    t = jnp.maximum(tables, 0)
    mode, ps, hkv, d = _pool_info(pools, kv_heads)
    b, w = t.shape
    if mode == "bf16":
        k, v = pools["k"][t], pools["v"][t]
    else:
        dt = jnp.dtype(dtype) if dtype is not None else jnp.bfloat16
        k = quant.kv_decode_rows(pools["k_q"][t], pools["k_scale"][t], dt)
        v = quant.kv_decode_rows(pools["v_q"][t], pools["v_scale"][t], dt)
    shape = (b, w * ps, hkv, d)
    return k.reshape(shape), v.reshape(shape)


def write_page_rows(pools, block_tables, positions, valid, k_rows, v_rows):
    """Commit token K/V rows straight into their page cells (per-layer).

    The decoder-scan twin of ``kv_cache.write_rows``: same
    phys = table[position // ps] / offset = position % ps math, same
    trash-page routing for invalid lanes, encode-on-write for int8 —
    but over ONE layer's pool slice so the layer scan can carry pools
    as xs. ``positions``/``valid`` are `[B, C]`; rows `[B, C, Hkv, D]`.
    """
    mode, ps, _, _ = _pool_info(pools, k_rows.shape[2])
    page_idx = positions // ps
    offs = positions % ps
    phys = jnp.take_along_axis(block_tables, page_idx, axis=1)
    phys = jnp.where(valid, jnp.maximum(phys, 0), 0)  # 0 == TRASH_PAGE
    offs = jnp.where(valid, offs, 0)
    if mode == "bf16":
        dt = pools["k"].dtype
        return {
            "k": pools["k"].at[phys, offs].set(k_rows.astype(dt)),
            "v": pools["v"].at[phys, offs].set(v_rows.astype(dt)),
        }
    blk = pools["k_q"].shape[-1]
    b, c, hkv, d = k_rows.shape
    kq, ks = quant.kv_encode_rows(k_rows.reshape(b, c, hkv * d), blk)
    vq, vs = quant.kv_encode_rows(v_rows.reshape(b, c, hkv * d), blk)
    return {
        "k_q": pools["k_q"].at[phys, offs].set(kq),
        "k_scale": pools["k_scale"].at[phys, offs].set(ks),
        "v_q": pools["v_q"].at[phys, offs].set(vq),
        "v_scale": pools["v_scale"].at[phys, offs].set(vs),
    }


# ---------------------------------------------------------------------------
# Pure-jnp reference (the parity oracle, and the CPU fast path)
# ---------------------------------------------------------------------------


def paged_attention_reference(
    q,                  # [B, C, H, D] (decode: C == 1)
    pools,              # per-LAYER pool slices (bf16 or int8 keys)
    block_tables,       # [B, max_pages] int32, -1 = unassigned
    positions,          # decode: [B] (or scalar); chunk/verify: [B, C]
    *,
    scale,
    window: int = 0,
    kv_heads=None,
    max_pages=None,
    variant: str = "decode",
    extra_k=None,       # verify: in-flight chunk K rows [B, C, Hkv, D]
    extra_v=None,
):
    """Paged attention via a pages-held-only gather + the dense cached
    attention, op for op.

    ``variant`` selects which dense reference to replicate — decode and
    chunk differ in precision placement (decode keeps probs f32 through
    the PV einsum; chunk casts probs to q.dtype first, mirroring
    ``mha_reference``) and must not be mixed or bf16 bitwise parity
    breaks. Output `[B, C, H, D]` in q.dtype. Masked/garbage pages
    (trash, beyond a slot's length) contribute exact zeros through the
    f32 softmax, so slicing the walk to ``max_pages`` held pages is
    invisible to the math — the same argument as the engine's dense
    parity pin.

    ``variant="verify"`` is the speculative-decoding verify step: the C
    queries are the draft chunk, whose K/V rows (``extra_k``/``extra_v``,
    at positions ``positions`` themselves) are IN-FLIGHT — appended as
    extra keys after the committed pages instead of written to the
    pools, so rejected draft rows never touch page storage. Per query
    it runs the DECODE variant's math (grouped heads, probs f32 through
    PV): committed keys mask at ``kpos < positions[:, 0]`` (pool cells
    at chunk positions may hold a previous tenant's stale rows) and
    in-flight key i serves query j iff i <= j. The nonzero softmax
    lanes are the same values in the same order as sequential
    write-then-attend decode steps, so bf16 verify logits are bitwise
    equal to the spec-off decode path (pinned by the serving tests).
    """
    b, c, h, d = q.shape
    k, v = gather_pages(pools, block_tables, kv_heads=kv_heads,
                        max_pages=max_pages, dtype=q.dtype)
    s_len = k.shape[1]
    hkv = k.shape[2]
    kpos = jnp.arange(s_len)
    if variant == "verify":
        if extra_k is None or extra_v is None:
            raise ValueError("verify variant needs extra_k/extra_v rows")
        positions = jnp.asarray(positions)
        if positions.ndim != 2:
            raise ValueError("verify variant needs per-query positions "
                             "[B, C]")
        start = positions[:, 0]
        groups = h // hkv
        qg = q.reshape(b, c, hkv, groups, d)
        kf = jnp.concatenate(
            [k.astype(jnp.float32), extra_k.astype(jnp.float32)], axis=1
        )
        vf = jnp.concatenate(
            [v.astype(jnp.float32), extra_v.astype(jnp.float32)], axis=1
        )
        # key positions: committed rows at their cell index, in-flight
        # rows at the chunk positions
        key_pos = jnp.concatenate(
            [jnp.broadcast_to(kpos, (b, s_len)), positions], axis=1
        )
        committed = jnp.concatenate(
            [jnp.ones((b, s_len), bool), jnp.zeros((b, c), bool)], axis=1
        )
        mask = key_pos[:, None, :] <= positions[:, :, None]
        mask = mask & (~committed | (key_pos < start[:, None]))[:, None, :]
        if window:
            mask = mask & (
                key_pos[:, None, :] > positions[:, :, None] - window
            )
        s = jnp.einsum(
            "bckgd,bskd->bckgs", qg.astype(jnp.float32), kf
        ) * scale
        s = jnp.where(mask[:, :, None, None, :], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bckgs,bskd->bckgd", p, vf)
        return out.reshape(b, c, h, d).astype(q.dtype)
    if variant == "decode":
        if c != 1:
            raise ValueError("decode variant takes a single query (C=1)")
        groups = h // hkv
        qg = q.reshape(b, hkv, groups, d)
        s = jnp.einsum(
            "bkgd,bskd->bkgs",
            qg.astype(jnp.float32),
            k.astype(jnp.float32),
        ) * scale
        pos = jnp.asarray(positions)
        if pos.ndim == 0:
            mask = kpos <= pos
            if window:
                mask = mask & (kpos > pos - window)
            s = jnp.where(mask[None, None, None, :], s, NEG_INF)
        else:
            mask = kpos[None, :] <= pos[:, None]
            if window:
                mask = mask & (kpos[None, :] > pos[:, None] - window)
            s = jnp.where(mask[:, None, None, :], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bkgs,bskd->bkgd", p, v.astype(jnp.float32))
        return out.reshape(b, 1, h, d).astype(q.dtype)
    if variant != "chunk":
        raise ValueError(f"variant must be decode|chunk, got {variant!r}")
    if jnp.asarray(positions).ndim != 2:
        raise ValueError("chunk variant needs per-query positions [B, C]")
    if hkv != h:
        k = _repeat_kv(k, h // hkv)
        v = _repeat_kv(v, h // hkv)
    if jax.default_backend() == "cpu":
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk",
            q.astype(jnp.float32),
            k.astype(jnp.float32),
        )
    else:
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
    logits = logits * scale
    mask = kpos[None, None, :] <= positions[:, :, None]
    if window:
        mask = mask & (kpos[None, None, :] > positions[:, :, None] - window)
    logits = jnp.where(mask[:, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# Fused kernel: one grid program per (slot, physical page)
# ---------------------------------------------------------------------------


def _paged_kernel(
    # scalar prefetch (SMEM)
    tab_ref,            # [B, W] int32 block tables
    pos_ref,            # [B, C] int32 query positions
    # VMEM blocks
    q_ref,              # [1, C, H, D]
    *refs,
    page_size,
    scale,
    window,
    hkv,
    groups,
    n_q,
    int8,
    out_dtype,
    verify=False,
):
    """Fold one physical page into every query row of one slot.

    Grid is (B, W): program (b, j) loads the page ``tab[b, j]`` names
    (clamped to the trash page when unassigned — its garbage is masked
    below), dequantizes int8 payloads in-register, and advances the
    flash-style running (max, sum, acc) state per kv head. The page
    walk is the ONLY K/V traffic: nothing the width of the block table
    is ever materialized.

    ``verify=True`` is the speculative-decoding verify step: the grid
    grows one extra column (B, W+1) whose last program folds the
    IN-FLIGHT draft-chunk K/V block (an extra VMEM operand, never
    resident in the pools) instead of a page; committed pages mask at
    ``kpos < start`` so stale rows at chunk positions are invisible,
    and in-flight key i serves query row j iff i <= j (causal within
    the chunk).
    """
    if verify:
        if int8:
            (kq_ref, ks_ref, vq_ref, vs_ref, ink_ref, inv_ref,
             o_ref, m_scr, l_scr, acc_scr) = refs
        else:
            (k_ref, v_ref, ink_ref, inv_ref,
             o_ref, m_scr, l_scr, acc_scr) = refs
    elif int8:
        kq_ref, ks_ref, vq_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    c = n_q // groups
    d = q_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # query positions for this slot, expanded to rows (c, g) — element
    # reads so SMEM access stays scalar on real hardware
    pos_rows = jnp.stack(
        [pos_ref[b, r // groups] for r in range(n_q)]
    )  # [n_q] int32
    max_pos = pos_ref[b, c - 1]
    min_pos = pos_ref[b, 0]

    def _fold_block(k, v, allowed):
        """Advance the running (max, sum, acc) state by one key block
        ``k``/``v`` [rows, hkv, d] under mask ``allowed`` [n_q, rows]."""
        for kh in range(hkv):
            # row order: q is [C, H, D] with H = hkv*groups kv-major, so
            # kv head kh owns columns [kh*groups, (kh+1)*groups) of H
            # for every chunk row c → gather those into [c*groups, d].
            # ``allowed`` is (c, g)-major too (masks depend only on the
            # chunk row), so it serves every head unchanged.
            q_h = q_ref[0, :, kh * groups:(kh + 1) * groups, :]
            q_h = q_h.reshape(c * groups, d).astype(jnp.float32)
            k_h = k[:, kh, :].astype(jnp.float32)  # [rows, d]
            v_h = v[:, kh, :].astype(jnp.float32)
            s = jax.lax.dot_general(
                q_h, k_h,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [c·g, rows]
            s = jnp.where(allowed, s, NEG_INF)
            m_prev = m_scr[kh][:, :1]
            l_prev = l_scr[kh][:, :1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            # zero masked probs explicitly: an all-masked page would
            # otherwise contribute exp(NEG_INF - NEG_INF) = 1 per lane
            p = jnp.where(allowed, jnp.exp(s - m_new), 0.0)
            l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
            pv = jax.lax.dot_general(
                p, v_h,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_scr[kh] = acc_scr[kh] * alpha + pv
            m_scr[kh] = jnp.broadcast_to(m_new, m_scr[kh].shape)
            l_scr[kh] = jnp.broadcast_to(l_new, l_scr[kh].shape)

    # the last grid column of a verify walk is the in-flight block, not
    # a page — clamp the table read so it never indexes out of bounds
    tab_w = tab_ref.shape[1]
    jt = jnp.minimum(j, tab_w - 1)
    page_ok = jnp.logical_and(tab_ref[b, jt] >= 0, j * page_size <= max_pos)
    if verify:
        # committed pages only hold usable rows BELOW the chunk start
        # (cells at chunk positions may be a previous tenant's stale
        # rows); the in-flight column handles the rest
        page_ok = jnp.logical_and(page_ok, j * page_size < min_pos)
        page_ok = jnp.logical_and(page_ok, j < nj - 1)
    if window:
        # page overlaps [min_pos - window + 1, max_pos]
        page_ok = jnp.logical_and(
            page_ok, (j + 1) * page_size - 1 > min_pos - window
        )

    @pl.when(page_ok)
    def _fold():
        if int8:
            # in-register dequant against the per-block f32 scales;
            # round-trip through the compute dtype so values match what
            # kv_decode_rows hands the reference path
            ks = ks_ref[0]  # [ps, n_blocks] f32
            vs = vs_ref[0]
            k = (kq_ref[0].astype(jnp.float32) * ks[..., None])
            v = (vq_ref[0].astype(jnp.float32) * vs[..., None])
            k = k.reshape(page_size, hkv, d).astype(out_dtype)
            v = v.reshape(page_size, hkv, d).astype(out_dtype)
        else:
            k = k_ref[0]  # [ps, hkv, d]
            v = v_ref[0]
        kpos = (
            jax.lax.broadcasted_iota(jnp.int32, (n_q, page_size), 1)
            + j * page_size
        )
        allowed = kpos <= pos_rows[:, None]
        if verify:
            allowed = jnp.logical_and(allowed, kpos < min_pos)
        if window:
            allowed = jnp.logical_and(
                allowed, kpos > pos_rows[:, None] - window
            )
        _fold_block(k, v, allowed)

    if verify:

        @pl.when(j == nj - 1)
        def _fold_inflight():
            kpos_in = jnp.stack(
                [pos_ref[b, i] for i in range(c)]
            )  # [C] int32 — the chunk positions themselves
            allowed = kpos_in[None, :] <= pos_rows[:, None]  # [n_q, C]
            if window:
                allowed = jnp.logical_and(
                    allowed, kpos_in[None, :] > pos_rows[:, None] - window
                )
            _fold_block(ink_ref[0], inv_ref[0], allowed)

    @pl.when(j == nj - 1)
    def _finish():
        for kh in range(hkv):
            l = l_scr[kh][:, :1]
            l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → 0 out
            out = (acc_scr[kh] / l).reshape(c, groups, d)
            o_ref[0, :, kh * groups:(kh + 1) * groups, :] = out.astype(
                o_ref.dtype
            )


def _paged_call(q, pools, tables, positions, *, scale, window, kv_heads,
                variant, interpret, extra_k=None, extra_v=None):
    mode, ps, hkv, d = _pool_info(pools, kv_heads)
    b, c, h, _ = q.shape
    groups = h // hkv
    w = tables.shape[1]
    n_q = c * groups
    verify = variant == "verify"

    kernel = functools.partial(
        _paged_kernel,
        page_size=ps,
        scale=scale,
        window=window,
        hkv=hkv,
        groups=groups,
        n_q=n_q,
        int8=(mode == "int8"),
        out_dtype=q.dtype,
        verify=verify,
    )

    # a verify walk has one extra grid column (the in-flight block) —
    # clamp the table read in every index map so it stays in bounds
    jw = w - 1
    q_spec = pl.BlockSpec((1, c, h, d), lambda i, j, tab, pos: (i, 0, 0, 0))
    if mode == "bf16":
        pool_args = (pools["k"], pools["v"])
        pool_specs = [
            pl.BlockSpec(
                (1, ps, hkv, d),
                lambda i, j, tab, pos: (
                    jnp.maximum(tab[i, jnp.minimum(j, jw)], 0), 0, 0, 0
                ),
            )
            for _ in range(2)
        ]
    else:
        nb, blk = pools["k_q"].shape[-2:]
        pool_args = (pools["k_q"], pools["k_scale"],
                     pools["v_q"], pools["v_scale"])
        qspec = pl.BlockSpec(
            (1, ps, nb, blk),
            lambda i, j, tab, pos: (
                jnp.maximum(tab[i, jnp.minimum(j, jw)], 0), 0, 0, 0
            ),
        )
        sspec = pl.BlockSpec(
            (1, ps, nb),
            lambda i, j, tab, pos: (
                jnp.maximum(tab[i, jnp.minimum(j, jw)], 0), 0, 0
            ),
        )
        pool_specs = [qspec, sspec, qspec, sspec]

    extra_args = ()
    extra_specs = []
    if verify:
        if extra_k is None or extra_v is None:
            raise ValueError("verify variant needs extra_k/extra_v rows")
        extra_args = (extra_k, extra_v)
        extra_specs = [
            pl.BlockSpec((1, c, hkv, d),
                         lambda i, j, tab, pos: (i, 0, 0, 0))
            for _ in range(2)
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, w + 1) if verify else (b, w),
        in_specs=[q_spec] + pool_specs + extra_specs,
        out_specs=pl.BlockSpec((1, c, h, d),
                               lambda i, j, tab, pos: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, n_q, 128), jnp.float32),  # running max
            pltpu.VMEM((hkv, n_q, 128), jnp.float32),  # running sum
            pltpu.VMEM((hkv, n_q, d), jnp.float32),    # f32 accumulator
        ],
    )
    compiler_params = (
        None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        )
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, c, h, d), q.dtype),
        compiler_params=compiler_params,
        interpret=interpret,
    )(tables, positions, q, *pool_args, *extra_args)
    return out


def paged_attention(
    q,
    pools,
    block_tables,
    positions,
    *,
    scale,
    window: int = 0,
    kv_heads=None,
    max_pages=None,
    variant: str = "decode",
    interpret=None,
    extra_k=None,
    extra_v=None,
):
    """Paged attention over block-table KV pools — fused when it can be.

    Dispatch mirrors the other Pallas ops: the kernel runs on real TPUs
    or under interpret mode; everywhere else the jnp reference runs
    (still touching only ``max_pages`` held pages, and carrying the
    bf16 bitwise-parity contract). The kernel accumulates in f32 with
    online softmax, so it matches the reference to float tolerance, not
    bitwise — CPU serving keeps bitwise pins because CPU dispatch IS
    the reference.

    ``variant="verify"`` (speculative decoding) additionally takes the
    draft chunk's in-flight ``extra_k``/``extra_v`` rows [B, C, Hkv, D];
    they are folded as keys WITHOUT ever touching the pools, so a
    rejected draft row leaves no trace in page storage.
    """
    interpret = INTERPRET if interpret is None else interpret
    if pltpu is None or not (_on_tpu() or interpret):
        return paged_attention_reference(
            q, pools, block_tables, positions, scale=scale, window=window,
            kv_heads=kv_heads, max_pages=max_pages, variant=variant,
            extra_k=extra_k, extra_v=extra_v,
        )
    tables = (
        block_tables if max_pages is None else block_tables[:, :max_pages]
    )
    pos = jnp.asarray(positions, jnp.int32)
    b, c = q.shape[0], q.shape[1]
    if pos.ndim == 0:
        pos = jnp.broadcast_to(pos, (b,))
    if pos.ndim == 1:
        pos = pos[:, None]
    if pos.shape != (b, c):
        raise ValueError(
            f"positions {pos.shape} must broadcast to queries {(b, c)}"
        )
    return _paged_call(
        q, pools, jnp.asarray(tables, jnp.int32), pos, scale=scale,
        window=window, kv_heads=kv_heads, variant=variant,
        interpret=interpret, extra_k=extra_k, extra_v=extra_v,
    )
