"""fp8 matmul with delayed scaling (TransformerEngine recipe, TPU-native).

Reference capability: atorch's fp8 path
(auto/opt_lib/amp_optimization.py:197 — TransformerEngine fp8 autocast
with a DelayedScaling recipe). Here the same numerics are expressed
functionally: forward operands quantize to e4m3, gradients to e5m2,
each with a per-tensor scale derived from a rolling amax history
(delayed scaling — the scale for step t comes from steps < t, so
quantization never serializes on the current tensor's max).

State threading uses the Flax fp8-einsum convention: the fp8 state is a
differentiable INPUT whose "cotangent" carries the UPDATED state out of
the backward pass (the only place the gradient's amax is observable) —

    out = fp8_dot(x, w, state)
    grads = jax.grad(loss, argnums=(0, 1, 2))(x, w, state)
    new_state = grads[2]          # updated amax histories, not a grad

On fp8 hardware (Trillium/v6e+, see accelerate.device_context) the
quantized operands feed the MXU directly; elsewhere the dot upcasts the
ALREADY-QUANTIZED values to bf16, so numerics are identical everywhere
and speed follows hardware support. Strategy hook: the "fp8" entry in
accelerate.strategy gates on ``device_context.fp8_supported()``.
"""

import functools
from typing import Dict

import jax
import jax.numpy as jnp

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2
E4M3_MAX = 448.0
E5M2_MAX = 57344.0
AMAX_HISTORY = 16


def init_fp8_state() -> Dict[str, jax.Array]:
    """Per-GEMM delayed-scaling state: amax histories for the forward
    operands (e4m3) and the incoming gradient (e5m2)."""
    return {
        "amax_x": jnp.ones((AMAX_HISTORY,), jnp.float32),
        "amax_w": jnp.ones((AMAX_HISTORY,), jnp.float32),
        "amax_g": jnp.ones((AMAX_HISTORY,), jnp.float32),
    }


def _scale_from_history(hist: jax.Array, fmax: float) -> jax.Array:
    """Delayed scale: map the history's max amax onto the format max."""
    amax = jnp.maximum(jnp.max(hist), 1e-12)
    return amax / fmax


def _push_amax(hist: jax.Array, x: jax.Array) -> jax.Array:
    cur = jnp.max(jnp.abs(x)).astype(jnp.float32)[None]
    return jnp.concatenate([hist[1:], cur])


def quantize_fp8(x: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    fmax = E4M3_MAX if dtype == E4M3 else E5M2_MAX
    return jnp.clip(
        x.astype(jnp.float32) / scale, -fmax, fmax
    ).astype(dtype)


def _dot(a_q, b_q, native: bool):
    if not native:
        # pre-fp8 hardware: same quantized VALUES, bf16 MXU path
        a_q = a_q.astype(jnp.bfloat16)
        b_q = b_q.astype(jnp.bfloat16)
    return jax.lax.dot_general(
        a_q, b_q, (((a_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _resolve_native(native):
    if native is not None:
        return bool(native)
    from dlrover_tpu.accelerate.device_context import kernel_capabilities

    return kernel_capabilities().fp8_native


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fp8_dot(x, w, state, native=None):
    """``x @ w`` with fp8 operands and delayed scaling.

    x: [..., K], w: [K, N], ``state`` from ``init_fp8_state``. Returns
    out [..., N] in x.dtype. Differentiating w.r.t. ``state`` yields the
    UPDATED state (see module docstring), never a real gradient.
    ``native=None`` probes the hardware (device_context.fp8_supported):
    fp8 operands feed the MXU directly on v6e+, bf16-upcast of the same
    quantized values elsewhere."""
    out, _ = _fp8_fwd_impl(x, w, state, _resolve_native(native))
    return out


def _fp8_fwd_impl(x, w, state, native):
    sx = _scale_from_history(state["amax_x"], E4M3_MAX)
    sw = _scale_from_history(state["amax_w"], E4M3_MAX)
    qx = quantize_fp8(x, sx, E4M3)
    qw = quantize_fp8(w, sw, E4M3)
    out = (_dot(qx, qw, native) * (sx * sw)).astype(x.dtype)
    return out, (qx, qw, sx, sw)


def _fp8_fwd(x, w, state, native):
    native = _resolve_native(native)
    out, (qx, qw, sx, sw) = _fp8_fwd_impl(x, w, state, native)
    res = (
        qx,
        qw,
        sx,
        sw,
        state,
        _push_amax(state["amax_x"], x),
        _push_amax(state["amax_w"], w),
        jnp.zeros((0,), x.dtype),  # dtype carriers (residuals must be
        jnp.zeros((0,), w.dtype),  # jax types, not raw dtypes)
    )
    return out, res


def _fp8_bwd(native, res, g):
    native = _resolve_native(native)
    qx, qw, sx, sw, state, hist_x, hist_w, xdt0, wdt0 = res
    xdt, wdt = xdt0.dtype, wdt0.dtype
    sg = _scale_from_history(state["amax_g"], E5M2_MAX)
    qg = quantize_fp8(g, sg, E5M2)
    dx = (_dot(qg, qw.T, native) * (sg * sw)).astype(xdt)
    x2d = qx.reshape(-1, qx.shape[-1])
    g2d = qg.reshape(-1, qg.shape[-1])
    dw = (_dot(x2d.T, g2d, native) * (sx * sg)).astype(wdt)
    new_state = {
        "amax_x": hist_x,
        "amax_w": hist_w,
        "amax_g": _push_amax(state["amax_g"], g),
    }
    return dx, dw, new_state


fp8_dot.defvjp(_fp8_fwd, _fp8_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fp8_dot_current(x, w, native=None):
    """``x @ w`` with fp8 operands and CURRENT scaling (TE's
    Float8CurrentScaling recipe): each tensor quantizes against its own
    amax, computed in-line — no delayed-scaling state.

    This is the fp8 path for pipeline-parallel meshes, where the
    state-on-cotangent convention of ``fp8_dot`` is unsound: the
    pipeline runs every microbatch through the same layer inside ONE
    forward, so the per-layer state's cotangent is the SUM of m updated
    histories (and bubble ticks contribute further garbage pushes) —
    summed amax histories are not a state. Current scaling has no state
    to corrupt and costs one extra reduction per operand, cheap next to
    the GEMM on TPU.
    """
    out, _ = _fp8_cur_fwd(x, w, _resolve_native(native))
    return out


def _cur_scale(t: jax.Array, fmax: float) -> jax.Array:
    amax = jnp.maximum(jnp.max(jnp.abs(t)).astype(jnp.float32), 1e-12)
    return amax / fmax


def _fp8_cur_fwd(x, w, native):
    sx = _cur_scale(x, E4M3_MAX)
    sw = _cur_scale(w, E4M3_MAX)
    qx = quantize_fp8(x, sx, E4M3)
    qw = quantize_fp8(w, sw, E4M3)
    out = (_dot(qx, qw, native) * (sx * sw)).astype(x.dtype)
    return out, (qx, qw, sx, sw,
                 jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype))


def _fp8_cur_bwd(native, res, g):
    native = _resolve_native(native)
    qx, qw, sx, sw, xdt0, wdt0 = res
    sg = _cur_scale(g, E5M2_MAX)
    qg = quantize_fp8(g, sg, E5M2)
    dx = (_dot(qg, qw.T, native) * (sg * sw)).astype(xdt0.dtype)
    x2d = qx.reshape(-1, qx.shape[-1])
    g2d = qg.reshape(-1, qg.shape[-1])
    dw = (_dot(x2d.T, g2d, native) * (sx * sg)).astype(wdt0.dtype)
    return dx, dw


def _fp8_cur_fwd_vjp(x, w, native):
    return _fp8_cur_fwd(x, w, _resolve_native(native))


fp8_dot_current.defvjp(_fp8_cur_fwd_vjp, _fp8_cur_bwd)


# ---- batched (per-expert) current scaling --------------------------------


def _bdot(a_q, b_q, native: bool):
    """[E,T,D]·[E,D,F] → [E,T,F] batched over the leading expert axis."""
    if not native:
        a_q = a_q.astype(jnp.bfloat16)
        b_q = b_q.astype(jnp.bfloat16)
    return jax.lax.dot_general(
        a_q, b_q, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )


def _cur_scale_per_expert(t: jax.Array, fmax: float) -> jax.Array:
    """Per-expert scale for stacked weights [E, ·, ·] → [E]: expert
    weight magnitudes diverge as routing specializes, so one shared
    scale would waste dynamic range on every small-weight expert."""
    amax = jnp.maximum(
        jnp.max(jnp.abs(t), axis=(1, 2)).astype(jnp.float32), 1e-12
    )
    return amax / fmax


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fp8_batched_dot_current(x, w, native=None):
    """Expert-batched ``einsum('etd,edf->etf')`` with fp8 operands and
    CURRENT scaling — the fp8 path for MoE expert FFN GEMMs (reference:
    TE fp8 is not dense-only, amp_optimization.py:197).

    Tokens/grads use one per-tensor scale (they are one routed batch);
    the stacked expert weights get a PER-EXPERT scale. Stateless like
    ``fp8_dot_current``, so it composes with any mesh incl. pipeline —
    and with the dropless ragged path being token-count-dynamic, the
    ragged lowering intentionally stays bf16 (``lax.ragged_dot`` has no
    scaled-fp8 lowering; quantizing there would be fake-quant cost with
    no MXU win).
    """
    out, _ = _fp8_bcur_fwd(x, w, _resolve_native(native))
    return out


def _fp8_bcur_fwd(x, w, native):
    sx = _cur_scale(x, E4M3_MAX)
    sw = _cur_scale_per_expert(w, E4M3_MAX)
    qx = quantize_fp8(x, sx, E4M3)
    qw = quantize_fp8(w, sw[:, None, None], E4M3)
    out = (_bdot(qx, qw, native) * (sx * sw)[:, None, None]).astype(
        x.dtype
    )
    return out, (qx, qw, sx, sw,
                 jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype))


def _fp8_bcur_bwd(native, res, g):
    native = _resolve_native(native)
    qx, qw, sx, sw, xdt0, wdt0 = res
    sg = _cur_scale(g, E5M2_MAX)
    qg = quantize_fp8(g, sg, E5M2)
    # dx_e = qg_e @ qw_e^T : [E,T,F]·[E,D,F] contracting F
    dx_q = jax.lax.dot_general(
        qg if native else qg.astype(jnp.bfloat16),
        qw if native else qw.astype(jnp.bfloat16),
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    dx = (dx_q * (sg * sw)[:, None, None]).astype(xdt0.dtype)
    # dw_e = qx_e^T @ qg_e : [E,T,D]·[E,T,F] contracting T
    dw_q = jax.lax.dot_general(
        qx if native else qx.astype(jnp.bfloat16),
        qg if native else qg.astype(jnp.bfloat16),
        (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    dw = (dw_q * (sx * sg)).astype(wdt0.dtype)
    return dx, dw


def _fp8_bcur_fwd_vjp(x, w, native):
    return _fp8_bcur_fwd(x, w, _resolve_native(native))


fp8_batched_dot_current.defvjp(_fp8_bcur_fwd_vjp, _fp8_bcur_bwd)
