"""Pallas TPU fused norm kernels: rmsnorm / layernorm with an optional
fused residual add.

Why a kernel for a memory-bound op: with the matmul side saturated
(flash attention + fused CE, see docs/performance.md), the residue of
the step is elementwise HBM traffic. XLA lowers the jnp norm as a
reduce pass plus a broadcast-apply pass, and the residual add that
precedes the second norm of every layer body is a third full
[B,S,d_model] round-trip (write x+attn, read it back, write the normed
value). Here each grid program holds a row block in VMEM, computes the
f32 statistics and the normed output in one visit, and — when
``residual`` is passed — also emits the summed stream, so
``x + attn_out -> norm(...)`` costs one read and two writes instead of
three round-trips.

Numerics mirror ``models/decoder.py::_norm`` exactly: the (optional)
residual add happens in the input dtype, statistics are f32
(single-pass E[x], E[x^2] for layernorm), the output is cast back to
the input dtype. Padded-lane handling: a non-128-multiple last dim is
zero-padded at the jnp level — zero lanes contribute nothing to the
sums (the divisor is the TRUE dim), and the padded output lanes are
sliced off, so no in-kernel masking is needed.

Backward is a custom_vjp with row-local Pallas kernels that recompute
the statistics from the saved summed stream (cheaper than storing
per-row stats: in the fused-residual case the stream is a forward
OUTPUT already, so the residuals cost nothing extra). The per-program
scale/bias cotangent partials are summed at the jnp level.

Off-TPU the public entry point falls back to the jnp reference; the
``INTERPRET`` hook (or the ``DLROVER_TPU_PALLAS_INTERPRET`` env var,
which also flips ``pallas_attention``) runs the real kernels through
the pallas interpreter so the CPU test mesh exercises the kernel path.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds of jaxlib
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from dlrover_tpu.ops.pallas_attention import _on_tpu

# test hook: run every kernel in pallas interpret mode (CPU-executable).
# Seeded from the environment so a whole pytest run can flip it without
# monkeypatching each module.
INTERPRET = os.environ.get(
    "DLROVER_TPU_PALLAS_INTERPRET", ""
).lower() in ("1", "true", "yes")

# eps defaults matching models/decoder.py::_norm — the decoder wires
# this module in WITHOUT passing eps, so these two constants are the
# single point of truth shared by kernel and fallback
RMS_EPS = 1e-6
LN_EPS = 1e-5

# per-program f32 row-block VMEM budget: bounds [rows, dp] f32
# transients to ~2 MB each (the kernel holds a handful alongside the
# input-dtype block), far under the ~16 MB VMEM/core
_ROW_BLOCK_BYTES = 2 * 1024 * 1024


def kernels_available(interpret=None) -> bool:
    """True when the Pallas path would actually run (real TPU or
    interpret mode) — what ``cfg.fused_norm=None`` (auto) keys off."""
    interpret = INTERPRET if interpret is None else interpret
    return pltpu is not None and (_on_tpu() or interpret)


def _fit_rows(n: int, dp: int, dtype) -> int:
    """Rows per grid program: largest power-of-two block that divides
    the row count, respects the dtype's min sublane tile, and keeps
    [rows, dp] f32 under the VMEM budget. None = shape untileable
    (fall back to the jnp reference)."""
    min_rows = 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8
    budget = _ROW_BLOCK_BYTES // (4 * dp)
    for bn in (512, 256, 128, 64, 32, 16, 8):
        if bn <= budget and bn >= min_rows and n % bn == 0:
            return bn
    return None


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, kind, eps, d, has_bias, has_res):
    it = iter(refs)
    x_ref = next(it)
    scale_ref = next(it)
    bias_ref = next(it) if has_bias else None
    res_ref = next(it) if has_res else None
    out_ref = next(it)
    h_ref = next(it) if has_res else None

    x = x_ref[...]
    if has_res:
        # input-dtype add, matching the jnp path's `x = x + attn`
        x = x + res_ref[...]
        h_ref[...] = x
    x32 = x.astype(jnp.float32)
    s32 = scale_ref[...].astype(jnp.float32)
    if kind == "rmsnorm":
        # padded lanes are zero: they add nothing to the sum, and the
        # divisor is the true dim
        ms = jnp.sum(x32 * x32, axis=-1, keepdims=True) / d
        out = x32 * jax.lax.rsqrt(ms + eps) * s32
    else:
        mean = jnp.sum(x32, axis=-1, keepdims=True) / d
        ex2 = jnp.sum(x32 * x32, axis=-1, keepdims=True) / d
        var = jnp.maximum(ex2 - mean * mean, 0.0)
        out = (x32 - mean) * jax.lax.rsqrt(var + eps) * s32
        if has_bias:
            out = out + bias_ref[...].astype(jnp.float32)
    out_ref[...] = out.astype(out_ref.dtype)


def _bwd_kernel(*refs, kind, eps, d, has_bias, has_res):
    it = iter(refs)
    g_ref = next(it)
    h_ref = next(it)
    scale_ref = next(it)
    gh_ref = next(it) if has_res else None
    dx_ref = next(it)
    ds_ref = next(it)
    db_ref = next(it) if has_bias else None

    g32 = g_ref[...].astype(jnp.float32)
    h32 = h_ref[...].astype(jnp.float32)
    s32 = scale_ref[...].astype(jnp.float32)
    # recompute the f32 statistics from the saved stream — one VPU
    # reduction instead of storing per-row stats in HBM
    if kind == "rmsnorm":
        ms = jnp.sum(h32 * h32, axis=-1, keepdims=True) / d
        r = jax.lax.rsqrt(ms + eps)
        gx = g32 * s32
        dot = jnp.sum(gx * h32, axis=-1, keepdims=True) / d
        dx = r * gx - (r * r * r) * dot * h32
        ds_ref[...] = jnp.sum(g32 * h32 * r, axis=0, keepdims=True)
    else:
        mean = jnp.sum(h32, axis=-1, keepdims=True) / d
        ex2 = jnp.sum(h32 * h32, axis=-1, keepdims=True) / d
        var = jnp.maximum(ex2 - mean * mean, 0.0)
        r = jax.lax.rsqrt(var + eps)
        xhat = (h32 - mean) * r
        gx = g32 * s32
        m1 = jnp.sum(gx, axis=-1, keepdims=True) / d
        m2 = jnp.sum(gx * xhat, axis=-1, keepdims=True) / d
        dx = r * (gx - m1 - xhat * m2)
        ds_ref[...] = jnp.sum(g32 * xhat, axis=0, keepdims=True)
        if has_bias:
            db_ref[...] = jnp.sum(g32, axis=0, keepdims=True)
    if has_res:
        # the summed stream's own downstream cotangent folds in here so
        # backward too is one visit per row block
        dx = dx + gh_ref[...].astype(jnp.float32)
    dx_ref[...] = dx.astype(dx_ref.dtype)


# ---------------------------------------------------------------------------
# custom_vjp (operates on [N, dp] padded 2-D views)
# ---------------------------------------------------------------------------


def _compiler_params(interpret):
    if interpret:
        return None
    return pltpu.CompilerParams(dimension_semantics=("parallel",))


def _call_fwd(kind, eps, dims, interpret, x, scale, bias, res):
    d, dp, bn = dims
    n = x.shape[0]
    has_bias = bias is not None
    has_res = res is not None
    row_spec = pl.BlockSpec((bn, dp), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, dp), lambda i: (0, 0))
    in_specs = [row_spec, vec_spec]
    inputs = [x, scale]
    if has_bias:
        in_specs.append(vec_spec)
        inputs.append(bias)
    if has_res:
        in_specs.append(row_spec)
        inputs.append(res)
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((n, dp), x.dtype)]
    if has_res:
        out_specs.append(row_spec)
        out_shape.append(jax.ShapeDtypeStruct((n, dp), x.dtype))
    outs = pl.pallas_call(
        functools.partial(
            _fwd_kernel, kind=kind, eps=eps, d=d,
            has_bias=has_bias, has_res=has_res,
        ),
        grid=(n // bn,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*inputs)
    if has_res:
        return outs[0], outs[1]
    return outs[0], x


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _norm_call(kind, eps, dims, interpret, x, scale, bias, res):
    out, h = _call_fwd(kind, eps, dims, interpret, x, scale, bias, res)
    return (out, h) if res is not None else out


def _norm_call_fwd(kind, eps, dims, interpret, x, scale, bias, res):
    out, h = _call_fwd(kind, eps, dims, interpret, x, scale, bias, res)
    primal = (out, h) if res is not None else out
    # h IS the residual set: in the fused-residual case it's already a
    # forward output (free), otherwise it's the input x
    return primal, (h, scale, bias, res is not None)


def _norm_call_bwd(kind, eps, dims, interpret, saved, g):
    d, dp, bn = dims
    h, scale, bias, has_res = saved
    has_bias = bias is not None
    if has_res:
        gout, gh = g
    else:
        gout, gh = g, None
    n = h.shape[0]
    grid = n // bn
    row_spec = pl.BlockSpec((bn, dp), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, dp), lambda i: (0, 0))
    part_spec = pl.BlockSpec((1, dp), lambda i: (i, 0))
    in_specs = [row_spec, row_spec, vec_spec]
    inputs = [gout, h, scale]
    if has_res:
        in_specs.append(row_spec)
        inputs.append(gh)
    out_specs = [row_spec, part_spec]
    out_shape = [
        jax.ShapeDtypeStruct((n, dp), h.dtype),
        jax.ShapeDtypeStruct((grid, dp), jnp.float32),
    ]
    if has_bias:
        out_specs.append(part_spec)
        out_shape.append(jax.ShapeDtypeStruct((grid, dp), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(
            _bwd_kernel, kind=kind, eps=eps, d=d,
            has_bias=has_bias, has_res=has_res,
        ),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*inputs)
    dx = outs[0]
    dscale = outs[1].sum(axis=0, keepdims=True).astype(scale.dtype)
    dbias = (
        outs[2].sum(axis=0, keepdims=True).astype(bias.dtype)
        if has_bias
        else None
    )
    # d(x + res)/dx = d(x + res)/dres = identity: both get the stream
    # cotangent (gh already folded into dx inside the kernel)
    dres = dx if has_res else None
    return dx, dscale, dbias, dres


_norm_call.defvjp(_norm_call_fwd, _norm_call_bwd)


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


def _reference(x, scale, bias, kind, eps, residual):
    """jnp fallback — the exact math of models/decoder.py::_norm (with
    the pre-norm residual add in the input dtype when fused)."""
    h = x + residual if residual is not None else x
    x32 = h.astype(jnp.float32)
    if kind == "rmsnorm":
        rms = jax.lax.rsqrt(
            jnp.mean(x32 * x32, -1, keepdims=True) + eps
        )
        out = x32 * rms * scale.astype(jnp.float32)
    else:
        mean = jnp.mean(x32, -1, keepdims=True)
        ex2 = jnp.mean(x32 * x32, -1, keepdims=True)
        var = jnp.maximum(ex2 - mean * mean, 0.0)
        out = (x32 - mean) * jax.lax.rsqrt(var + eps)
        out = out * scale.astype(jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
    out = out.astype(x.dtype)
    return (out, h) if residual is not None else out


def norm(
    x,
    scale,
    bias=None,
    kind: str = "rmsnorm",
    *,
    residual=None,
    eps: float = None,
    interpret: bool = None,
):
    """Fused norm over the last axis of ``x`` ([..., D]).

    Without ``residual``: returns ``norm(x)``. With ``residual``:
    returns ``(norm(x + residual), x + residual)`` — the summed stream
    is emitted from the same kernel visit so the caller's residual
    carry costs no extra HBM round-trip.

    ``kind``: "rmsnorm" (bias ignored) | "layernorm". Off-TPU (and for
    untileable shapes) this is the jnp reference with identical
    numerics semantics (f32 statistics, output in ``x.dtype``).
    """
    if kind not in ("rmsnorm", "layernorm"):
        raise ValueError(f"unknown norm kind {kind!r}")
    interpret = INTERPRET if interpret is None else interpret
    if eps is None:
        eps = RMS_EPS if kind == "rmsnorm" else LN_EPS
    if kind == "rmsnorm":
        bias = None
    d = x.shape[-1]
    if not (pltpu is not None and (_on_tpu() or interpret)):
        return _reference(x, scale, bias, kind, eps, residual)
    n = math.prod(x.shape[:-1])
    dp = (d + 127) // 128 * 128
    bn = _fit_rows(n, dp, x.dtype)
    if bn is None:
        return _reference(x, scale, bias, kind, eps, residual)

    lead = x.shape[:-1]

    def rows(a):
        a = a.reshape(n, d)
        if dp != d:
            a = jnp.pad(a, ((0, 0), (0, dp - d)))
        return a

    def vec(a):
        a = a.reshape(1, d)
        if dp != d:
            a = jnp.pad(a, ((0, 0), (0, dp - d)))
        return a

    def unrows(a):
        if dp != d:
            a = a[:, :d]
        return a.reshape(lead + (d,))

    out = _norm_call(
        kind,
        eps,
        (d, dp, bn),
        interpret,
        rows(x),
        vec(scale),
        vec(bias) if bias is not None else None,
        rows(residual) if residual is not None else None,
    )
    if residual is not None:
        return unrows(out[0]), unrows(out[1])
    return unrows(out)
