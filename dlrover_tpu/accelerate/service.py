"""Acceleration-engine service: strategy search as a callable endpoint.

Reference: atorch's engine split — auto/engine/servicer.py (a gRPC
service running the strategy search/dryrun loop) with
auto/engine_client.py on the trainer side. TPU framing: the search
itself is analytic-first (accelerate/engine.py) and cheap, but the
split still earns its keep when (a) one search brain serves many jobs
(the Brain pairing), or (b) the measured modes should run somewhere
with a chip while the client is a CPU-only submitter. The transport is
the framework's own framed-JSON gRPC pair (common/comm.py) — no new
protocol, no pickling.

    server = EngineService(port=0)             # chip-side
    client = EngineClient(f"127.0.0.1:{server.port}")
    strategy, plan = client.search(cfg, n_devices=8, global_batch=32,
                                   seq=256, mode="heuristic")
"""

import dataclasses
import json

from dlrover_tpu.common.comm import (
    MasterTransportClient,
    MasterTransportServer,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common import messages as msgs
from dlrover_tpu.models.config import ModelConfig

logger = get_logger(__name__)


def _cfg_to_json(cfg: ModelConfig) -> str:
    return json.dumps(dataclasses.asdict(cfg))


def _cfg_from_json(raw: str) -> ModelConfig:
    return ModelConfig(**json.loads(raw))


class _EngineServicer:
    """report() is unused; get() answers StrategySearchRequest."""

    def report(self, msg) -> bool:  # pragma: no cover - protocol stub
        return True

    def get(self, msg):
        if not isinstance(msg, msgs.StrategySearchRequest):
            return None
        from dlrover_tpu.accelerate.engine import search_strategy
        from dlrover_tpu.accelerate.strategy import strategy_to_json

        try:
            cfg = _cfg_from_json(msg.model_config_json)
            strategy, plan = search_strategy(
                cfg,
                msg.n_devices,
                msg.global_batch,
                msg.seq,
                mode=msg.mode,
            )
        except Exception as e:  # noqa: BLE001
            logger.exception("strategy search failed")
            return msgs.StrategySearchResponse(error=str(e))
        return msgs.StrategySearchResponse(
            strategy_json=strategy_to_json(strategy)
        )


class EngineService:
    """Hosts the search engine behind the typed transport."""

    def __init__(self, port: int = 0):
        self._server = MasterTransportServer(_EngineServicer(), port=port)
        self._server.start()
        self.port = self._server.port

    def stop(self):
        self._server.stop()


class EngineClient:
    """Trainer-side: submit a model config, receive a strategy."""

    def __init__(self, addr: str, timeout_s: float = 120.0):
        self._t = MasterTransportClient(addr, timeout_s=timeout_s)

    def search(
        self,
        cfg: ModelConfig,
        n_devices: int,
        global_batch: int,
        seq: int,
        mode: str = "heuristic",
    ):
        """Returns (strategy, plan) exactly like engine.search_strategy."""
        from dlrover_tpu.accelerate.strategy import (
            apply_strategy,
            strategy_from_json,
        )

        resp = self._t.get(
            msgs.StrategySearchRequest(
                model_config_json=_cfg_to_json(cfg),
                n_devices=n_devices,
                global_batch=global_batch,
                seq=seq,
                mode=mode,
            )
        )
        if resp is None:
            raise RuntimeError("engine service unreachable")
        if resp.error:
            raise RuntimeError(f"strategy search failed: {resp.error}")
        strategy = strategy_from_json(resp.strategy_json)
        return strategy, apply_strategy(strategy)

    def close(self):
        self._t.close()
