"""``auto_accelerate``: one call from model config to an optimized,
sharded, jitted training setup.

Reference: atorch auto_accelerate (auto/accelerate.py:406) returning
(model, optim, dataloader, loss_func) after strategy search. TPU version
returns the mesh + jitted train step + state-init closure; the strategy is
serializable for the semi-automatic path (load_strategy ≡ pass
``strategy=`` explicitly).
"""

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.models.config import ModelConfig
from dlrover_tpu.accelerate.dry_runner import build_from_plan
from dlrover_tpu.accelerate.engine import search_strategy
from dlrover_tpu.accelerate.strategy import (
    AccelerationPlan,
    Strategy,
    apply_strategy,
    strategy_from_json,
)

logger = get_logger(__name__)


@dataclass
class AccelerateResult:
    mesh: Any
    model_config: ModelConfig
    strategy: Strategy
    plan: AccelerationPlan
    train_step: Callable          # (state, batch) -> (state, metrics)
    init_state: Callable          # (rng) -> sharded TrainState
    batch_sharding: Any
    eval_step: Optional[Callable] = None
    # the optimizer and the fully-configured TrainStepBuilder the plan
    # lowered to (sp attention override, offload_opt_state, grad_accum
    # all applied). To drive the plan through the high-level loop, hand
    # Trainer the full lowering: Trainer(..., optimizer=res.optimizer,
    # step_builder=res.step_builder, init_state_fn=res.init_state,
    # eval_step_fn=res.eval_step) — rebuilding from the raw plan fields
    # would drop the overrides (for eval too).
    optimizer: Any = None
    step_builder: Any = None


def auto_accelerate(
    cfg: ModelConfig,
    global_batch: int,
    seq: int,
    strategy: Optional[Strategy] = None,
    strategy_json: Optional[str] = None,
    search_mode: str = "heuristic",
    devices=None,
) -> AccelerateResult:
    devices = devices if devices is not None else jax.devices()
    if strategy_json is not None:
        strategy = strategy_from_json(strategy_json)
    if strategy is not None:
        plan = apply_strategy(strategy)
        logger.info("using provided strategy: %s", strategy)
    else:
        strategy, plan = search_strategy(
            cfg,
            len(devices),
            global_batch,
            seq,
            mode=search_mode,
            devices=devices,
        )

    mesh, builder, opt, bsh, cfg2 = build_from_plan(cfg, plan, devices)

    from dlrover_tpu.train import init_train_state
    from dlrover_tpu.train.train_step import build_eval_step

    def init_state(rng):
        return init_train_state(
            rng, cfg2, mesh, opt,
            offload_opt_state=plan.offload_opt_state,
        )

    return AccelerateResult(
        mesh=mesh,
        model_config=cfg2,
        strategy=strategy,
        plan=plan,
        train_step=builder.build(),
        init_state=init_state,
        batch_sharding=bsh,
        # builder.attn_impl carries the EFFECTIVE choice (sp meshes
        # override plan.attn_impl to the sp_mode) — eval must match
        eval_step=build_eval_step(
            cfg2, mesh, attn_impl=builder.attn_impl
        ),
        optimizer=opt,
        step_builder=builder,
    )
