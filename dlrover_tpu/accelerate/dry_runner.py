"""DryRunner: measure a strategy's actual step time.

Reference: atorch auto/dry_runner/dry_runner.py:12 (short profiled runs).
Additionally exposes XLA's compiled cost analysis — an analytic signal the
reference lacked — so candidate ranking can be done without running at all
(``cost_only=True``).
"""

import time
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.models.config import ModelConfig

logger = get_logger(__name__)


@dataclass
class DryRunResult:
    strategy_json: str
    ok: bool
    steps_per_sec: float = 0.0
    tokens_per_sec: float = 0.0
    compile_s: float = 0.0
    cost_flops: float = 0.0
    cost_bytes: float = 0.0
    error: str = ""


def build_from_plan(cfg: ModelConfig, plan, devices=None):
    """Lower a plan to (mesh, train_step, state, batch_sharding)."""
    import dataclasses as dc

    from dlrover_tpu.parallel.mesh import build_mesh
    from dlrover_tpu.train import (
        TrainStepBuilder,
        batch_sharding,
        init_train_state,
        make_optimizer,
    )

    from dlrover_tpu.parallel.pipeline import validate_pipeline_config

    devices = devices if devices is not None else jax.devices()
    validate_pipeline_config(cfg, plan.mesh)
    mesh = build_mesh(plan.mesh, devices=devices)
    cfg = dc.replace(
        cfg,
        dtype=plan.compute_dtype,
        param_dtype=plan.param_dtype,
        remat=plan.remat,
        fp8=plan.fp8,
    )
    # streamed offload (per-leaf HBM working set, see
    # streamed_offload_adamw) replaces the legacy whole-tree
    # device_put dance whenever the plan's optimizer supports it; the
    # builder-level flag remains only for optimizers without a
    # streaming implementation
    streamed = (
        plan.offload_opt_state
        and plan.optimizer == "adamw"
        and plan.optimizer_state_dtype is None
    )
    opt = make_optimizer(
        name=plan.optimizer,
        state_dtype=plan.optimizer_state_dtype,
        offload_states=streamed,
    )
    attn_impl = plan.attn_impl
    if plan.sp_mode in ("ring", "ulysses") and plan.mesh.sp != 1:
        attn_impl = plan.sp_mode
    builder = TrainStepBuilder(
        cfg,
        mesh,
        opt,
        grad_accum=plan.grad_accum,
        attn_impl=attn_impl,
        offload_opt_state=plan.offload_opt_state and not streamed,
        comm=getattr(plan, "comm_config", lambda: None)(),
    )
    return mesh, builder, opt, batch_sharding(mesh), cfg


def dry_run(
    cfg: ModelConfig,
    plan,
    global_batch: int,
    seq: int,
    steps: int = 5,
    warmup: int = 2,
    cost_only: bool = False,
    devices=None,
) -> DryRunResult:
    from dlrover_tpu.train import init_train_state

    sj = plan.to_json()
    try:
        mesh, builder, opt, bsh, cfg2 = build_from_plan(cfg, plan, devices)
        step_fn = builder.build()
        tokens = jnp.zeros((global_batch, seq), jnp.int32)
        batch = jax.device_put({"tokens": tokens, "targets": tokens}, bsh)

        t0 = time.perf_counter()
        state = init_train_state(
            jax.random.key(0), cfg2, mesh, opt,
            offload_opt_state=plan.offload_opt_state,
            comm=builder.comm_resolved,
        )
        if cost_only:
            lowered = jax.jit(builder.step_fn).lower(state, batch)
            compiled = lowered.compile()
            cost = compiled.cost_analysis() or {}
            return DryRunResult(
                strategy_json=sj,
                ok=True,
                compile_s=time.perf_counter() - t0,
                cost_flops=float(cost.get("flops", 0.0)),
                cost_bytes=float(cost.get("bytes accessed", 0.0)),
            )
        for _ in range(warmup):
            state, metrics = step_fn(state, batch)
        jax.block_until_ready(state)
        compile_s = time.perf_counter() - t0

        t1 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, batch)
        jax.block_until_ready(metrics["loss"])
        dt = time.perf_counter() - t1
        sps = steps / dt
        return DryRunResult(
            strategy_json=sj,
            ok=True,
            steps_per_sec=sps,
            tokens_per_sec=sps * global_batch * seq,
            compile_s=compile_s,
        )
    except Exception as e:  # noqa: BLE001 — infeasible strategies land here
        logger.info("dry run failed for %s: %s", sj, e)
        return DryRunResult(strategy_json=sj, ok=False, error=str(e)[:500])
