from dlrover_tpu.accelerate.api import (  # noqa: F401
    AccelerateResult,
    auto_accelerate,
)
from dlrover_tpu.accelerate.strategy import (  # noqa: F401
    AccelerationPlan,
    Strategy,
    OPTIMIZATION_LIBRARY,
)
from dlrover_tpu.accelerate.hpsearch import (  # noqa: F401
    BayesianOptimizer,
    Choice,
    Float,
    Int,
    SearchSpace,
)
