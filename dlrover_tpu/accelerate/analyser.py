"""Static model/plan analysis: parameter counts, memory feasibility.

Reference: atorch auto/analyser/analyser.py:14 (num params, module types)
+ device_context.py (GPU capability/memory). On TPU the analyser can be
exact about sharded memory: bytes = Σ params·dtype / (fsdp·tp shards) etc.,
so infeasible strategies are rejected before any compilation.
"""

from dataclasses import dataclass
from typing import Dict

import jax

from dlrover_tpu.models.config import ModelConfig
from dlrover_tpu.accelerate.strategy import AccelerationPlan

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1}

# optimizer state slots per param (mu, nu for adam family)
_OPT_SLOTS = {"adamw": 2, "adam": 2, "agd": 3, "sgd": 1, "lion": 1}
# extra slack multiplier on the streamed-offload working-set bound
# (transfer double-buffering of adjacent leaves in the chain)
OFFLOAD_OPT_LEAF_SLACK = 2.0
# legacy whole-tree offload (non-streaming optimizers): the transient
# device working set is unbounded in principle; budget a conservative
# half of the tree (pre-r3 behavior)
OFFLOAD_OPT_WORKING_SET = 0.5


def offload_streams(plan) -> bool:
    """Whether this plan's offload takes the per-leaf streamed path
    (train/optimizer.py streamed_offload_adamw) — must mirror
    dry_runner.build_from_plan's gate."""
    return (
        plan.offload_opt_state
        and plan.optimizer == "adamw"
        and plan.optimizer_state_dtype is None
    )


@dataclass
class AnalysisResult:
    num_params: int
    param_bytes_per_chip: float
    opt_bytes_per_chip: float
    grad_bytes_per_chip: float
    act_bytes_per_chip: float
    total_bytes_per_chip: float
    flops_per_token: float
    fits: bool
    hbm_bytes: float


def device_hbm_bytes() -> float:
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return float(limit)
    except Exception:  # noqa: BLE001
        pass
    kind = ""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        pass
    for key, gb in (
        ("v5p", 95),
        ("v5 lite", 16),
        ("v5e", 16),
        ("v6", 32),
        ("v4", 32),
    ):
        if key in kind:
            return gb * 1e9
    return 16e9


def analyse(
    cfg: ModelConfig,
    plan: AccelerationPlan,
    n_devices: int,
    batch_per_chip: int,
    seq: int,
    hbm_bytes: float = 0.0,
) -> AnalysisResult:
    sizes = plan.mesh.resolved_sizes(n_devices)
    n = cfg.num_params()
    pbytes = _DTYPE_BYTES.get(plan.param_dtype, 4)
    param_shards = max(1, sizes["fsdp"] * sizes["tp"] * sizes["pp"])

    param_b = n * pbytes / param_shards
    slots = _OPT_SLOTS.get(plan.optimizer, 2)
    opt_dtype_b = _DTYPE_BYTES.get(
        plan.optimizer_state_dtype or plan.param_dtype, pbytes
    )
    opt_b = n * slots * opt_dtype_b / param_shards
    if (
        getattr(plan, "update_sharding", False)
        and sizes["dp"] > 1
        and sizes["pp"] == 1
        and not plan.offload_opt_state
    ):
        # ZeRO update sharding: each dp rank owns 1/dp of the flattened
        # optimizer state, padded up to whole comm buckets
        # (parallel.sharding.PackPlan). Same gate as
        # resolve_update_sharding — it engages on pure-dp and hybrid
        # dp×fsdp / dp×tp meshes (pp still falls back). On hybrid
        # meshes the flat state is REPLICATED over the model axes and
        # sharded over dp only, so the moments' divisor is dp, not
        # dp × param_shards — fsdp's per-leaf opt sharding is traded
        # for the flat dp shard.
        bucket_b = getattr(plan, "comm_bucket_mb", 4.0) * 2**20
        opt_b = n * slots * opt_dtype_b / sizes["dp"] + slots * bucket_b
    if offload_streams(plan):
        # moments live in pinned host memory and the streamed update
        # (train/optimizer.py streamed_offload_adamw) serializes the
        # per-leaf transfers with optimization_barrier chaining, so the
        # device-resident moment working set is bounded by the LARGEST
        # LEAF's m+v (f32), not a fraction of the tree. Largest leaves:
        # the embedding [vocab, d] and the stacked mlp [L, d, ff].
        d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
        max_leaf = max(v * d, cfg.n_layer * d * f)
        opt_b = (
            OFFLOAD_OPT_LEAF_SLACK * slots * 4 * max_leaf / param_shards
        )
    elif plan.offload_opt_state:
        # non-streaming optimizer on the legacy whole-tree path: no
        # structural bound exists — keep the conservative budget
        opt_b *= OFFLOAD_OPT_WORKING_SET
    grad_b = n * pbytes / param_shards

    act_dtype_b = _DTYPE_BYTES.get(plan.compute_dtype, 2)
    tokens = batch_per_chip * seq
    if plan.remat == "full":
        # only layer-boundary activations are kept
        act_b = tokens * cfg.d_model * act_dtype_b * cfg.n_layer
    else:
        # rough: ~12 activation tensors per layer survive to the backward
        act_b = tokens * cfg.d_model * act_dtype_b * cfg.n_layer * 12
    act_b /= max(1, sizes["tp"] * sizes["sp"])
    # logits in f32 dominate for big vocabs
    act_b += tokens * cfg.vocab_size * 4 / max(1, sizes["tp"])
    if sizes["pp"] > 1:
        # pipeline_apply keeps the full per-stage batch (all microbatches)
        # as fp32 input + output accumulator on every pp stage — these
        # buffers do not shrink with pp
        act_b += 2 * tokens * cfg.d_model * 4

    hbm = hbm_bytes or device_hbm_bytes()
    total = (param_b + opt_b + grad_b + act_b) * 1.15  # fragmentation slack
    return AnalysisResult(
        num_params=n,
        param_bytes_per_chip=param_b,
        opt_bytes_per_chip=opt_b,
        grad_bytes_per_chip=grad_b,
        act_bytes_per_chip=act_b,
        total_bytes_per_chip=total,
        flops_per_token=cfg.flops_per_token(seq),
        fits=total < hbm * 0.92,
        hbm_bytes=hbm,
    )
