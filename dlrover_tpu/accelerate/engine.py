"""Strategy search engine.

Reference: atorch AccelerationEngine (auto/engine/acceleration_engine.py:13)
with Planner → candidate strategies, Executor → dryrun tasks, and HEBO
Bayesian optimisation over measured throughput.

TPU version: candidates are axis factorizations of the device count plus
remat/precision choices; infeasible ones are rejected analytically
(``analyser``), survivors are ranked either by a locality-aware heuristic
score (free), XLA compiled cost (cheap), or measured dry runs (exact).
"""

import itertools
from typing import List, Optional, Tuple

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.models.config import ModelConfig
from dlrover_tpu.accelerate.analyser import (
    analyse,
    device_hbm_bytes,
)
from dlrover_tpu.accelerate.dry_runner import dry_run
from dlrover_tpu.accelerate.strategy import (
    AccelerationPlan,
    Strategy,
    apply_strategy,
)

logger = get_logger(__name__)


# candidate cap for the cheap analytic phase (measured modes are
# separately capped by max_measured); shared with tests
ANALYTIC_CANDIDATE_CAP = 512


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(
    cfg: ModelConfig,
    n_devices: int,
    seq: int,
    max_candidates: int = 32,
) -> List[Strategy]:
    """Enumerate (tp, sp, pp, fsdp, dp) factorizations + remat choices.

    On fp8-native hardware (device_context.fp8_supported) every dense-
    model candidate carries the fp8 method by default — the reference
    auto-applies TE fp8 the same way when the GPU supports it
    (atorch/auto/opt_lib/amp_optimization.py:197). MoE models stay bf16
    (expert GEMMs have no fp8 wiring)."""
    from dlrover_tpu.accelerate.device_context import fp8_supported

    fp8_default = fp8_supported() and cfg.n_experts == 0
    candidates: List[Strategy] = []
    for tp, sp in itertools.product(_divisors(n_devices), repeat=2):
        if n_devices % (tp * sp):
            continue
        if cfg.n_head % tp or cfg.kv_heads % tp:
            continue
        if seq % max(1, sp):
            continue
        if sp > 1 and cfg.n_head % (sp * tp):
            continue  # ulysses shards the tp-sharded heads across sp too
        rest = n_devices // (tp * sp)
        for pp in _divisors(rest):
            if pp > 1 and (sp > 1 or cfg.n_layer % pp):
                continue  # pipeline can't nest sp shard_maps / split layers
            rest2 = rest // pp
            for fsdp in _divisors(rest2):
                dp = rest2 // fsdp
                base: Strategy = [
                    ("amp_bf16", {}),
                    (
                        "mixed_parallel",
                        {
                            "dp": dp,
                            "fsdp": fsdp,
                            "tp": tp,
                            "sp": sp,
                            "pp": pp,
                        },
                    ),
                ]
                if sp > 1:
                    base.append(("sequence_parallel", {"size": sp}))
                if fp8_default:
                    base.append(("fp8", {}))
                candidates.append(base + [("checkpoint", {"policy": "none"})])
                candidates.append(base + [("checkpoint", {"policy": "full"})])
                # memory-squeeze tier: host-offloaded moments on top of
                # full remat — fits models the resident plans cannot
                candidates.append(
                    base
                    + [("checkpoint", {"policy": "full"}),
                       ("offload_opt", {})]
                )
    # dedupe, keep stable order
    seen = set()
    out = []
    for c in candidates:
        key = str(c)
        if key not in seen:
            seen.add(key)
            out.append(c)
    if len(out) <= max_candidates:
        return out
    # Over the cap: truncate diversity-first, not prefix-first (a prefix
    # cut silently drops whole regions — e.g. every tp>1 plan at 16+
    # devices). Keep the best-scoring plan of every (tp, sp, pp) group,
    # then fill remaining slots by score.
    def model_axes(c):
        for name, cfg_d in c:
            if name == "mixed_parallel":
                return (
                    cfg_d.get("tp", 1),
                    cfg_d.get("sp", 1),
                    cfg_d.get("pp", 1),
                )
        return (1, 1, 1)

    def score(c):
        return _heuristic_score(cfg, apply_strategy(c), n_devices)

    groups = {}
    for c in out:
        groups.setdefault(model_axes(c), []).append(c)
    picked = []
    for group in groups.values():
        group.sort(key=score, reverse=True)
        picked.append(group[0])
    rest = [c for g in groups.values() for c in g[1:]]
    rest.sort(key=score, reverse=True)
    picked.extend(rest)
    picked = picked[:max_candidates]

    def has_offload(c):
        return any(name == "offload_opt" for name, _ in c)

    if not any(has_offload(c) for c in picked):
        # the offload tier scores low (host DMA) so score-based
        # truncation always drops it — but it exists for the case where
        # nothing resident fits, so reserve one slot for the MOST
        # SHARDED offload variant (minimum device memory), not the
        # best-scoring one
        def shards(c):
            for name, d in c:
                if name == "mixed_parallel":
                    return (
                        d.get("fsdp", 1) * d.get("tp", 1) * d.get("pp", 1)
                    )
            return 1

        offloads = sorted(
            (c for c in out if has_offload(c)),
            key=lambda c: (shards(c), score(c)),
            reverse=True,
        )
        if offloads:
            picked[-1] = offloads[0]
    return picked


def _heuristic_score(
    cfg: ModelConfig, plan: AccelerationPlan, n_devices: int
) -> float:
    """Cheap locality-aware preference: less model parallelism is better
    unless memory forces it; remat costs ~30% extra FLOPs."""
    sizes = plan.mesh.resolved_sizes(n_devices)
    score = 1.0
    score /= 1.0 + 0.15 * (sizes["tp"] - 1)   # tp all-reduces per layer
    score /= 1.0 + 0.10 * (sizes["sp"] - 1)   # sp all-to-alls
    score /= 1.0 + 0.02 * (sizes["fsdp"] - 1)  # fsdp all-gathers overlap well
    pp = sizes["pp"]
    if pp > 1:
        from dlrover_tpu.parallel.pipeline import pipeline_bubble_fraction

        n_micro = cfg.pp_microbatches or pp
        score *= 1.0 - pipeline_bubble_fraction(pp, n_micro)  # fill/drain
    if plan.remat == "full":
        score *= 0.75
    if plan.offload_opt_state:
        # host DMA around the optimizer update (measured ~2x step cost
        # at 124M single-chip; relatively cheaper as models grow) —
        # chosen only when resident plans don't fit
        score *= 0.55
    return score


def _bo_search(
    cfg: ModelConfig,
    feasible: List[Tuple[float, Strategy, AccelerationPlan]],
    n_devices: int,
    global_batch: int,
    seq: int,
    budget: int,
    devices,
) -> Optional[Tuple[float, Strategy, AccelerationPlan]]:
    """Bayesian-opt over the feasible set, measured by dry runs.

    Reference: ATorch's HEBO BO over dryrun throughput
    (auto/engine/sg_algo/bayes_opt_sg.py). The BO space is the strategy's
    knobs (log2 of each mesh axis + remat); each suggestion is projected
    onto the nearest feasible candidate, so the surrogate learns over a
    smooth space while only real plans get measured.
    """
    import math

    import numpy as np

    from dlrover_tpu.accelerate.hpsearch import (
        BayesianOptimizer,
        Choice,
        Int,
        SearchSpace,
    )

    def knobs(plan: AccelerationPlan) -> dict:
        sizes = plan.mesh.resolved_sizes(n_devices)
        return {
            "log2_tp": int(math.log2(sizes["tp"])),
            "log2_sp": int(math.log2(sizes["sp"])),
            "log2_pp": int(math.log2(sizes["pp"])),
            "log2_fsdp": int(math.log2(sizes["fsdp"])),
            "remat": plan.remat,
        }

    max_log2 = max(1, int(math.log2(n_devices)))
    space = SearchSpace(
        {
            "log2_tp": Int(0, max_log2),
            "log2_sp": Int(0, max_log2),
            "log2_pp": Int(0, max_log2),
            "log2_fsdp": Int(0, max_log2),
            "remat": Choice(["none", "full"]),
        }
    )
    encoded = [space.encode(knobs(plan)) for _, _, plan in feasible]
    opt = BayesianOptimizer(space, n_init=max(2, budget // 3))
    measured: dict = {}
    best = None
    for _ in range(budget):
        want = space.encode(opt.suggest())
        # project onto the nearest not-yet-measured feasible candidate
        order = np.argsort(
            [float(np.sum((e - want) ** 2)) for e in encoded]
        )
        idx = next((int(i) for i in order if int(i) not in measured), None)
        if idx is None:
            break  # feasible set exhausted
        _, strat, plan = feasible[idx]
        res = dry_run(cfg, plan, global_batch, seq, devices=devices)
        metric = res.tokens_per_sec if res.ok else 0.0
        measured[idx] = metric
        opt.observe(knobs(plan), metric)
        logger.info("BO measured %s → %.3g tokens/s", strat, metric)
        if res.ok and (best is None or metric > best[0]):
            best = (metric, strat, plan)
    return best


def search_strategy(
    cfg: ModelConfig,
    n_devices: int,
    global_batch: int,
    seq: int,
    mode: str = "heuristic",  # heuristic | cost | measure | bo
    max_measured: int = 6,
    devices=None,
) -> Tuple[Strategy, AccelerationPlan]:
    if mode == "measured":  # common alias
        mode = "measure"
    if mode not in ("heuristic", "cost", "measure", "bo"):
        # an unknown mode used to silently fall through to the measure
        # loop — fail loudly instead
        raise ValueError(
            f"unknown search mode {mode!r}: expected "
            "heuristic | cost | measure | bo"
        )
    hbm = device_hbm_bytes()
    batch_per_chip = max(1, global_batch // n_devices)
    feasible: List[Tuple[float, Strategy, AccelerationPlan]] = []
    # the analytic feasibility filter is cheap — consider the (near-)
    # full candidate set here; only the measured modes below are capped
    # (max_measured), so the default truncation would just hide plans
    # (e.g. the offload tier) that memory pressure makes load-bearing
    for strat in generate_candidates(cfg, n_devices, seq,
                                     max_candidates=ANALYTIC_CANDIDATE_CAP):
        plan = apply_strategy(strat)
        try:
            a = analyse(cfg, plan, n_devices, batch_per_chip, seq, hbm)
        except ValueError:
            continue
        if not a.fits:
            continue
        feasible.append((_heuristic_score(cfg, plan, n_devices), strat, plan))
    if not feasible:
        # nothing fits: force max sharding + full remat + bf16 params
        # + host-offloaded moments (the one offload strategy method;
        # activation offload is the remat='offload_attn' policy, not
        # taken here — full remat is the lower device-memory bound)
        strat = [
            ("half", {}),
            ("mixed_parallel", {"dp": 1, "fsdp": n_devices, "tp": 1, "sp": 1}),
            ("checkpoint", {"policy": "full"}),
            ("bf16_optim", {}),
            ("offload_opt", {}),
        ]
        logger.warning("no analytically-feasible strategy; forcing %s", strat)
        return strat, apply_strategy(strat)

    feasible.sort(key=lambda t: -t[0])

    def _warn_if_unvalidated_offload(plan):
        # analyse() budgets the offloaded moments' device working set at
        # the largest-leaf bound the streamed update enforces
        # (streamed_offload_adamw's barrier-serialized transfers). The
        # bound is structural for the streamed adamw path; a measured
        # step (mode='measure'/'bo') remains the ground truth for
        # optimizers that still take the legacy whole-tree path.
        if plan.offload_opt_state and (
            plan.optimizer != "adamw"
            or plan.optimizer_state_dtype is not None
        ):
            logger.warning(
                "selected offload_opt with a non-streaming optimizer "
                "(%s/%s): the whole-tree legacy path has no working-set "
                "bound — run mode='measure' or 'bo' to validate before "
                "training",
                plan.optimizer,
                plan.optimizer_state_dtype,
            )

    if mode == "heuristic":
        score, strat, plan = feasible[0]
        logger.info("heuristic strategy (score %.3f): %s", score, strat)
        _warn_if_unvalidated_offload(plan)
        return strat, plan

    if mode == "bo":
        best = _bo_search(
            cfg, feasible, n_devices, global_batch, seq, max_measured, devices
        )
        if best is None:
            _, strat, plan = feasible[0]
            _warn_if_unvalidated_offload(plan)
            return strat, plan
        return best[1], best[2]

    best = None
    for score, strat, plan in feasible[:max_measured]:
        res = dry_run(
            cfg,
            plan,
            global_batch,
            seq,
            cost_only=(mode == "cost"),
            devices=devices,
        )
        if not res.ok:
            continue
        metric = (
            -res.cost_flops - res.cost_bytes
            if mode == "cost"
            else res.tokens_per_sec
        )
        logger.info(
            "measured %s → %.3g (%s)",
            strat,
            metric,
            "cost" if mode == "cost" else "tokens/s",
        )
        if best is None or metric > best[0]:
            best = (metric, strat, plan)
    if best is None:
        # every dry run failed: the fallback pick is exactly as
        # unvalidated as the heuristic one
        _, strat, plan = feasible[0]
        _warn_if_unvalidated_offload(plan)
        return strat, plan
    if mode == "cost":
        # cost mode compiles but never executes a step, so an offload
        # pick is still runtime-unvalidated
        _warn_if_unvalidated_offload(best[2])
    return best[1], best[2]
