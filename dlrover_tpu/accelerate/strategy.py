"""Acceleration strategies: named optimization methods over a plan.

Reference: atorch's OptimizationLibrary (auto/opt_lib/optimization_library.py:18
— 16 methods: amp_native, fsdp, tensor_parallel, pipeline_parallel,
sequence_parallel, checkpoint, module_replace, zero1/2, mixed_parallel …).

TPU-native difference: a method does not wrap or swap modules — it edits an
``AccelerationPlan`` (mesh axis sizes, sharding rules, model numerics,
optimizer settings). The plan lowers to one jitted train step; XLA does the
rest. A Strategy is the serializable list of (method, config) pairs, same
shape as the reference's strategy objects (auto/accelerate.py:246-305).
"""

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from dlrover_tpu.parallel.mesh import MeshConfig

Strategy = List[Tuple[str, Dict[str, Any]]]


@dataclass
class AccelerationPlan:
    """Everything needed to build the train step for one strategy."""

    mesh: MeshConfig = field(default_factory=MeshConfig)
    rules: Dict[str, Any] = field(default_factory=dict)
    # model overrides
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    remat: str = "none"
    attn_impl: str = "auto"
    # optimizer
    optimizer: str = "adamw"
    optimizer_state_dtype: Optional[str] = None
    # host-offloaded moments (reference: atorch CPU-offload Adam)
    offload_opt_state: bool = False
    # fp8 GEMMs w/ delayed scaling (ops/fp8.py; native on v6e+ only)
    fp8: bool = False
    # data
    grad_accum: int = 1
    # sequence parallelism flavour: none | ulysses | ring
    sp_mode: str = "none"
    # ZeRO update sharding over dp (parallel.sharding.CommConfig):
    # reduce-scatter grads, 1/dp optimizer shard, all-gather params.
    # False = off; "zero1" = deferred exchange (one reduce-scatter per
    # step, full grad accumulator); "zero2" = per-microbatch scattered
    # accumulation (no full-gradient residency across the accum scan);
    # True = legacy alias for "zero2". Engages on pure-dp AND hybrid
    # dp×fsdp / dp×tp meshes (train_step.resolve_update_sharding).
    update_sharding: Union[bool, str] = False
    # gradient-collective bucket size (MB of f32 payload)
    comm_bucket_mb: float = 4.0
    # wire dtype for the bucketed exchange: float32 | bfloat16 | int8
    comm_wire_dtype: str = "float32"
    # override wire dtype when dp crosses DCN; None = same everywhere
    comm_wire_dtype_dcn: Optional[str] = None

    def comm_config(self):
        """The resolved CommConfig, or None when update sharding is off."""
        if not self.update_sharding:
            return None
        from dlrover_tpu.parallel.sharding import CommConfig

        return CommConfig(
            update_sharding=self.update_sharding,
            bucket_mb=self.comm_bucket_mb,
            wire_dtype=self.comm_wire_dtype,
            wire_dtype_dcn=self.comm_wire_dtype_dcn,
        )

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "AccelerationPlan":
        d = json.loads(s)
        d["mesh"] = MeshConfig(**d["mesh"])
        return cls(**d)


# ---------------------------------------------------------------------------
# Optimization methods
# ---------------------------------------------------------------------------


def _amp_bf16(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.compute_dtype = cfg.get("dtype", "bfloat16")


def _half(plan: AccelerationPlan, cfg: Dict) -> None:
    """Blanket half precision incl. params (reference: half_optimization)."""
    plan.compute_dtype = "bfloat16"
    plan.param_dtype = "bfloat16"


def _fsdp(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.mesh.fsdp = int(cfg.get("size", -1))


def _tensor_parallel(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.mesh.tp = int(cfg.get("size", 1))


def _pipeline_parallel(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.mesh.pp = int(cfg.get("size", 1))


def _expert_parallel(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.mesh.ep = int(cfg.get("size", 1))


def _sequence_parallel(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.mesh.sp = int(cfg.get("size", 1))
    plan.sp_mode = cfg.get("mode", "ulysses")


def _ring_attention(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.mesh.sp = int(cfg.get("size", 1))
    plan.sp_mode = "ring"


def _checkpoint(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.remat = cfg.get("policy", "full")


def _module_replace(plan: AccelerationPlan, cfg: Dict) -> None:
    """Fused-attention swap (reference: module_replace_optimization)."""
    plan.attn_impl = cfg.get("attn_impl", "flash")


def _low_bit_optim(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.optimizer_state_dtype = cfg.get("dtype", "int8")


def _bf16_optim(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.optimizer_state_dtype = "bfloat16"


def _offload_opt(plan: AccelerationPlan, cfg: Dict) -> None:
    """Moments to pinned host memory (reference: CPU-offload Adam)."""
    plan.offload_opt_state = cfg.get("enabled", True)


def _fp8(plan: AccelerationPlan, cfg: Dict) -> None:
    """fp8 GEMMs with delayed scaling (reference: atorch's
    TransformerEngine fp8 autocast, amp_optimization.py:197; TPU impl
    in ops/fp8.py). Hard-gated on native fp8 hardware unless the caller
    forces it — on pre-fp8 chips (v5e) the quantization would cost
    accuracy with zero speedup."""
    if cfg.get("force"):
        plan.fp8 = True
        return
    from dlrover_tpu.accelerate.device_context import fp8_supported

    if not fp8_supported():
        raise ValueError(
            "fp8 strategy requires native fp8 hardware (TPU v6e+); "
            "pass {'force': True} to apply anyway"
        )
    plan.fp8 = True


def _grad_accum(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.grad_accum = int(cfg.get("steps", 1))


def _optimizer(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.optimizer = cfg.get("name", "adamw")


def _data_parallel(plan: AccelerationPlan, cfg: Dict) -> None:
    plan.mesh.dp = int(cfg.get("size", -1))


def _zero1(plan: AccelerationPlan, cfg: Dict) -> None:
    """ZeRO-1 weight-update sharding over dp (reference: atorch
    zero_optimization stage 1). Grads reduce-scatter in fixed-byte
    buckets, each rank steps 1/dp of the optimizer state, params
    all-gather back. Wire dtype of the bucketed exchange is tunable
    (float32 is bitwise vs the unsharded step; bfloat16/int8 use
    per-bucket scales, EQuARX-style). Under gradient accumulation the
    exchange is deferred: one reduce-scatter of the full accumulated
    gradient per step (classic stage 1 — gradients stay unsharded)."""
    plan.update_sharding = "zero1" if cfg.get("enabled", True) else False
    _comm_tuning(plan, cfg)


def _zero2(plan: AccelerationPlan, cfg: Dict) -> None:
    """ZeRO-2 gradient + weight-update sharding over dp (reference:
    atorch zero_optimization stage 2). Same bucketed wire and 1/dp
    optimizer shard as ``zero1``, but each microbatch's gradients are
    reduce-scattered immediately and accumulated in the scattered 1/dp
    form — no full-gradient buffer survives the accum scan, trading
    (grad_accum−1) extra reduce-scatters for grad memory."""
    plan.update_sharding = "zero2" if cfg.get("enabled", True) else False
    _comm_tuning(plan, cfg)


def _comm_tuning(plan: AccelerationPlan, cfg: Dict) -> None:
    if "bucket_mb" in cfg:
        plan.comm_bucket_mb = float(cfg["bucket_mb"])
    if "wire_dtype" in cfg:
        plan.comm_wire_dtype = str(cfg["wire_dtype"])
    if "wire_dtype_dcn" in cfg:
        plan.comm_wire_dtype_dcn = cfg["wire_dtype_dcn"]


def _mixed_parallel(plan: AccelerationPlan, cfg: Dict) -> None:
    """Arbitrary axis combination in one method (reference:
    mixed_parallel_optimization.py:32)."""
    for axis in ("dp", "pp", "ep", "fsdp", "sp", "tp"):
        if axis in cfg:
            setattr(plan.mesh, axis, int(cfg[axis]))


OPTIMIZATION_LIBRARY: Dict[str, Callable[[AccelerationPlan, Dict], None]] = {
    "amp_bf16": _amp_bf16,
    "half": _half,
    "fsdp": _fsdp,
    "zero3": _fsdp,  # alias: fully-sharded params ≡ fsdp axis
    "tensor_parallel": _tensor_parallel,
    "pipeline_parallel": _pipeline_parallel,
    "expert_parallel": _expert_parallel,
    "sequence_parallel": _sequence_parallel,
    "ring_attention": _ring_attention,
    "checkpoint": _checkpoint,
    "module_replace": _module_replace,
    "low_bit_optim": _low_bit_optim,
    "bf16_optim": _bf16_optim,
    "fp8": _fp8,
    "offload_opt": _offload_opt,
    "grad_accum": _grad_accum,
    "optimizer": _optimizer,
    "data_parallel": _data_parallel,
    "zero1": _zero1,
    "zero2": _zero2,
    "mixed_parallel": _mixed_parallel,
}


def apply_strategy(strategy: Strategy) -> AccelerationPlan:
    plan = AccelerationPlan()
    for name, cfg in strategy:
        method = OPTIMIZATION_LIBRARY.get(name)
        if method is None:
            raise ValueError(f"unknown optimization method: {name}")
        method(plan, cfg or {})
    return plan


def strategy_to_json(strategy: Strategy) -> str:
    return json.dumps(strategy)


def strategy_from_json(s: str) -> Strategy:
    return [(name, cfg) for name, cfg in json.loads(s)]
