"""Bayesian-optimization hyperparameter search.

Reference: dlrover/python/brain/hpsearch/bo.py (BayesianOptimizer:30, base
RecommendationAlgorithm hpsearch/base.py:21) and ATorch's HEBO-backed
strategy tuning (auto/engine/sg_algo/bayes_opt_sg.py) — suggest/observe
loops over a mixed search space, maximizing a measured objective.

Self-contained numpy implementation: Gaussian-process surrogate (RBF
kernel, median-heuristic lengthscale) + expected-improvement acquisition
maximized over random candidates. No scipy/sklearn dependency — the whole
fit is a Cholesky solve, which is plenty for the tens-of-observations
regime strategy search lives in.
"""

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class Float:
    lo: float
    hi: float
    log: bool = False


@dataclass(frozen=True)
class Int:
    lo: int
    hi: int
    log: bool = False


@dataclass(frozen=True)
class Choice:
    options: Tuple[Any, ...]

    def __init__(self, options: Sequence[Any]):
        object.__setattr__(self, "options", tuple(options))


@dataclass
class SearchSpace:
    """Named mixed-type box: Float / Int / Choice per parameter."""

    params: Dict[str, Any] = field(default_factory=dict)

    def dim(self) -> int:
        return sum(
            len(p.options) if isinstance(p, Choice) else 1
            for p in self.params.values()
        )

    # ---- encoding: config dict ⇄ unit hypercube ------------------------

    def encode(self, conf: Dict[str, Any]) -> np.ndarray:
        xs: List[float] = []
        for name, p in self.params.items():
            v = conf[name]
            if isinstance(p, Choice):
                onehot = [0.0] * len(p.options)
                onehot[p.options.index(v)] = 1.0
                xs.extend(onehot)
            elif isinstance(p, (Float, Int)):
                lo, hi = float(p.lo), float(p.hi)
                if p.log:
                    lo, hi, v = math.log(lo), math.log(hi), math.log(v)
                xs.append(0.0 if hi == lo else (float(v) - lo) / (hi - lo))
            else:
                raise TypeError(f"bad param {name}: {p!r}")
        return np.asarray(xs, dtype=np.float64)

    def decode(self, x: np.ndarray) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        i = 0
        for name, p in self.params.items():
            if isinstance(p, Choice):
                k = len(p.options)
                out[name] = p.options[int(np.argmax(x[i : i + k]))]
                i += k
                continue
            lo, hi = float(p.lo), float(p.hi)
            if p.log:
                lo, hi = math.log(lo), math.log(hi)
            v = lo + float(np.clip(x[i], 0.0, 1.0)) * (hi - lo)
            if p.log:
                v = math.exp(v)
            if isinstance(p, Int):
                out[name] = int(min(p.hi, max(p.lo, round(v))))
            else:
                out[name] = v
            i += 1
        return out

    def sample(self, rng: np.random.Generator) -> Dict[str, Any]:
        return self.decode(rng.random(self.dim()))


def _rbf(a: np.ndarray, b: np.ndarray, ls: float) -> np.ndarray:
    d2 = (
        np.sum(a * a, 1)[:, None]
        + np.sum(b * b, 1)[None, :]
        - 2.0 * (a @ b.T)
    )
    return np.exp(-0.5 * np.maximum(d2, 0.0) / (ls * ls))


class GaussianProcess:
    """Zero-mean GP on standardized targets, RBF kernel."""

    def __init__(self, noise: float = 1e-6):
        self.noise = noise
        self._x: Optional[np.ndarray] = None

    def fit(self, x: np.ndarray, y: np.ndarray):
        self._x = np.atleast_2d(x)
        y = np.asarray(y, dtype=np.float64)
        self._mu, self._sd = float(y.mean()), float(y.std() or 1.0)
        self._y = (y - self._mu) / self._sd
        n = len(self._x)
        if n > 1:
            d2 = (
                np.sum(self._x * self._x, 1)[:, None]
                + np.sum(self._x * self._x, 1)[None, :]
                - 2.0 * (self._x @ self._x.T)
            )
            med = np.median(np.sqrt(np.maximum(d2, 0.0))[~np.eye(n, dtype=bool)])
            self.ls = max(float(med), 1e-3)
        else:
            self.ls = 1.0
        k = _rbf(self._x, self._x, self.ls) + (
            self.noise + 1e-8
        ) * np.eye(n)
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, self._y)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(x)
        ks = _rbf(x, self._x, self.ls)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.maximum(1.0 - np.sum(v * v, 0), 1e-12)
        return mean * self._sd + self._mu, np.sqrt(var) * self._sd


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)


def expected_improvement(
    mean: np.ndarray, std: np.ndarray, best: float, xi: float = 0.01
) -> np.ndarray:
    z = (mean - best - xi) / std
    return (mean - best - xi) * _norm_cdf(z) + std * _norm_pdf(z)


class BayesianOptimizer:
    """suggest()/observe() loop maximizing a black-box objective.

    First ``n_init`` suggestions are quasi-random exploration; afterwards a
    GP surrogate is refit on every observation and suggestions maximize
    expected improvement over ``n_candidates`` random probes (plus local
    perturbations of the incumbent).
    """

    def __init__(
        self,
        space: SearchSpace,
        seed: int = 0,
        n_init: int = 5,
        n_candidates: int = 512,
    ):
        self.space = space
        self.rng = np.random.default_rng(seed)
        self.n_init = n_init
        self.n_candidates = n_candidates
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []
        self._gp = GaussianProcess()

    def suggest(self) -> Dict[str, Any]:
        if len(self._ys) < self.n_init:
            return self.space.sample(self.rng)
        x = np.array(self._xs)
        self._gp.fit(x, np.array(self._ys))
        d = self.space.dim()
        cands = self.rng.random((self.n_candidates, d))
        # local candidates around the incumbent sharpen exploitation
        inc = self._xs[int(np.argmax(self._ys))]
        local = np.clip(
            inc[None, :]
            + self.rng.normal(0.0, 0.1, (self.n_candidates // 4, d)),
            0.0,
            1.0,
        )
        cands = np.vstack([cands, local])
        mean, std = self._gp.predict(cands)
        ei = expected_improvement(mean, std, max(self._ys))
        return self.space.decode(cands[int(np.argmax(ei))])

    def observe(self, conf: Dict[str, Any], value: float):
        self._xs.append(self.space.encode(conf))
        self._ys.append(float(value))

    @property
    def num_observations(self) -> int:
        return len(self._ys)

    def best(self) -> Tuple[Dict[str, Any], float]:
        if not self._ys:
            raise RuntimeError("no observations yet")
        i = int(np.argmax(self._ys))
        return self.space.decode(self._xs[i]), self._ys[i]
