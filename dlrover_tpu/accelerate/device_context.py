"""Device capability probe.

Reference: atorch's device context (auto/device_context.py:10 — probes
GPU name/memory/compute capability to gate optimizations like fp8 and
flash attention). TPU-native: probe the jax backend once and expose the
facts the strategy search and analyser gate on — HBM size, bf16 peak,
native-fp8 matmul support (Trillium/v6e+), and whether devices share an
ICI domain.
"""

import functools
from dataclasses import dataclass

import jax

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# bf16 peak TFLOP/s per chip by device-kind substring
_PEAK_BF16_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}

# device kinds with native fp8 MXU support (Trillium on)
_FP8_KINDS = ("v6 lite", "v6e", "v7")

# HBM sizing delegates to analyser.device_hbm_bytes() — one table (plus
# its runtime memory_stats probe), not two to keep in sync


@dataclass(frozen=True)
class DeviceContext:
    platform: str          # "tpu" | "cpu" | ...
    device_kind: str       # e.g. "TPU v5 lite"
    n_devices: int
    hbm_bytes: float
    peak_bf16_tflops: float
    supports_fp8: bool     # native fp8 matmul (not emulated)
    on_tpu: bool


def _lookup(kind: str, table, default):
    kind = kind.lower()
    for key, val in table.items():
        if key in kind:
            return val
    return default


@functools.lru_cache(maxsize=1)
def detect_device_context() -> DeviceContext:
    try:
        devices = jax.devices()
        d = devices[0]
        kind = getattr(d, "device_kind", "") or ""
        platform = d.platform.lower()
        n = len(devices)
    except Exception:  # noqa: BLE001
        return DeviceContext("cpu", "cpu", 0, 16e9, 0.1, False, False)
    from dlrover_tpu.accelerate.analyser import device_hbm_bytes

    on_tpu = platform == "tpu" or "tpu" in kind.lower()
    ctx = DeviceContext(
        platform=platform,
        device_kind=kind,
        n_devices=n,
        hbm_bytes=device_hbm_bytes(),
        peak_bf16_tflops=_lookup(kind, _PEAK_BF16_TFLOPS, 197.0)
        if on_tpu
        else 0.1,
        supports_fp8=on_tpu
        and any(k in kind.lower() for k in _FP8_KINDS),
        on_tpu=on_tpu,
    )
    logger.info("device context: %s", ctx)
    return ctx


def fp8_supported() -> bool:
    return detect_device_context().supports_fp8
