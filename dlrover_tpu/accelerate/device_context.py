"""Device capability probe.

Reference: atorch's device context (auto/device_context.py:10 — probes
GPU name/memory/compute capability to gate optimizations like fp8 and
flash attention). TPU-native: probe the jax backend once and expose the
facts the strategy search and analyser gate on — HBM size, bf16 peak,
native-fp8 matmul support (Trillium/v6e+), and whether devices share an
ICI domain.
"""

import functools
from dataclasses import dataclass

import jax

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# bf16 peak TFLOP/s per chip by device-kind substring
_PEAK_BF16_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}

# device kinds with native fp8 MXU support (Trillium on)
_FP8_KINDS = ("v6 lite", "v6e", "v7")

# HBM sizing delegates to analyser.device_hbm_bytes() — one table (plus
# its runtime memory_stats probe), not two to keep in sync


@dataclass(frozen=True)
class DeviceContext:
    platform: str          # "tpu" | "cpu" | ...
    device_kind: str       # e.g. "TPU v5 lite"
    n_devices: int
    hbm_bytes: float
    peak_bf16_tflops: float
    supports_fp8: bool     # native fp8 matmul (not emulated)
    on_tpu: bool


def _lookup(kind: str, table, default):
    kind = kind.lower()
    for key, val in table.items():
        if key in kind:
            return val
    return default


@functools.lru_cache(maxsize=1)
def detect_device_context() -> DeviceContext:
    try:
        devices = jax.devices()
        d = devices[0]
        kind = getattr(d, "device_kind", "") or ""
        platform = d.platform.lower()
        n = len(devices)
    except Exception:  # noqa: BLE001
        return DeviceContext("cpu", "cpu", 0, 16e9, 0.1, False, False)
    from dlrover_tpu.accelerate.analyser import device_hbm_bytes

    on_tpu = platform == "tpu" or "tpu" in kind.lower()
    ctx = DeviceContext(
        platform=platform,
        device_kind=kind,
        n_devices=n,
        hbm_bytes=device_hbm_bytes(),
        peak_bf16_tflops=_lookup(kind, _PEAK_BF16_TFLOPS, 197.0)
        if on_tpu
        else 0.1,
        supports_fp8=on_tpu
        and any(k in kind.lower() for k in _FP8_KINDS),
        on_tpu=on_tpu,
    )
    logger.info("device context: %s", ctx)
    return ctx


def fp8_supported() -> bool:
    return detect_device_context().supports_fp8


# ---------------------------------------------------------------------------
# Kernel capability table
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class KernelCapabilities:
    """One gating table for every hand-written kernel path.

    Before this existed the gates lived scattered: the flash kernels
    keyed off ``pallas_attention._on_tpu``, the fused norms off
    ``pallas_norm.kernels_available`` (what ``cfg.fused_norm=None``
    auto resolves to), and fp8 off ``fp8_supported`` — three probes
    that could silently disagree (e.g. a relay backend that looks like
    TPU to one and not another). Consumers: ``decoder`` (fused norm
    auto), ``ops.fp8._resolve_native`` (native vs bf16-upcast dots),
    and ``bench.check_kernels`` (which kernel numerics gates to run).

    ``fp8_native`` means the quantized operands feed the MXU directly;
    False still runs the fp8 recipe with bf16-upcast of the SAME
    quantized values — identical numerics, no speedup (ops/fp8.py).
    """

    flash_attention: bool  # Pallas flash attention kernels usable
    fused_norm: bool       # Pallas fused norm/residual kernels usable
    paged_attention: bool  # fused paged-decode kernel usable (serving)
    fp8_native: bool       # native fp8 MXU dots (else bf16 upcast)
    interpret: bool        # kernels run in Pallas interpret mode


def kernel_capabilities(interpret=None) -> KernelCapabilities:
    """The capability table for this process's backend.

    ``interpret=None`` honors the DLROVER_TPU_PALLAS_INTERPRET test
    hook (kernels execute in interpret mode on CPU); pass True/False
    to force. Cheap: the device probe underneath is lru-cached, the
    rest is module lookups — so callers needn't cache the table and
    env-flipping tests see fresh answers.
    """
    from dlrover_tpu.ops import pallas_attention, pallas_norm, pallas_paged

    if interpret is None:
        # the kernel modules all seed from the same env var; norm's
        # copy is authoritative for defaulting
        interpret = pallas_norm.INTERPRET
    ctx = detect_device_context()
    # one Pallas-usability predicate for both kernel families: pltpu
    # importable AND (real TPU — pallas_attention._on_tpu, which also
    # recognizes TPU relays — or interpret mode)
    pallas_ok = pallas_norm.kernels_available(interpret)
    on_tpu = pallas_attention._on_tpu()
    return KernelCapabilities(
        flash_attention=pallas_ok,
        fused_norm=pallas_ok,
        paged_attention=pallas_paged.kernels_available(interpret),
        fp8_native=ctx.supports_fp8,
        interpret=bool(interpret) and not on_tpu,
    )
