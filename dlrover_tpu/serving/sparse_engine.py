"""Continuous-batching recommendation serving over the sparse tier.

The recommender scenario from the reference system's original
production domain: DeepFM predictions (models/deepfm.py) served from
the tiered embedding stack (sparse/tiered.py) behind the SAME
scheduler/server loop the LLM path uses. A request is one example —
``n_fields`` categorical ids (the scheduler ``prompt``) plus a dense
feature vector — and the engine drains the queue in batches, runs one
jitted forward, and resolves each future with the predicted CTR.

The async lookup pipeline: a ``LookaheadPrefetcher``
(sparse/prefetch.py) peeks the scheduler queue (``Scheduler.peek``),
extracts the keyed embedding ids of the next requests, and promotes
cold rows hot off-thread — so the step-time ``pull_frozen`` gather is
an in-RAM hit instead of a synchronous cold-store fault in the request
path. ``SparseServingRecord`` telemetry carries the tier hit-rate,
prefetch-coverage and promotion-latency gauges next to the usual
scheduler latency histograms.

Elastic PS resharding: when the model's collection is a
``DistributedEmbedding``, ``SparseServingServer.resync_ps`` adopts the
master's versioned server set at a step boundary (``paused()``), so
the two-phase checksummed-wire key migration runs with no step in
flight and queued requests keep their original admission tickets —
a PS scale-out mid-traffic loses zero rows and zero requests.
"""

import json
import time
from typing import List, Optional

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.models.deepfm import _field_key
from dlrover_tpu.serving.scheduler import LATENCY_PHASES, Request
from dlrover_tpu.serving.server import GenerationServer
from dlrover_tpu.sparse.prefetch import LookaheadPrefetcher

logger = get_logger(__name__)


def extract_request_keys(req: Request) -> np.ndarray:
    """Keyed embedding ids one queued request will gather: the
    (field, id) keying of models/deepfm.py, over the request's prompt
    (its categorical ids). Both DeepFM tables share the keying, so one
    extraction feeds every table's prefetch."""
    ids = np.asarray(req.prompt, np.int64)
    return np.stack(
        [_field_key(i, ids[i]) for i in range(ids.size)]
    ).reshape(-1)


class _FanoutPrefetchTarget:
    """One prefetch surface over the model's tiered tables (DeepFM has
    two — ``emb`` and ``wide`` — keyed identically)."""

    def __init__(self, tables):
        self.tables = list(tables)

    def prefetch(self, keys, now_ts=None) -> int:
        return sum(t.prefetch(keys, now_ts) for t in self.tables)


def tier_model_tables(model, cold_dir: str, *, flush_every: int = 256,
                      codec: str = "f32") -> List:
    """Wrap every KvTable in ``model.coll`` with a TieredTable over a
    FileColdStore under ``cold_dir/<table>`` — the one-call setup for
    tiered serving (bench + drills). Returns the TieredTables."""
    import os

    from dlrover_tpu.sparse.tiered import FileColdStore, TieredTable

    out = []
    for name, table in list(model.coll.tables.items()):
        cold = FileColdStore(
            os.path.join(cold_dir, name), width=table.width,
            flush_every=flush_every, codec=codec,
        )
        tiered = TieredTable(table, cold)
        model.coll.tables[name] = tiered
        out.append(tiered)
    return out


def _tiered_tables(model) -> List:
    """The model collection's TieredTable values (empty when the
    collection is flat KvTables or a DistributedEmbedding ring)."""
    tables = getattr(getattr(model, "coll", None), "tables", None)
    if not isinstance(tables, dict):
        return []
    return [t for t in tables.values() if hasattr(t, "prefetch")]


def merged_tier_snapshot(tables) -> dict:
    """Sum TierStats across tables and recompute the derived rates."""
    snap = {
        "gathered": 0, "hot_hits": 0, "cold_faults": 0, "prefetched": 0,
        "inserted": 0, "demoted": 0, "hot_rows": 0, "cold_rows": 0,
        "promote_latency_avg_ms": 0.0,
    }
    lat_num = lat_den = 0.0
    for t in tables:
        s = t.stats.snapshot()
        for k in ("gathered", "hot_hits", "cold_faults", "prefetched",
                  "inserted", "demoted"):
            snap[k] += int(s[k])
        snap["hot_rows"] += t.hot_size
        snap["cold_rows"] += t.cold_size
        lat_num += s["promote_time_s"]
        lat_den += s["promote_batches"]
    looked_up = max(1, snap["gathered"])
    promoted = snap["cold_faults"] + snap["prefetched"]
    snap["hot_hit_rate"] = snap["hot_hits"] / looked_up
    snap["prefetch_coverage"] = (
        snap["prefetched"] / promoted if promoted else 1.0
    )
    snap["promote_latency_avg_ms"] = (
        1e3 * lat_num / lat_den if lat_den else 0.0
    )
    return snap


class SparseServingEngine:
    """DeepFM inference engine satisfying the GenerationServer engine
    contract (step/stats/max_len/role/draining/observability_snapshot)."""

    def __init__(self, model, cfg, scheduler, *, max_batch: int = 32,
                 lookahead: int = 4):
        self.model = model
        self.cfg = cfg
        self.scheduler = scheduler
        self.max_batch = max(1, int(max_batch))
        self.lookahead = int(lookahead)
        # admission bound the base server checks: a prompt is exactly
        # n_fields ids and every request asks for one "token" (score)
        self.max_len = int(cfg.n_fields) + 1
        self.role = "recommend"
        self.draining = False
        self.tiered = _tiered_tables(model)
        self._completed = 0
        self._t0 = 0.0

    @staticmethod
    def _can_admit(req: Request) -> bool:
        # producers attach dense_x right after scheduler.submit returns;
        # a request popped in that microsecond window would have no
        # features, so the head waits (lookahead lets others run)
        return getattr(req, "dense_x", None) is not None

    def step(self) -> bool:
        if self.draining:
            return False
        batch: List[Request] = []
        while len(batch) < self.max_batch:
            req = self.scheduler.pop_next(
                can_admit=self._can_admit, lookahead=self.lookahead
            )
            if req is None:
                break
            self.scheduler.record_admitted(req)
            batch.append(req)
        if not batch:
            return False
        if not self._t0:
            self._t0 = time.monotonic()
        cat = np.stack(
            [np.asarray(r.prompt, np.int64) for r in batch]
        )
        dense = np.stack(
            [np.asarray(r.dense_x, np.float32) for r in batch]
        )
        try:
            scores = self.model.predict(cat, dense)
        except Exception as exc:  # fail the batch, keep the loop alive
            logger.exception("sparse predict batch of %d failed",
                             len(batch))
            for r in batch:
                self.scheduler.fail(r, exc)
            return True
        for r, s in zip(batch, scores):
            self.scheduler.record_first_token(r)
            self.scheduler.complete(r, [float(s)])
        self._completed += len(batch)
        return True

    def stats(self) -> dict:
        dt = (time.monotonic() - self._t0) if self._t0 else 0.0
        qps = self._completed / dt if dt > 0 else 0.0
        out = {
            "active_slots": 0,
            "free_pages": 0,
            "tokens_per_s": qps,
            "qps": qps,
            "completed": self._completed,
            "role": self.role,
        }
        out.update(merged_tier_snapshot(self.tiered))
        return out

    def observability_snapshot(self) -> dict:
        return self.stats()


class SparseServingServer(GenerationServer):
    """Recommendation replica front end: the GenerationServer loop
    (pause protocol, drain, pacing) around a ``SparseServingEngine``,
    publishing ``SparseServingRecord`` and owning the lookahead
    prefetcher and the PS-resync path."""

    def __init__(self, model, cfg, *, prefetch: bool = True,
                 prefetch_lookahead: int = 8, **kw):
        super().__init__(model, cfg, **kw)
        self.ps_reshards = 0
        self.last_reshard_s = 0.0
        self.prefetcher: Optional[LookaheadPrefetcher] = None
        if prefetch and self.engine.tiered:
            self.prefetcher = LookaheadPrefetcher(
                _FanoutPrefetchTarget(self.engine.tiered),
                self.scheduler.peek,
                extract_request_keys,
                lookahead=prefetch_lookahead,
            )

    def _build_engine(self, params, cfg, scheduler, **engine_kw):
        return SparseServingEngine(params, cfg, scheduler, **engine_kw)

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "SparseServingServer":
        super().start()
        if self.prefetcher is not None:
            self.prefetcher.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        if self.prefetcher is not None:
            self.prefetcher.stop()
        super().stop(timeout)

    # ---- intake ----------------------------------------------------------

    def submit(self, cat_ids, dense_x, *, priority: int = 0,
               deadline_s: Optional[float] = None) -> Request:
        """One example in: ``cat_ids`` [n_fields] int64 categorical
        ids, ``dense_x`` [n_dense] float features. The future resolves
        with ``[score]``."""
        cat = np.asarray(cat_ids, np.int64).reshape(-1)
        if cat.size != self.engine.cfg.n_fields:
            raise ValueError(
                f"expected {self.engine.cfg.n_fields} categorical ids, "
                f"got {cat.size}"
            )
        dense = np.asarray(dense_x, np.float32).reshape(-1)
        if dense.size != self.engine.cfg.n_dense:
            raise ValueError(
                f"expected {self.engine.cfg.n_dense} dense features, "
                f"got {dense.size}"
            )
        req = self.scheduler.submit(
            cat.tolist(), 1, priority=priority, deadline_s=deadline_s
        )
        req.dense_x = dense
        if self.prefetcher is not None:
            self.prefetcher.notify()
        return req

    def predict(self, cat_ids, dense_x, timeout: float = 30.0) -> float:
        """Blocking convenience: submit one example, wait for its score."""
        return self.submit(cat_ids, dense_x).future.result(timeout)[0]

    # ---- elastic PS ------------------------------------------------------

    def resync_ps(self, client) -> bool:
        """Adopt the master's current PS server set at a step boundary.

        Runs the versioned reroute (sparse/server.py sync_with_master →
        two-phase migration over the checksummed wire) under
        ``paused()``: no step is mid-gather while owners change, queued
        requests keep their original tickets, and new submissions keep
        landing in the scheduler throughout — the engine just resumes
        against the wider ring. Returns True when the routing changed."""
        from dlrover_tpu.sparse.server import sync_with_master

        demb = self.engine.model.coll
        if not hasattr(demb, "set_servers"):
            raise ValueError(
                "resync_ps needs a DistributedEmbedding-backed model"
            )
        t0 = time.monotonic()
        with self.paused():
            changed = sync_with_master(demb, client)
        if changed:
            self.ps_reshards += 1
            self.last_reshard_s = time.monotonic() - t0
            logger.info(
                "PS reshard %d adopted version %d in %.3fs",
                self.ps_reshards, demb.version, self.last_reshard_s,
            )
        return changed

    # ---- telemetry -------------------------------------------------------

    def _publish(self):
        from dlrover_tpu.observability.telemetry import SparseServingRecord

        stats = self.engine.stats()
        sched = self.scheduler
        hists = sched.histograms()
        lat = hists["e2e"].summary()
        demb = getattr(self.engine.model, "coll", None)
        rec = SparseServingRecord(
            replica=self.replica,
            queue_depth=sched.queue_depth(),
            admitted=sched.admitted,
            completed=sched.completed,
            re_admitted=sched.re_admitted,
            shed=sched.shed,
            rejected=sched.rejected,
            timed_out=sched.timed_out,
            qps=round(float(stats["qps"]), 3),
            p50_ms=round(lat["p50"], 3),
            p99_ms=round(lat["p99"], 3),
            queue_wait_p99_ms=round(
                hists["queue_wait"].percentile(99.0), 3
            ),
            hot_hit_rate=round(float(stats["hot_hit_rate"]), 6),
            prefetch_coverage=round(
                float(stats["prefetch_coverage"]), 6
            ),
            promote_latency_avg_ms=round(
                float(stats["promote_latency_avg_ms"]), 3
            ),
            cold_faults=int(stats["cold_faults"]),
            prefetched=int(stats["prefetched"]),
            demoted=int(stats["demoted"]),
            hot_rows=int(stats["hot_rows"]),
            cold_rows=int(stats["cold_rows"]),
            ps_version=int(getattr(demb, "version", 0) or 0),
            ps_reshards=self.ps_reshards,
            last_reshard_s=round(self.last_reshard_s, 3),
            hists=json.dumps(
                {k: hists[k].to_dict() for k in LATENCY_PHASES},
                sort_keys=True,
            ),
        )
        if sched.hub is not None:
            sched.hub.publish(rec)
        return rec
