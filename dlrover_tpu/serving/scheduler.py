"""Threaded request queue for the generation server.

Pure host-side Python (no jax import): a priority heap ordered by
(priority, arrival) — lower priority value first, FIFO within a class —
with admission control (bounded depth → ``AdmissionError``), and
latency accounting that publishes ``ServingRecord`` telemetry on the
shared ``TelemetryHub``. The engine pops work at step boundaries; user
threads submit concurrently.

Re-admission (``re_admit``) keeps a request's ORIGINAL arrival ticket:
a request bumped by allocator pressure or replica failover re-enters
ahead of later arrivals instead of going to the back of the line — the
elastic story's no-starvation guarantee.
"""

import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional


class AdmissionError(ValueError):
    """The request cannot be admitted: queue at capacity or shed under
    migration pressure (back off ``retry_after_s`` and retry), or
    invalid parameters (fix the request). Subclasses ValueError so
    pre-existing callers catching ValueError on the future still work.

    ``retry_after_s`` is the scheduler's deadline-aware hint — estimated
    queue drain time from the recent completion rate, 0.0 when the
    error is not load-related (invalid parameters)."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode policy carried on the ``Request``.

    ``temperature=0`` is greedy (the engine's pinned bitwise path);
    ``temperature>0`` samples ``categorical(warp_logits(...))`` with a
    per-slot threefry key derived from ``seed`` — deterministic given
    the seed and STABLE across admit/evict reordering and router
    failover re-admission, because every draw folds in the absolute
    buffer position of the token being drawn rather than any engine
    step counter. ``top_k=0`` / ``top_p=1.0`` disable those warps.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def validate(self) -> None:
        """Raise ``AdmissionError`` on out-of-domain parameters. The
        engine calls this at ADMISSION (not submit) so a poisoned
        request fails its own future instead of killing the step-loop
        thread."""
        if not (self.temperature >= 0.0):  # catches NaN too
            raise AdmissionError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.top_k < 0:
            raise AdmissionError(f"top_k must be >= 0, got {self.top_k}")
        if not (0.0 < self.top_p <= 1.0):  # catches NaN too
            raise AdmissionError(
                f"top_p must be in (0, 1], got {self.top_p}"
            )


@dataclass
class Request:
    """One generation request as the engine sees it."""

    rid: str
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    priority: int = 0
    arrival: int = 0            # admission ticket, stable across re-admits
    submit_t: float = 0.0
    first_token_t: float = 0.0  # 0 until the prefill emits token 0
    done_t: float = 0.0
    deadline_s: Optional[float] = None  # wall budget from submit_t, if any
    re_admits: int = 0          # >0 marks preempted/migrated — never shed
    sampling: SamplingParams = field(default_factory=SamplingParams)
    future: Future = field(default_factory=Future)

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens


class Scheduler:
    """Thread-safe request queue + latency bookkeeping for ONE engine."""

    def __init__(
        self,
        *,
        max_queue: int = 256,
        max_latencies: int = 4096,
        hub=None,
        replica: str = "replica-0",
    ):
        self._heap: list = []
        self._lock = threading.Lock()
        self._ticket = itertools.count()
        # heap tiebreak: arrival tickets are per-scheduler, so a request
        # RE-ADMITTED from a dead peer can tie a local one exactly —
        # and Request is deliberately not orderable
        self._seq = itertools.count()
        self.max_queue = max_queue
        self.hub = hub
        self.replica = replica
        self._latencies_ms: List[float] = []
        self._max_latencies = max_latencies
        self._done_ts: List[float] = []  # recent completion times, for hints
        self.admitted = 0
        self.completed = 0
        self.re_admitted = 0
        self.shed = 0

    # ---- intake ----------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        eos_id: Optional[int] = None,
        priority: int = 0,
        sampling: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
    ) -> Request:
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            if len(self._heap) >= self.max_queue:
                raise AdmissionError(
                    f"queue at capacity ({self.max_queue}); retry later",
                    retry_after_s=self._retry_after_locked(),
                )
            arrival = next(self._ticket)
            req = Request(
                rid=f"{self.replica}/r{arrival}",
                prompt=[int(t) for t in prompt],
                max_new_tokens=int(max_new_tokens),
                eos_id=eos_id,
                priority=int(priority),
                arrival=arrival,
                submit_t=time.monotonic(),
                deadline_s=deadline_s,
                sampling=sampling or SamplingParams(),
            )
            heapq.heappush(
                self._heap,
                (req.priority, req.arrival, next(self._seq), req),
            )
            self.admitted += 1
        return req

    def re_admit(self, req: Request) -> None:
        """Re-queue a preempted/failed-over request under its ORIGINAL
        (priority, arrival) ticket — it outranks later arrivals. The
        admission-control bound is deliberately not applied: the request
        was already admitted once. Marks the request shed-exempt."""
        with self._lock:
            req.re_admits += 1
            heapq.heappush(
                self._heap,
                (req.priority, req.arrival, next(self._seq), req),
            )
            self.re_admitted += 1

    # ---- overload degradation --------------------------------------------

    def _retry_after_locked(self) -> float:
        """Estimated queue drain time from the recent completion rate —
        the ``AdmissionError.retry_after_s`` hint. Caller holds _lock."""
        depth = len(self._heap)
        ts = self._done_ts
        if len(ts) >= 2 and ts[-1] > ts[0]:
            rate = (len(ts) - 1) / (ts[-1] - ts[0])
            est = (depth + 1) / rate
        else:
            est = 1.0
        return min(30.0, max(0.05, est))

    def retry_after_hint(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    def shed_lowest(
        self,
        count: int = 1,
        below_priority: Optional[int] = None,
    ) -> List[Request]:
        """Shed up to ``count`` of the LOWEST-priority queued new
        admissions: fail their futures with a retry-after-carrying
        ``AdmissionError`` so callers back off instead of hammering a
        replica absorbing a failover. Never sheds a re-admitted request
        (``re_admits > 0`` — it already paid for its place once, and
        shedding it would turn a migration fallback into a lost
        request). ``below_priority`` restricts victims to strictly
        lower-priority (numerically greater) classes, so migration
        admission never sheds traffic it doesn't outrank."""
        with self._lock:
            cands = [
                t
                for t in self._heap
                if t[-1].re_admits == 0 and not t[-1].future.done()
            ]
            if below_priority is not None:
                cands = [t for t in cands if t[0] > below_priority]
            cands.sort(reverse=True)  # worst (priority, arrival) first
            victims = cands[: max(int(count), 0)]
            if victims:
                drop = {id(t[-1]) for t in victims}
                self._heap = [t for t in self._heap if id(t[-1]) not in drop]
                heapq.heapify(self._heap)
                self.shed += len(victims)
            hint = self._retry_after_locked()
        shed = [t[-1] for t in victims]
        for req in shed:
            self.fail(
                req,
                AdmissionError(
                    f"{req.rid} shed under migration pressure; "
                    f"retry after {hint:.2f}s",
                    retry_after_s=hint,
                ),
            )
        return shed

    # ---- engine side -----------------------------------------------------

    def pop_next(self, can_admit=None) -> Optional[Request]:
        """Pop the highest-priority request, or None when empty or when
        ``can_admit(req)`` rejects the head (head-of-line admission:
        lower-ranked requests never jump a head waiting on pages)."""
        with self._lock:
            while self._heap:
                req = self._heap[0][-1]
                if req.future.cancelled():
                    heapq.heappop(self._heap)
                    continue
                if can_admit is not None and not can_admit(req):
                    return None
                heapq.heappop(self._heap)
                return req
        return None

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def record_first_token(self, req: Request) -> None:
        req.first_token_t = time.monotonic()

    def complete(self, req: Request, output) -> None:
        """Resolve a request exactly once and record its latency."""
        req.done_t = time.monotonic()
        with self._lock:
            self.completed += 1
            self._latencies_ms.append((req.done_t - req.submit_t) * 1e3)
            if len(self._latencies_ms) > self._max_latencies:
                del self._latencies_ms[: -self._max_latencies]
            self._done_ts.append(req.done_t)
            if len(self._done_ts) > 256:
                del self._done_ts[:-256]
        if not req.future.done():
            req.future.set_result(output)

    def fail(self, req: Request, exc: Exception) -> None:
        if not req.future.done():
            req.future.set_exception(exc)

    # ---- accounting ------------------------------------------------------

    @staticmethod
    def _percentile(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
        return sorted_vals[idx]

    def latency_ms(self) -> dict:
        with self._lock:
            vals = sorted(self._latencies_ms)
        return {
            "p50": self._percentile(vals, 0.50),
            "p99": self._percentile(vals, 0.99),
            "n": len(vals),
        }

    def reset_latencies(self) -> None:
        """Drop warmup samples (compile time) before a timed window."""
        with self._lock:
            self._latencies_ms.clear()

    def publish(self, engine_stats: Optional[dict] = None):
        """Emit one ``ServingRecord`` on the hub; returns the record
        (also when no hub is attached, for callers that sink it
        themselves)."""
        from dlrover_tpu.observability.telemetry import ServingRecord

        lat = self.latency_ms()
        es = engine_stats or {}
        rec = ServingRecord(
            replica=self.replica,
            active_slots=int(es.get("active_slots", 0)),
            queue_depth=self.queue_depth(),
            admitted=self.admitted,
            completed=self.completed,
            re_admitted=self.re_admitted,
            tokens_per_s=float(es.get("tokens_per_s", 0.0)),
            p50_ms=round(lat["p50"], 3),
            p99_ms=round(lat["p99"], 3),
            draft_tokens=int(es.get("draft_tokens", 0)),
            accepted_tokens=int(es.get("accepted_tokens", 0)),
            spec_accept_rate=float(es.get("spec_accept_rate", 0.0)),
            shed=self.shed,
            migrated_in=int(es.get("migrated_in", 0)),
            migrated_out=int(es.get("migrated_out", 0)),
        )
        if self.hub is not None:
            self.hub.publish(rec)
        return rec
