"""Threaded request queue for the generation server.

Pure host-side Python (no jax import): a priority heap ordered by
(priority, arrival) — lower priority value first, FIFO within a class —
with admission control (bounded depth → ``AdmissionError``), and
latency accounting that publishes ``ServingRecord`` telemetry on the
shared ``TelemetryHub``. The engine pops work at step boundaries; user
threads submit concurrently.

Re-admission (``re_admit``) keeps a request's ORIGINAL arrival ticket:
a request bumped by allocator pressure or replica failover re-enters
ahead of later arrivals instead of going to the back of the line — the
elastic story's no-starvation guarantee.

Latency accounting is four mergeable log-bucketed histograms
(observability/histogram.py), one per phase:

- ``e2e``       — submit → complete, the classic request latency;
- ``ttft``      — submit → first emitted token (prefill + queue);
- ``tpot``      — mean inter-token ms within one request (decode pace);
- ``queue_wait``— (re-)enqueue → engine admission.

Histograms replace the old truncating flat list: O(1) record, no
window bias under sustained load, and the router/master merge replica
histograms bucket-by-bucket so fleet percentiles are computed from
counts, never from averaged per-replica percentiles. Every dropped
request lands in exactly one of ``shed`` / ``rejected`` /
``timed_out`` / ``poisoned`` so goodput vs offered load is computable.
"""

import heapq
import itertools
import json
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.observability.histogram import LatencyHistogram
from dlrover_tpu.observability.tracing import get_tracer

#: phase keys of the scheduler's latency histograms, in envelope order
LATENCY_PHASES = ("e2e", "ttft", "tpot", "queue_wait", "handoff")


class AdmissionError(ValueError):
    """The request cannot be admitted: queue at capacity or shed under
    migration pressure (back off ``retry_after_s`` and retry), or
    invalid parameters (fix the request). Subclasses ValueError so
    pre-existing callers catching ValueError on the future still work.

    ``retry_after_s`` is the scheduler's deadline-aware hint — estimated
    queue drain time from the recent completion rate, 0.0 when the
    error is not load-related (invalid parameters)."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode policy carried on the ``Request``.

    ``temperature=0`` is greedy (the engine's pinned bitwise path);
    ``temperature>0`` samples ``categorical(warp_logits(...))`` with a
    per-slot threefry key derived from ``seed`` — deterministic given
    the seed and STABLE across admit/evict reordering and router
    failover re-admission, because every draw folds in the absolute
    buffer position of the token being drawn rather than any engine
    step counter. ``top_k=0`` / ``top_p=1.0`` disable those warps.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def validate(self) -> None:
        """Raise ``AdmissionError`` on out-of-domain parameters. The
        engine calls this at ADMISSION (not submit) so a poisoned
        request fails its own future instead of killing the step-loop
        thread."""
        if not (self.temperature >= 0.0):  # catches NaN too
            raise AdmissionError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.top_k < 0:
            raise AdmissionError(f"top_k must be >= 0, got {self.top_k}")
        if not (0.0 < self.top_p <= 1.0):  # catches NaN too
            raise AdmissionError(
                f"top_p must be in (0, 1], got {self.top_p}"
            )


@dataclass
class Request:
    """One generation request as the engine sees it."""

    rid: str
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    priority: int = 0
    arrival: int = 0            # admission ticket, stable across re-admits
    submit_t: float = 0.0
    last_enqueue_t: float = 0.0  # refreshed on re-admit (queue-wait base)
    first_token_t: float = 0.0  # 0 until the prefill emits token 0
    done_t: float = 0.0
    deadline_s: Optional[float] = None  # wall budget from submit_t, if any
    re_admits: int = 0          # >0 marks preempted/migrated — never shed
    sampling: SamplingParams = field(default_factory=SamplingParams)
    future: Future = field(default_factory=Future)

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens


class Scheduler:
    """Thread-safe request queue + latency bookkeeping for ONE engine."""

    def __init__(
        self,
        *,
        max_queue: int = 256,
        max_latencies: int = 4096,
        hub=None,
        replica: str = "replica-0",
    ):
        self._heap: list = []
        self._lock = threading.Lock()
        self._ticket = itertools.count()
        # heap tiebreak: arrival tickets are per-scheduler, so a request
        # RE-ADMITTED from a dead peer can tie a local one exactly —
        # and Request is deliberately not orderable
        self._seq = itertools.count()
        self.max_queue = max_queue
        self.hub = hub
        self.replica = replica
        # max_latencies is kept for signature compatibility only: the
        # histograms are O(1)-bounded by geometry, not by sample count
        self._max_latencies = max_latencies
        self._hists: Dict[str, LatencyHistogram] = {
            k: LatencyHistogram() for k in LATENCY_PHASES
        }
        self._done_ts: List[float] = []  # recent completion times, for hints
        self.admitted = 0
        self.completed = 0
        self.re_admitted = 0
        self.shed = 0
        self.rejected = 0   # admission failures: capacity + oversize
        self.timed_out = 0  # per-request deadline expiries
        self.poisoned = 0   # invalid sampling parameters

    # ---- intake ----------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        eos_id: Optional[int] = None,
        priority: int = 0,
        sampling: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
    ) -> Request:
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            if len(self._heap) >= self.max_queue:
                self.rejected += 1
                raise AdmissionError(
                    f"queue at capacity ({self.max_queue}); retry later",
                    retry_after_s=self._retry_after_locked(),
                )
            arrival = next(self._ticket)
            now = time.monotonic()
            req = Request(
                rid=f"{self.replica}/r{arrival}",
                prompt=[int(t) for t in prompt],
                max_new_tokens=int(max_new_tokens),
                eos_id=eos_id,
                priority=int(priority),
                arrival=arrival,
                submit_t=now,
                last_enqueue_t=now,
                deadline_s=deadline_s,
                sampling=sampling or SamplingParams(),
            )
            heapq.heappush(
                self._heap,
                (req.priority, req.arrival, next(self._seq), req),
            )
            self.admitted += 1
        return req

    def re_admit(self, req: Request) -> None:
        """Re-queue a preempted/failed-over request under its ORIGINAL
        (priority, arrival) ticket — it outranks later arrivals. The
        admission-control bound is deliberately not applied: the request
        was already admitted once. Marks the request shed-exempt."""
        with self._lock:
            req.re_admits += 1
            req.last_enqueue_t = time.monotonic()
            heapq.heappush(
                self._heap,
                (req.priority, req.arrival, next(self._seq), req),
            )
            self.re_admitted += 1
        tr = get_tracer()
        if tr.enabled:
            tr.instant(
                "serving.re_admit", rid=req.rid, replica=self.replica,
                re_admits=req.re_admits,
            )

    # ---- overload degradation --------------------------------------------

    def _retry_after_locked(self) -> float:
        """Estimated queue drain time from the recent completion rate —
        the ``AdmissionError.retry_after_s`` hint. Caller holds _lock."""
        depth = len(self._heap)
        ts = self._done_ts
        if len(ts) >= 2 and ts[-1] > ts[0]:
            rate = (len(ts) - 1) / (ts[-1] - ts[0])
            est = (depth + 1) / rate
        else:
            est = 1.0
        return min(30.0, max(0.05, est))

    def retry_after_hint(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    def shed_lowest(
        self,
        count: int = 1,
        below_priority: Optional[int] = None,
    ) -> List[Request]:
        """Shed up to ``count`` of the LOWEST-priority queued new
        admissions: fail their futures with a retry-after-carrying
        ``AdmissionError`` so callers back off instead of hammering a
        replica absorbing a failover. Never sheds a re-admitted request
        (``re_admits > 0`` — it already paid for its place once, and
        shedding it would turn a migration fallback into a lost
        request). ``below_priority`` restricts victims to strictly
        lower-priority (numerically greater) classes, so migration
        admission never sheds traffic it doesn't outrank."""
        with self._lock:
            cands = [
                t
                for t in self._heap
                if t[-1].re_admits == 0 and not t[-1].future.done()
            ]
            if below_priority is not None:
                cands = [t for t in cands if t[0] > below_priority]
            cands.sort(reverse=True)  # worst (priority, arrival) first
            victims = cands[: max(int(count), 0)]
            if victims:
                drop = {id(t[-1]) for t in victims}
                self._heap = [t for t in self._heap if id(t[-1]) not in drop]
                heapq.heapify(self._heap)
                self.shed += len(victims)
            hint = self._retry_after_locked()
        shed = [t[-1] for t in victims]
        for req in shed:
            self.fail(
                req,
                AdmissionError(
                    f"{req.rid} shed under migration pressure; "
                    f"retry after {hint:.2f}s",
                    retry_after_s=hint,
                ),
            )
        return shed

    # ---- engine side -----------------------------------------------------

    def pop_next(
        self, can_admit=None, lookahead: int = 0
    ) -> Optional[Request]:
        """Pop the highest-priority request, or None when empty or when
        ``can_admit(req)`` rejects the head (head-of-line admission:
        lower-ranked requests never jump a head waiting on pages).
        Requests whose wall deadline already expired in the queue are
        failed fast (counted ``timed_out``) instead of burning slot
        time on an answer nobody is waiting for.

        ``lookahead > 0`` relaxes strict head-of-line when the head is
        BLOCKED: up to ``lookahead`` requests behind it are offered to
        ``can_admit`` in heap order and the first admissible one is
        popped. With a hit-aware ``can_admit`` (prefix sharing) this
        lets a cheap hot-prefix request — whose resident prefix pages
        cost nothing from the free list — run instead of idling a slot
        behind an expensive cold request. The head keeps its ticket and
        is re-offered first on every later call, so it is delayed only
        while it cannot run anyway — never starved by the jumpers."""
        expired: List[Request] = []
        got: Optional[Request] = None
        with self._lock:
            now = time.monotonic()
            while self._heap:
                req = self._heap[0][-1]
                if req.future.cancelled():
                    heapq.heappop(self._heap)
                    continue
                if (
                    req.deadline_s is not None
                    and now - req.submit_t > req.deadline_s
                ):
                    heapq.heappop(self._heap)
                    self.timed_out += 1
                    expired.append(req)
                    continue
                if can_admit is not None and not can_admit(req):
                    if lookahead > 0:
                        got = self._pop_lookahead_locked(
                            can_admit, lookahead, now
                        )
                    break
                heapq.heappop(self._heap)
                got = req
                break
        for req in expired:
            self.fail(
                req,
                AdmissionError(
                    f"{req.rid} deadline ({req.deadline_s}s) expired "
                    f"in queue"
                ),
            )
        return got

    def _pop_lookahead_locked(
        self, can_admit, lookahead: int, now: float
    ) -> Optional[Request]:
        """Scan up to ``lookahead`` requests behind a blocked head (heap
        order) and pop the first one ``can_admit`` accepts. Cancelled /
        expired candidates are skipped in place — the head pass owns
        their bookkeeping. Caller holds ``_lock``."""
        for t in heapq.nsmallest(lookahead + 1, self._heap)[1:]:
            req = t[-1]
            if req.future.cancelled() or req.future.done():
                continue
            if (
                req.deadline_s is not None
                and now - req.submit_t > req.deadline_s
            ):
                continue
            if can_admit(req):
                self._heap.remove(t)
                heapq.heapify(self._heap)
                return req
        return None

    def record_admitted(self, req: Request) -> None:
        """Engine-side admission hook: close the queue-wait interval
        (enqueue → admission) into the histogram and the trace."""
        t0 = req.last_enqueue_t or req.submit_t
        wait_ms = max(0.0, (time.monotonic() - t0) * 1e3)
        with self._lock:
            self._hists["queue_wait"].record(wait_ms)
        tr = get_tracer()
        if tr.enabled:
            tr.complete_span(
                "serving.queue_wait", t0, rid=req.rid,
                replica=self.replica, priority=req.priority,
            )

    def count_rejected(self) -> None:
        """An admission-rejected request (engine oversize check)."""
        with self._lock:
            self.rejected += 1

    def count_poisoned(self) -> None:
        """A request failed for invalid sampling parameters."""
        with self._lock:
            self.poisoned += 1

    def count_timed_out(self) -> None:
        """A request that missed its wall deadline outside the queue
        (the router's waiter observed the expiry)."""
        with self._lock:
            self.timed_out += 1

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def peek(self, n: int = 1) -> List[Request]:
        """Non-destructive head-of-line peek: the next ``n`` LIVE
        requests in pop order. Cancelled/resolved entries are skipped
        without consuming the lookahead budget — the scan walks the heap
        in sorted order until ``n`` live requests are collected, so a
        burst of cancellations at the head can't blind the prefetcher to
        queued work further back. The lookahead prefetcher reads queued
        prompts here to warm caches (tiered embedding rows) before the
        engine pops them; the queue itself is untouched."""
        n = max(int(n), 0)
        out: List[Request] = []
        with self._lock:
            if n:
                for t in sorted(self._heap):
                    if not t[-1].future.done():
                        out.append(t[-1])
                        if len(out) == n:
                            break
        return out

    def record_first_token(self, req: Request) -> None:
        """Stamp TTFT once per request — a re-prefilled failover does
        not reset the clock the user has been watching since submit."""
        if req.first_token_t:
            return
        req.first_token_t = time.monotonic()
        with self._lock:
            self._hists["ttft"].record(
                max(0.0, (req.first_token_t - req.submit_t) * 1e3)
            )

    def record_handoff_ms(self, ms: float) -> None:
        """One prefill→decode handoff's wire time (first fragment export
        to reservation commit), recorded on the RECEIVING replica's
        scheduler so the decode pool's handoff_ms_p99 is the admission
        latency its streams actually pay."""
        with self._lock:
            self._hists["handoff"].record(max(0.0, ms))

    def complete(self, req: Request, output) -> None:
        """Resolve a request exactly once and record its latency."""
        req.done_t = time.monotonic()
        with self._lock:
            self.completed += 1
            self._hists["e2e"].record((req.done_t - req.submit_t) * 1e3)
            # inter-token pace: mean decode-token spacing after token 0
            n_new = len(output) - len(req.prompt) if output else 0
            if req.first_token_t and n_new >= 2:
                self._hists["tpot"].record(
                    max(0.0, req.done_t - req.first_token_t)
                    / (n_new - 1) * 1e3
                )
            self._done_ts.append(req.done_t)
            if len(self._done_ts) > 256:
                del self._done_ts[:-256]
        if not req.future.done():
            req.future.set_result(output)

    def fail(self, req: Request, exc: Exception) -> None:
        if not req.future.done():
            req.future.set_exception(exc)

    # ---- accounting ------------------------------------------------------

    def histograms(self) -> Dict[str, LatencyHistogram]:
        """Consistent copies of the per-phase histograms, keyed by
        ``LATENCY_PHASES`` — what the router/master merge for fleet
        percentiles."""
        with self._lock:
            return {k: h.copy() for k, h in self._hists.items()}

    def latency_ms(self) -> dict:
        """End-to-end latency percentiles, in the historical
        ``{p50, p99, n}`` shape — now backed by the histogram, so no
        window truncation and no per-call sort."""
        with self._lock:
            return self._hists["e2e"].summary()

    def latency_summary(self) -> dict:
        """Flat per-phase percentile summary (the bench/record shape)."""
        h = self.histograms()
        out = h["e2e"].summary()
        out.update(
            ttft_p50_ms=h["ttft"].percentile(50.0),
            ttft_p99_ms=h["ttft"].percentile(99.0),
            tpot_p50_ms=h["tpot"].percentile(50.0),
            tpot_p99_ms=h["tpot"].percentile(99.0),
            queue_wait_p99_ms=h["queue_wait"].percentile(99.0),
        )
        return out

    def reset_latencies(self) -> None:
        """Drop warmup samples (compile time) before a timed window."""
        with self._lock:
            for h in self._hists.values():
                h.clear()

    def publish(self, engine_stats: Optional[dict] = None):
        """Emit one ``ServingRecord`` on the hub; returns the record
        (also when no hub is attached, for callers that sink it
        themselves)."""
        from dlrover_tpu.observability.telemetry import ServingRecord

        hists = self.histograms()
        lat = hists["e2e"].summary()
        es = engine_stats or {}
        rec = ServingRecord(
            replica=self.replica,
            active_slots=int(es.get("active_slots", 0)),
            queue_depth=self.queue_depth(),
            admitted=self.admitted,
            completed=self.completed,
            re_admitted=self.re_admitted,
            tokens_per_s=float(es.get("tokens_per_s", 0.0)),
            p50_ms=round(lat["p50"], 3),
            p99_ms=round(lat["p99"], 3),
            draft_tokens=int(es.get("draft_tokens", 0)),
            accepted_tokens=int(es.get("accepted_tokens", 0)),
            spec_accept_rate=float(es.get("spec_accept_rate", 0.0)),
            shed=self.shed,
            migrated_in=int(es.get("migrated_in", 0)),
            migrated_out=int(es.get("migrated_out", 0)),
            ttft_p50_ms=round(hists["ttft"].percentile(50.0), 3),
            ttft_p99_ms=round(hists["ttft"].percentile(99.0), 3),
            tpot_p50_ms=round(hists["tpot"].percentile(50.0), 3),
            tpot_p99_ms=round(hists["tpot"].percentile(99.0), 3),
            queue_wait_p99_ms=round(
                hists["queue_wait"].percentile(99.0), 3
            ),
            rejected=self.rejected,
            timed_out=self.timed_out,
            poisoned=self.poisoned,
            prefix_hit_rate=float(es.get("prefix_hit_rate", 0.0)),
            prefill_tokens_saved=int(es.get("prefill_tokens_saved", 0)),
            trie_pages=int(es.get("trie_pages", 0)),
            dedup_ratio=float(es.get("dedup_ratio", 1.0)),
            role=str(es.get("role", "unified")),
            handoffs_in=int(es.get("handoffs_in", 0)),
            handoffs_out=int(es.get("handoffs_out", 0)),
            handoff_bytes=int(es.get("handoff_bytes", 0)),
            handoff_ms_p99=round(hists["handoff"].percentile(99.0), 3),
            hists=json.dumps(
                {k: hists[k].to_dict() for k in LATENCY_PHASES},
                sort_keys=True,
            ),
        )
        if self.hub is not None:
            self.hub.publish(rec)
        return rec
