"""Elastic serving replicas: master registration + failover routing.

A ``ServingReplica`` wraps one ``GenerationServer`` and, when given a
master address, registers with the job master EXACTLY like a trainer
node (``NodeType.SERVING``): same heartbeat/failure machinery, same KV
store for discovery (address published under
``serving_replica_addr_<name>``, mirroring sparse/server.py's
``sparse_server_addr_`` channel). The master's node manager lists them
via ``serving_nodes()`` without treating them as part of the train
rendezvous.

``ReplicaRouter`` is the client-side elastic story: round-robin
dispatch over live replicas, and on replica death (``poll``) every
in-flight request of the dead replica moves to a survivor — exactly
once, no lost and no duplicated requests (the failover drills in
tests/test_serving_replica.py and tests/test_serving_migration.py pin
this). With a ``ServingMigrator`` attached the move is a LIVE KV-page
migration (serving/migration.py): the survivor adopts the victim's
pages and resumes mid-decode with zero re-prefilled prompt tokens,
bitwise-identical output. Without one — or when the migrator itself
degrades — requests are re-admitted under their original ticket and
re-prefill from the prompt (docs/serving.md describes the ladder).

Disaggregated fleets (serving/disagg.py): when the replica set carries
both ``prefill``- and ``decode``-role engines the router grows a
dispatch layer — new requests go to the least-loaded live prefill
replica and stream to the decode pool through a
:class:`~dlrover_tpu.serving.disagg.HandoffCoordinator`; a request
whose prompt hits a prefix already RESIDENT on a decode replica's trie
skips the prefill fleet entirely (only the divergent suffix prefills
on the decode replica — the cross-replica placement residual of
ROADMAP 1(a)). Failover is role-aware: a dead prefill replica's
requests re-dispatch on the prefill pool (committed handoffs just
repoint), a dead decode replica's slots live-migrate to decode
survivors via the PR 14 ladder, and when either pool empties the
fleet collapses to ``unified`` — a one-replica "fleet" therefore
silently runs today's engine. A decode-role replica is never handed a
raw un-prefilled request on re-admission (it would chunk-prefill it
and recreate the interference the split removed).
"""

import json
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.serving.scheduler import Request
from dlrover_tpu.serving.server import GenerationServer

logger = get_logger(__name__)

ADDR_KV_PREFIX = "serving_replica_addr_"


class ServingReplica:
    """One serving host: a GenerationServer plus master-plane plumbing."""

    def __init__(
        self,
        name: str,
        params,
        cfg,
        *,
        master_addr: Optional[str] = None,
        node_id: int = 0,
        hub=None,
        server_cls: type = GenerationServer,
        **server_kw,
    ):
        self.name = name
        self.node_id = node_id
        self.master_addr = master_addr
        # server_cls swaps the front end while keeping the master-plane
        # plumbing: the sparse recommendation server
        # (serving/sparse_engine.SparseServingServer) registers through
        # the same node/KV path, role-tagged "recommend"
        self.server = server_cls(
            params, cfg, hub=hub, replica=name, **server_kw
        )
        self._client = None

    @property
    def alive(self) -> bool:
        return self.server.alive

    @property
    def role(self) -> str:
        return self.server.role

    def start(self) -> "ServingReplica":
        self.server.start()
        if self.master_addr:
            from dlrover_tpu.agent.master_client import MasterClient

            self._client = MasterClient(
                self.master_addr, node_id=self.node_id
            )
            # role-tagged registration: the master's node manager keeps
            # the prefill and decode pools distinguishable so
            # plan_serving_reshard can scale them independently
            self._client.register_node(
                node_type=NodeType.SERVING, role=self.role
            )
            self._client.kv_store_set(
                ADDR_KV_PREFIX + self.name,
                json.dumps({
                    "name": self.name,
                    "node_id": self.node_id,
                    "role": self.role,
                }),
            )
        return self

    def stop(self) -> None:
        self.server.stop()
        if self._client is not None:
            self._client.report_node_status("exited", retries=1)
            self._client.close()
            self._client = None

    def kill(self) -> None:
        """Simulated host eviction: the serve loop halts, in-flight
        futures stay unresolved, and (unlike ``stop``) the master is
        NOT told about a clean exit — failure detection or the router's
        liveness poll must notice."""
        self.server.kill()
        if self._client is not None:
            self._client.close()
            self._client = None

    # convenience passthroughs
    def submit(self, *a, **kw) -> Request:
        return self.server.submit(*a, **kw)

    def generate(self, *a, **kw):
        return self.server.generate(*a, **kw)


def discover_replicas(client, names) -> Optional[Dict[str, dict]]:
    """Resolve replica names → registration payloads via the master KV
    store; None when any member hasn't registered yet (mirrors
    sparse/server.py resolve_ring: never adopt a partial set)."""
    out: Dict[str, dict] = {}
    for name in names:
        raw = client.kv_store_get(ADDR_KV_PREFIX + name)
        if not raw:
            logger.warning(
                "serving replica %s has no registration yet; deferring",
                name,
            )
            return None
        out[name] = json.loads(raw)
    return out


def refresh_discovery(client, names, known=None) -> Dict[str, dict]:
    """Incremental discovery for a LIVE fleet: resolve whichever of
    ``names`` have registered since ``known`` was built and return only
    the new entries (name → registration payload).

    ``discover_replicas`` enforces the all-or-nothing startup rule — a
    router must never adopt a partial initial set. Scale-out breaks
    that premise on purpose: the autoscaler launches replicas one at a
    time, and each becomes routable the moment its
    ``serving_replica_addr_<name>`` key lands, while the rest of the
    candidate roster stays pending without deferring anybody. Callers
    fold the result into their ``known`` map and call again on the
    next refresh tick."""
    known = known or {}
    out: Dict[str, dict] = {}
    for name in names:
        if name in known:
            continue
        raw = client.kv_store_get(ADDR_KV_PREFIX + name)
        if raw:
            out[name] = json.loads(raw)
    return out


class _Entry:
    """Router-side view of one request: which replica holds it and
    whether its result already landed."""

    __slots__ = ("req", "replica", "done")

    def __init__(self, req: Request, replica: ServingReplica):
        self.req = req
        self.replica = replica
        self.done = False


class ReplicaRouter:
    """Request router with exactly-once failover and, on a role-typed
    fleet, the prefill/decode dispatch layer.

    Requests fan out over live replicas (round-robin when unified;
    least-loaded-prefill or prefix-affinity-decode when disaggregated).
    ``poll`` detects dead replicas and moves their incomplete requests
    to survivors under the ORIGINAL admission ticket (the ``Request``
    object travels — its future resolves wherever the survivor finishes
    it). Completed entries are never resubmitted; ``Scheduler.complete``
    resolves each future at most once even if a race double-delivers.
    """

    def __init__(
        self,
        replicas: List[ServingReplica],
        migrator=None,
        watchdog=None,
        faults=None,
        streaming: bool = True,
    ):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.migrator = migrator  # ServingMigrator or None (re-admit path)
        # optional ServingWatchdog: fed every MigrationReport so a run
        # of fallback outcomes classifies as ``migration_fallback``
        self.watchdog = watchdog
        self._entries: List[_Entry] = []
        self._by_rid: Dict[str, _Entry] = {}
        self._rr = 0
        # reentrant: the migrator's role-aware re_admit override runs
        # while poll already holds the lock, and must also work when a
        # drill drives the migrator directly with no lock held
        self._lock = threading.RLock()
        self.reports: List = []   # MigrationReports, drill introspection

        self.prefill_pool = [r for r in self.replicas if r.role == "prefill"]
        self.decode_pool = [r for r in self.replicas if r.role == "decode"]
        self.disaggregated = bool(self.prefill_pool) and bool(
            self.decode_pool
        )
        self.coordinator = None
        self._dead_seen: set = set()
        # replicas removed ON PURPOSE (scale-in): drained, detached from
        # every pool, and invisible to the failover sweep — a detached
        # replica's stopped loop must never read as a death and trigger
        # a spurious migration or a collapse-to-unified
        self._detached: set = set()
        if self.disaggregated:
            from dlrover_tpu.serving.disagg import HandoffCoordinator

            self.coordinator = HandoffCoordinator(
                self.prefill_pool,
                self.decode_pool,
                router=self,
                faults=faults,
                streaming=streaming,
            ).start()
            if self.migrator is not None:
                # satellite fix: the migrator's fallback must never hand
                # a decode-only survivor a raw un-prefilled request
                self.migrator.re_admit = self._role_aware_re_admit
        else:
            # one-sided or one-replica "fleet": silently run unified —
            # a lone prefill replica would park every prompt forever,
            # a lone decode replica would bounce every cold prompt
            for r in self.replicas:
                if r.role != "unified":
                    logger.warning(
                        "replica %s has role=%s but the fleet has no "
                        "%s counterpart — running unified",
                        r.name, r.role,
                        "decode" if r.role == "prefill" else "prefill",
                    )
                    r.server.engine.role = "unified"
            self.prefill_pool = []
            self.decode_pool = []

    # ---- fleet latency rollup -------------------------------------------

    def fleet_histograms(self) -> Dict:
        """Merge every replica's per-phase latency histograms
        bucket-by-bucket (observability/histogram.py) — dead replicas
        included, their schedulers outlive the serve loop. Because the
        bucket boundaries are fixed by geometry, the merged counts are
        IDENTICAL to histogramming the concatenated raw samples: fleet
        percentiles come from counts, never from averaging per-replica
        percentiles."""
        from dlrover_tpu.observability.histogram import merge_histograms
        from dlrover_tpu.serving.scheduler import LATENCY_PHASES

        per = [r.server.scheduler.histograms() for r in self.replicas]
        out = {}
        for k in LATENCY_PHASES:
            merged = merge_histograms(p[k] for p in per)
            if merged is not None:
                out[k] = merged
        return out

    def fleet_latency_ms(self) -> dict:
        """Fleet end-to-end percentiles in the scheduler's
        ``{p50, p99, n}`` shape, from the merged histogram."""
        hists = self.fleet_histograms()
        if "e2e" not in hists:
            return {"p50": 0.0, "p99": 0.0, "n": 0}
        return hists["e2e"].summary()

    def _mark_done(self, entry: _Entry):
        def _cb(_future):
            entry.done = True

        return _cb

    def _live(self) -> List[ServingReplica]:
        return [
            r for r in self.replicas
            if r.alive and id(r) not in self._detached
        ]

    def live_replicas(self, role: Optional[str] = None) -> List[
        "ServingReplica"
    ]:
        """Routable replicas (live and not detached), optionally
        filtered to one role pool — the autoscaler's fleet view."""
        with self._lock:
            live = self._live()
        if role is None:
            return live
        return [r for r in live if r.role == role]

    def is_detached(self, replica: "ServingReplica") -> bool:
        with self._lock:
            return id(replica) in self._detached

    def submit(
        self, prompt, max_new_tokens: int, eos_id=None, priority: int = 0,
        sampling=None, deadline_s=None,
    ) -> Request:
        with self._lock:
            live = self._live()
            if not live:
                raise RuntimeError("no live serving replicas")
            if self.disaggregated:
                replica = self._dispatch_target(prompt)
            else:
                replica = live[self._rr % len(live)]
                self._rr += 1
            req = replica.submit(
                prompt, max_new_tokens, eos_id=eos_id, priority=priority,
                sampling=sampling, deadline_s=deadline_s,
            )
            entry = _Entry(req, replica)
            req.future.add_done_callback(self._mark_done(entry))
            self._entries.append(entry)
            self._by_rid[req.rid] = entry
        return req

    # ---- disaggregated dispatch ------------------------------------------

    def _dispatch_target(self, prompt) -> ServingReplica:
        """Where a new request starts. Prefix affinity first: if a
        decode replica's radix index holds a resident prefix covering
        all but an ``affinity_suffix_max`` suffix of the prompt, the
        request skips the prefill fleet — shared pages map in place and
        only the divergent suffix prefills there. Otherwise the
        least-loaded live prefill replica takes it (the engine
        re-checks the plan at admission and bounces if the donor pages
        churned out meanwhile). Caller holds the lock."""
        from dlrover_tpu.serving import prefix as prefix_mod

        tokens = [int(t) for t in prompt]
        best, best_resume = None, 0
        for r in self.decode_pool:
            if not r.alive:
                continue
            eng = r.server.engine
            if eng.trie is None:
                continue
            match = eng.trie.lookup(tokens)
            if not match.pages and not match.tail_tokens:
                continue
            plan = prefix_mod.plan_admission(
                match, len(tokens), eng.geom.page_size, eng.prefill_chunk
            )
            if (
                prefix_mod.affinity_ok(
                    plan, len(tokens), eng.affinity_suffix_max
                )
                and plan.resume > best_resume
            ):
                best, best_resume = r, plan.resume
        if best is not None:
            logger.info(
                "prefix-affinity dispatch to %s (%d resident tokens)",
                best.name, best_resume,
            )
            return best
        live_prefill = [r for r in self.prefill_pool if r.alive]
        if not live_prefill:
            self._collapse_locked()
            live = self._live()
            if not live:
                raise RuntimeError("no live serving replicas")
            r = live[self._rr % len(live)]
            self._rr += 1
            return r
        return min(
            live_prefill,
            key=lambda r: r.server.scheduler.queue_depth()
            + sum(s is not None for s in r.server.engine.slots),
        )

    def _repoint(self, rid: str, replica: ServingReplica) -> None:
        """A committed handoff moved ``rid``'s ownership; track it so
        failover sweeps watch the right replica."""
        with self._lock:
            entry = self._by_rid.get(rid)
            if entry is not None:
                entry.replica = replica

    def redispatch(self, req: Request) -> str:
        """Degraded-handoff / affinity-bounce intake: requeue ``req``
        under its original ticket on a replica that can PREFILL it,
        and repoint its entry. Returns the receiving replica's name."""
        with self._lock:
            tgt = self._re_admit_target()
            tgt.server.re_admit(req)
            entry = self._by_rid.get(req.rid)
            if entry is not None:
                entry.replica = tgt
            return tgt.name

    def _re_admit_target(self) -> ServingReplica:
        """A live replica that accepts raw (un-prefilled) requests —
        never a decode-role one. When only decode replicas survive,
        collapse the fleet so they can. Caller holds the lock."""
        cand = [r for r in self._live() if r.role != "decode"]
        if not cand:
            self._collapse_locked()
            cand = self._live()
        if not cand:
            raise RuntimeError("no live serving replicas")
        r = cand[self._rr % len(cand)]
        self._rr += 1
        return r

    def _role_aware_re_admit(self, req: Request, survivor) -> str:
        """Installed as the migrator's ``re_admit`` override on a
        disaggregated fleet (satellite fix): the fallback ladder's raw
        re-admissions route through the prefill pool instead of the
        decode survivor the migrator happened to pick."""
        if survivor.role != "decode":
            survivor.server.re_admit(req)
            with self._lock:
                entry = self._by_rid.get(req.rid)
                if entry is not None:
                    entry.replica = survivor
            return survivor.name
        return self.redispatch(req)

    def _drain_bounced(self) -> int:
        """Decode-role engines bounce admissions whose affinity plan
        degraded between dispatch and admission (lock-free deque — the
        engine loop must never wait on the router). Re-dispatch them
        through the prefill pool. Caller holds the lock."""
        n = 0
        for r in self.decode_pool:
            bounced = r.server.engine.bounced
            while bounced:
                try:
                    req = bounced.popleft()
                except IndexError:
                    break
                tgt = self._re_admit_target()
                tgt.server.re_admit(req)
                entry = self._by_rid.get(req.rid)
                if entry is not None:
                    entry.replica = tgt
                logger.info(
                    "affinity bounce: %s re-dispatched from %s to %s",
                    req.rid, r.name, tgt.name,
                )
                n += 1
        return n

    def _collapse_locked(self) -> None:
        """Runtime degradation: one pool has no live member, so the
        split cannot function — fold every surviving engine back to
        ``unified`` and re-dispatch the requests the collapse orphaned
        (prefill-role slots hold prompt-only footprints and cannot
        decode in place). Caller holds the lock."""
        if not self.disaggregated:
            return
        logger.warning(
            "collapsing disaggregated fleet to unified "
            "(prefill live=%d decode live=%d)",
            sum(r.alive for r in self.prefill_pool),
            sum(r.alive for r in self.decode_pool),
        )
        self.disaggregated = False
        coord, self.coordinator = self.coordinator, None
        if self.migrator is not None:
            self.migrator.re_admit = None
        orphans = coord.collapse() if coord is not None else []
        self.prefill_pool = []
        self.decode_pool = []
        live = self._live()
        for req in orphans:
            if not live:
                raise RuntimeError(
                    "all serving replicas died with requests in flight"
                )
            tgt = live[self._rr % len(live)]
            self._rr += 1
            tgt.server.re_admit(req)
            entry = self._by_rid.get(req.rid)
            if entry is not None:
                entry.replica = tgt

    # ---- elastic fleet membership (serving autoscaler) -------------------

    def add_replica(self, replica: ServingReplica) -> None:
        """Attach a warm (already-started) replica to the live fleet —
        the autoscaler's scale-out path. On a disaggregated fleet the
        replica joins its role pool and the handoff coordinator starts
        targeting/sourcing it immediately; a role-typed replica joining
        a UNIFIED fleet folds to unified, mirroring ``__init__``'s
        one-sided-fleet rule. Idempotent for an already-member replica."""
        if not replica.alive:
            raise ValueError(
                f"cannot attach replica {replica.name}: not alive"
            )
        with self._lock:
            if replica in self.replicas and not self.is_detached(replica):
                return
            # a re-attached replica sheds its detached/dead history:
            # the failover sweep should watch it again
            self._detached.discard(id(replica))
            self._dead_seen.discard(id(replica))
            if replica not in self.replicas:
                self.replicas.append(replica)
            if self.disaggregated:
                if replica.role == "prefill":
                    if replica not in self.prefill_pool:
                        self.prefill_pool.append(replica)
                    if self.coordinator is not None:
                        self.coordinator.attach_prefill(replica)
                elif replica.role == "decode":
                    if replica not in self.decode_pool:
                        self.decode_pool.append(replica)
                    if self.coordinator is not None:
                        self.coordinator.attach_decode(replica)
                # a unified joiner on a split fleet serves only failover
                # re-admissions (it is in no dispatch pool) — harmless
            elif replica.role != "unified":
                logger.warning(
                    "replica %s joins a unified fleet with role=%s — "
                    "running unified", replica.name, replica.role,
                )
                replica.server.engine.role = "unified"
            # work stealing: queued-but-unadmitted requests rebalance
            # onto the joiner, so a scale-out relieves the very backlog
            # that triggered it instead of only absorbing FUTURE
            # arrivals. Decode-role joiners steal nothing — a raw
            # un-prefilled request must never land on one.
            stolen = 0
            if replica.role != "decode":
                donors = [
                    r for r in self._live()
                    if r is not replica
                    and (
                        not self.disaggregated or r.role == replica.role
                    )
                ]
                while donors:
                    src = max(
                        donors,
                        key=lambda r: r.server.scheduler.queue_depth(),
                    )
                    if (
                        src.server.scheduler.queue_depth()
                        <= replica.server.scheduler.queue_depth() + 1
                    ):
                        break
                    q = src.server.scheduler.pop_next()
                    if q is None:
                        break
                    replica.server.re_admit(q)
                    entry = self._by_rid.get(q.rid)
                    if entry is not None:
                        entry.replica = replica
                    stolen += 1
            logger.info(
                "scale-out: attached replica %s (role=%s), fleet=%d "
                "live, %d queued request(s) rebalanced",
                replica.name, replica.role, len(self._live()), stolen,
            )

    def remove_replica(
        self,
        replica: ServingReplica,
        *,
        reason: str = "scale_in",
        drain_timeout_s: float = 30.0,
    ):
        """Planned scale-in: drain ``replica`` and detach it from the
        fleet with zero lost or duplicated requests. Decode/unified
        victims evacuate over the live-migration wire (the migrator's
        detect phase sees the victim ALIVE → ``begin_drain`` + stop at
        a step boundary → pages move to pool peers, zero re-prefilled
        prompt tokens). Prefill victims drain cooperatively: queued
        prompts re-dispatch on the pool, in-flight handoffs finish
        streaming, then the loop stops. Either way the replica ends
        ``detached`` — never counted dead, never migrated again, never
        collapsing the fleet. Returns the MigrationReport when the
        live path ran, else None. Raises ValueError when the victim is
        the last live member of its pool."""
        with self._lock:
            if id(replica) in self._detached or replica not in self.replicas:
                return None
            in_prefill = (
                self.disaggregated and replica in self.prefill_pool
            )
            if in_prefill:
                peers = [
                    r for r in self.prefill_pool
                    if r.alive and r is not replica
                ]
            elif self.disaggregated and replica in self.decode_pool:
                peers = [
                    r for r in self.decode_pool
                    if r.alive and r is not replica
                ]
            else:
                peers = [r for r in self._live() if r is not replica]
            if not peers:
                raise ValueError(
                    f"cannot scale in {replica.name}: last live member "
                    "of its pool"
                )
            # detach FIRST: no new dispatch lands on the victim, and the
            # failover sweep must never read the drained loop as a death
            self._detached.add(id(replica))
            self._dead_seen.add(id(replica))
            if replica in self.prefill_pool:
                self.prefill_pool.remove(replica)
            if replica in self.decode_pool:
                self.decode_pool.remove(replica)
            if self.coordinator is not None:
                self.coordinator.detach(replica)
            if not in_prefill and self.migrator is not None:
                self._migrate_victim(replica, peers)
                logger.info(
                    "scale-in: detached replica %s via live migration "
                    "(reason=%s)", replica.name, reason,
                )
                return self.reports[-1]
            # cooperative drain (prefill victim, or no migrator): stop
            # admitting, re-route the queue under original tickets
            replica.server.begin_drain()
            while True:
                q = replica.server.scheduler.pop_next()
                if q is None:
                    break
                tgt = self._re_admit_target()
                tgt.server.re_admit(q)
                entry = self._by_rid.get(q.rid)
                if entry is not None:
                    entry.replica = tgt
        # wait OUTSIDE the lock for in-flight slots to finish (prefill
        # slots hand off and repoint via the coordinator's commit path)
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            eng = replica.server.engine
            if not any(
                s is not None and not s.req.future.done()
                for s in eng.slots
            ):
                break
            time.sleep(0.005)
        replica.server.stop()
        with self._lock:
            # anything still parked on the victim (drain deadline hit)
            # re-admits from the prompt — degraded but never lost
            for entry in self._entries:
                if entry.done or entry.replica is not replica:
                    continue
                tgt = self._re_admit_target()
                tgt.server.re_admit(entry.req)
                entry.replica = tgt
        logger.info(
            "scale-in: detached replica %s via cooperative drain "
            "(reason=%s)", replica.name, reason,
        )
        return None

    def close(self) -> None:
        """Stop the handoff coordinator's worker thread (no-op on a
        unified fleet)."""
        with self._lock:
            coord, self.coordinator = self.coordinator, None
        if coord is not None:
            coord.stop()

    # ---- failover --------------------------------------------------------

    def poll(self) -> int:
        """Failover sweep: move every incomplete request whose replica
        died onto a survivor — live page migration when a migrator is
        attached, re-admission otherwise. On a disaggregated fleet the
        sweep is role-aware: dead-prefill requests re-dispatch on the
        prefill pool (committed handoffs just repoint to their decode
        owner), dead-decode slots migrate to decode survivors, and an
        emptied pool collapses the fleet to unified. Returns how many
        requests moved."""
        with self._lock:
            moved = 0
            if self.disaggregated:
                moved += self._drain_bounced()
                if self.coordinator is not None:
                    for r in self.decode_pool:
                        if not r.alive and id(r) not in self._dead_seen:
                            self._dead_seen.add(id(r))
                            n = self.coordinator.on_replica_dead(r)
                            if n:
                                logger.info(
                                    "dead decode replica %s: %d in-flight "
                                    "handoffs restarting elsewhere",
                                    r.name, n,
                                )
                if not any(r.alive for r in self.decode_pool) or not any(
                    r.alive for r in self.prefill_pool
                ):
                    self._collapse_locked()
            live = self._live()
            migrated_victims = set()
            for entry in self._entries:
                if entry.done or entry.replica.alive:
                    continue
                if not live:
                    raise RuntimeError(
                        "all serving replicas died with requests in flight"
                    )
                victim = entry.replica
                if self.disaggregated and victim in self.prefill_pool:
                    owner = self.coordinator.resolve_dead_donor(
                        entry.req.rid
                    )
                    if owner is not None and owner.alive:
                        # the handoff committed before the donor died —
                        # the decode replica owns the stream; re-admitting
                        # would duplicate it
                        entry.replica = owner
                        moved += 1
                        continue
                    survivor = self._re_admit_target()
                    logger.info(
                        "re-dispatching %s from dead prefill replica %s "
                        "onto %s", entry.req.rid, victim.name, survivor.name,
                    )
                    survivor.server.re_admit(entry.req)
                    entry.replica = survivor
                    moved += 1
                    continue
                if (
                    self.migrator is not None
                    and id(victim) not in migrated_victims
                    # a detached victim was already evacuated by
                    # remove_replica; a straggler entry here just
                    # re-admits below instead of re-running a migration
                    # against the drained engine
                    and id(victim) not in self._detached
                ):
                    migrated_victims.add(id(victim))
                    survivors = (
                        [r for r in self.decode_pool if r.alive]
                        if self.disaggregated and victim in self.decode_pool
                        else live
                    )
                    if survivors:
                        moved += self._migrate_victim(victim, survivors)
                if not entry.replica.alive:
                    # no migrator, or this request slipped past one
                    # (e.g. completed-but-unresolved slot): re-admit on a
                    # replica that can prefill it
                    survivor = self._re_admit_target()
                    logger.info(
                        "re-admitting %s from dead replica %s onto %s",
                        entry.req.rid, victim.name, survivor.name,
                    )
                    survivor.server.re_admit(entry.req)
                    entry.replica = survivor
                    moved += 1
            return moved

    def _migrate_victim(self, victim, live) -> int:
        """Drive one dead/drained replica through the migrator and
        repoint every entry it placed. Caller holds ``_lock``."""
        report = self.migrator.migrate(victim, live)
        self.reports.append(report)
        if self.watchdog is not None:
            self.watchdog.observe_migration(report, replica=victim.name)
        by_name = {r.name: r for r in live}
        placed = {}
        placed.update(report.placements)
        placed.update(report.re_prefilled)
        placed.update(report.re_routed)
        moved = 0
        for entry in self._entries:
            if entry.done or entry.replica is not victim:
                continue
            survivor_name = placed.get(entry.req.rid)
            if survivor_name in by_name:
                entry.replica = by_name[survivor_name]
                moved += 1
        logger.info(
            "migrated replica %s: path=%s live=%d re_prefilled=%d",
            victim.name, report.path, len(report.placements),
            len(report.re_prefilled),
        )
        return moved

    def wait_all(self, timeout: float = 120.0) -> List:
        """Poll for failovers while gathering every outstanding result
        (submission order). Raises on per-request failure or timeout.

        Waits in jittered-backoff slices (``comm._backoff_delay``, capped
        at attempt 3 ≈ 4 s so a mid-wait replica death is still noticed
        promptly) instead of a fixed 50 ms spin — one slow straggler no
        longer costs a poll storm. Deadlines are per-request: a request
        carrying ``deadline_s`` must finish within that budget of its
        OWN submit time; the ``timeout`` argument bounds the rest
        relative to this call."""
        import concurrent.futures
        import time

        from dlrover_tpu.common.comm import _backoff_delay

        t_start = time.monotonic()
        with self._lock:
            entries = list(self._entries)
        results = []
        for entry in entries:
            req = entry.req
            if req.deadline_s is not None:
                deadline = req.submit_t + req.deadline_s
            else:
                deadline = t_start + timeout
            attempt = 0
            while True:
                self.poll()
                remaining = deadline - time.monotonic()
                if remaining <= 0 and not req.future.done():
                    entry.replica.server.scheduler.count_timed_out()
                    raise concurrent.futures.TimeoutError(
                        f"request {req.rid} missed its deadline"
                    )
                wait = min(_backoff_delay(min(attempt, 3)), max(remaining, 0.0))
                try:
                    results.append(req.future.result(timeout=wait))
                    break
                except concurrent.futures.TimeoutError:
                    attempt += 1
        return results
