"""Elastic serving replicas: master registration + failover routing.

A ``ServingReplica`` wraps one ``GenerationServer`` and, when given a
master address, registers with the job master EXACTLY like a trainer
node (``NodeType.SERVING``): same heartbeat/failure machinery, same KV
store for discovery (address published under
``serving_replica_addr_<name>``, mirroring sparse/server.py's
``sparse_server_addr_`` channel). The master's node manager lists them
via ``serving_nodes()`` without treating them as part of the train
rendezvous.

``ReplicaRouter`` is the client-side elastic story: round-robin
dispatch over live replicas, and on replica death (``poll``) every
in-flight request of the dead replica moves to a survivor — exactly
once, no lost and no duplicated requests (the failover drills in
tests/test_serving_replica.py and tests/test_serving_migration.py pin
this). With a ``ServingMigrator`` attached the move is a LIVE KV-page
migration (serving/migration.py): the survivor adopts the victim's
pages and resumes mid-decode with zero re-prefilled prompt tokens,
bitwise-identical output. Without one — or when the migrator itself
degrades — requests are re-admitted under their original ticket and
re-prefill from the prompt (docs/serving.md describes the ladder).
"""

import json
import threading
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.serving.scheduler import Request
from dlrover_tpu.serving.server import GenerationServer

logger = get_logger(__name__)

ADDR_KV_PREFIX = "serving_replica_addr_"


class ServingReplica:
    """One serving host: a GenerationServer plus master-plane plumbing."""

    def __init__(
        self,
        name: str,
        params,
        cfg,
        *,
        master_addr: Optional[str] = None,
        node_id: int = 0,
        hub=None,
        **server_kw,
    ):
        self.name = name
        self.node_id = node_id
        self.master_addr = master_addr
        self.server = GenerationServer(
            params, cfg, hub=hub, replica=name, **server_kw
        )
        self._client = None

    @property
    def alive(self) -> bool:
        return self.server.alive

    def start(self) -> "ServingReplica":
        self.server.start()
        if self.master_addr:
            from dlrover_tpu.agent.master_client import MasterClient

            self._client = MasterClient(
                self.master_addr, node_id=self.node_id
            )
            self._client.register_node(node_type=NodeType.SERVING)
            self._client.kv_store_set(
                ADDR_KV_PREFIX + self.name,
                json.dumps({"name": self.name, "node_id": self.node_id}),
            )
        return self

    def stop(self) -> None:
        self.server.stop()
        if self._client is not None:
            self._client.report_node_status("exited", retries=1)
            self._client.close()
            self._client = None

    def kill(self) -> None:
        """Simulated host eviction: the serve loop halts, in-flight
        futures stay unresolved, and (unlike ``stop``) the master is
        NOT told about a clean exit — failure detection or the router's
        liveness poll must notice."""
        self.server.kill()
        if self._client is not None:
            self._client.close()
            self._client = None

    # convenience passthroughs
    def submit(self, *a, **kw) -> Request:
        return self.server.submit(*a, **kw)

    def generate(self, *a, **kw):
        return self.server.generate(*a, **kw)


def discover_replicas(client, names) -> Optional[Dict[str, dict]]:
    """Resolve replica names → registration payloads via the master KV
    store; None when any member hasn't registered yet (mirrors
    sparse/server.py resolve_ring: never adopt a partial set)."""
    out: Dict[str, dict] = {}
    for name in names:
        raw = client.kv_store_get(ADDR_KV_PREFIX + name)
        if not raw:
            logger.warning(
                "serving replica %s has no registration yet; deferring",
                name,
            )
            return None
        out[name] = json.loads(raw)
    return out


class _Entry:
    """Router-side view of one request: which replica holds it and
    whether its result already landed."""

    __slots__ = ("req", "replica", "done")

    def __init__(self, req: Request, replica: ServingReplica):
        self.req = req
        self.replica = replica
        self.done = False


class ReplicaRouter:
    """Round-robin request router with exactly-once failover.

    Requests fan out over live replicas. ``poll`` detects dead replicas
    and re-admits their incomplete requests on survivors under the
    ORIGINAL admission ticket (the ``Request`` object travels — its
    future resolves wherever the survivor finishes it). Completed
    entries are never resubmitted; ``Scheduler.complete`` resolves each
    future at most once even if a race double-delivers.
    """

    def __init__(
        self,
        replicas: List[ServingReplica],
        migrator=None,
        watchdog=None,
    ):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.migrator = migrator  # ServingMigrator or None (re-admit path)
        # optional ServingWatchdog: fed every MigrationReport so a run
        # of fallback outcomes classifies as ``migration_fallback``
        self.watchdog = watchdog
        self._entries: List[_Entry] = []
        self._rr = 0
        self._lock = threading.Lock()
        self.reports: List = []   # MigrationReports, drill introspection

    # ---- fleet latency rollup -------------------------------------------

    def fleet_histograms(self) -> Dict:
        """Merge every replica's per-phase latency histograms
        bucket-by-bucket (observability/histogram.py) — dead replicas
        included, their schedulers outlive the serve loop. Because the
        bucket boundaries are fixed by geometry, the merged counts are
        IDENTICAL to histogramming the concatenated raw samples: fleet
        percentiles come from counts, never from averaging per-replica
        percentiles."""
        from dlrover_tpu.observability.histogram import merge_histograms
        from dlrover_tpu.serving.scheduler import LATENCY_PHASES

        per = [r.server.scheduler.histograms() for r in self.replicas]
        out = {}
        for k in LATENCY_PHASES:
            merged = merge_histograms(p[k] for p in per)
            if merged is not None:
                out[k] = merged
        return out

    def fleet_latency_ms(self) -> dict:
        """Fleet end-to-end percentiles in the scheduler's
        ``{p50, p99, n}`` shape, from the merged histogram."""
        hists = self.fleet_histograms()
        if "e2e" not in hists:
            return {"p50": 0.0, "p99": 0.0, "n": 0}
        return hists["e2e"].summary()

    def _mark_done(self, entry: _Entry):
        def _cb(_future):
            entry.done = True

        return _cb

    def _live(self) -> List[ServingReplica]:
        return [r for r in self.replicas if r.alive]

    def submit(
        self, prompt, max_new_tokens: int, eos_id=None, priority: int = 0,
        sampling=None, deadline_s=None,
    ) -> Request:
        with self._lock:
            live = self._live()
            if not live:
                raise RuntimeError("no live serving replicas")
            replica = live[self._rr % len(live)]
            self._rr += 1
            req = replica.submit(
                prompt, max_new_tokens, eos_id=eos_id, priority=priority,
                sampling=sampling, deadline_s=deadline_s,
            )
            entry = _Entry(req, replica)
            req.future.add_done_callback(self._mark_done(entry))
            self._entries.append(entry)
        return req

    def poll(self) -> int:
        """Failover sweep: move every incomplete request whose replica
        died onto a survivor — live page migration when a migrator is
        attached, re-admission otherwise. Returns how many moved."""
        with self._lock:
            live = self._live()
            moved = 0
            migrated_victims = set()
            for entry in self._entries:
                if entry.done or entry.replica.alive:
                    continue
                if not live:
                    raise RuntimeError(
                        "all serving replicas died with requests in flight"
                    )
                victim = entry.replica
                if (
                    self.migrator is not None
                    and id(victim) not in migrated_victims
                ):
                    migrated_victims.add(id(victim))
                    moved += self._migrate_victim(victim, live)
                if not entry.replica.alive:
                    # no migrator, or this request slipped past one
                    # (e.g. completed-but-unresolved slot): re-admit
                    survivor = live[self._rr % len(live)]
                    self._rr += 1
                    logger.info(
                        "re-admitting %s from dead replica %s onto %s",
                        entry.req.rid, victim.name, survivor.name,
                    )
                    survivor.server.re_admit(entry.req)
                    entry.replica = survivor
                    moved += 1
            return moved

    def _migrate_victim(self, victim, live) -> int:
        """Drive one dead/drained replica through the migrator and
        repoint every entry it placed. Caller holds ``_lock``."""
        report = self.migrator.migrate(victim, live)
        self.reports.append(report)
        if self.watchdog is not None:
            self.watchdog.observe_migration(report, replica=victim.name)
        by_name = {r.name: r for r in live}
        placed = {}
        placed.update(report.placements)
        placed.update(report.re_prefilled)
        placed.update(report.re_routed)
        moved = 0
        for entry in self._entries:
            if entry.done or entry.replica is not victim:
                continue
            survivor_name = placed.get(entry.req.rid)
            if survivor_name in by_name:
                entry.replica = by_name[survivor_name]
                moved += 1
        logger.info(
            "migrated replica %s: path=%s live=%d re_prefilled=%d",
            victim.name, report.path, len(report.placements),
            len(report.re_prefilled),
        )
        return moved

    def wait_all(self, timeout: float = 120.0) -> List:
        """Poll for failovers while gathering every outstanding result
        (submission order). Raises on per-request failure or timeout.

        Waits in jittered-backoff slices (``comm._backoff_delay``, capped
        at attempt 3 ≈ 4 s so a mid-wait replica death is still noticed
        promptly) instead of a fixed 50 ms spin — one slow straggler no
        longer costs a poll storm. Deadlines are per-request: a request
        carrying ``deadline_s`` must finish within that budget of its
        OWN submit time; the ``timeout`` argument bounds the rest
        relative to this call."""
        import concurrent.futures
        import time

        from dlrover_tpu.common.comm import _backoff_delay

        t_start = time.monotonic()
        with self._lock:
            entries = list(self._entries)
        results = []
        for entry in entries:
            req = entry.req
            if req.deadline_s is not None:
                deadline = req.submit_t + req.deadline_s
            else:
                deadline = t_start + timeout
            attempt = 0
            while True:
                self.poll()
                remaining = deadline - time.monotonic()
                if remaining <= 0 and not req.future.done():
                    entry.replica.server.scheduler.count_timed_out()
                    raise concurrent.futures.TimeoutError(
                        f"request {req.rid} missed its deadline"
                    )
                wait = min(_backoff_delay(min(attempt, 3)), max(remaining, 0.0))
                try:
                    results.append(req.future.result(timeout=wait))
                    break
                except concurrent.futures.TimeoutError:
                    attempt += 1
        return results
