"""Radix prefix index over committed KV pages (SGLang-style).

Host-side companion to the refcounted ``PageAllocator``: a trie keyed on
token-id runs of ``page_size`` granularity. When a prefill chunk commits
a FULL page of pure prompt tokens, the engine interns that page here;
at admission the engine looks an incoming prompt up and — on a hit —
maps the matched physical pages straight into the new slot's block-table
row via ``admit_shared`` (zero prefill compute for matched pages).

Invariants:

- every node indexes exactly one live physical page (rc ≥ 1 in the
  allocator) whose pool payload is the committed KV of the node's
  root-to-node token path;
- keep-first on collision: a second slot committing the same token run
  descends through the existing holder's node, it never replaces it;
- ``drop_pages`` is wired to ``PageAllocator.on_free`` so a page whose
  refcount hits zero leaves the index atomically with its free-list
  return — a recycled page can never be offered as a prefix hit.

The planner (``plan_admission``) turns a raw trie match into the
admission recipe: which pages to map read-only, which single tail page
to copy-on-write, and where chunked prefill resumes. The resume point is
floored to a ``prefill_chunk`` multiple (chunk starts must stay aligned
— ``lax.dynamic_slice`` clamps out-of-range starts) and capped at
``prompt_len - 1`` so the final prompt token is always recomputed for
the first-token logits. Pages the plan keeps shared lie entirely below
the resume point, so prefill and decode never write into them; the COW
page's committed rows below ``matched_tokens`` are rewritten with
bitwise-identical values (chunked prefill is deterministic and the int8
row codec is row-local), which is what makes a prefix-hit stream
bitwise-equal to the cold stream.
"""

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

__all__ = [
    "PrefixMatch",
    "AdmissionPlan",
    "PrefixIndex",
    "plan_admission",
]


class PrefixMatch(NamedTuple):
    """Raw trie lookup result: ``pages[j]`` is the physical page whose
    committed KV covers prompt tokens ``[j*ps, (j+1)*ps)``; ``tail_page``
    (if any) matches only its first ``tail_tokens`` tokens."""

    pages: Tuple[int, ...]
    tail_page: Optional[int]
    tail_tokens: int

    def matched_tokens(self, page_size: int) -> int:
        return len(self.pages) * page_size + self.tail_tokens


class AdmissionPlan(NamedTuple):
    """Admission recipe derived from a match (see ``plan_admission``)."""

    shared: Tuple[int, ...]       # phys pages mapped read-only, logical 0..
    cow: Tuple[Tuple[int, int], ...]  # (logical, src_phys) to duplicate
    resume: int                   # first prompt position prefill recomputes
    matched_tokens: int           # raw trie match length (tokens)

    @property
    def prefix_pages(self) -> Tuple[int, ...]:
        """Contiguous logical run handed to ``admit_shared``: the shared
        pages followed by the COW sources (COW'd immediately after)."""
        return self.shared + tuple(src for _, src in self.cow)


class _Node:
    __slots__ = ("children", "page", "parent", "key")

    def __init__(self, parent=None, key=None, page=None):
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.key = key
        self.page = page


class PrefixIndex:
    """The radix/trie index. Mutated only on the engine thread (or under
    ``GenerationServer.paused()``) — same serialization contract as the
    allocator it mirrors."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self._root = _Node()
        self._by_page: Dict[int, _Node] = {}
        self.interned_total = 0
        self.dropped_total = 0

    def __len__(self) -> int:
        return len(self._by_page)

    @property
    def n_pages(self) -> int:
        """Live physical pages currently indexed."""
        return len(self._by_page)

    def stats(self) -> Dict[str, int]:
        return {
            "pages": len(self._by_page),
            "interned_total": self.interned_total,
            "dropped_total": self.dropped_total,
        }

    # ---- mutation --------------------------------------------------------

    def intern(self, tokens: Sequence[int], n_pages: int, phys_row) -> int:
        """Index the first ``n_pages`` FULL pages of ``tokens``;
        ``phys_row[j]`` is the physical page holding logical page ``j``.
        Existing nodes win (keep-first) — the walk descends through them
        without touching their page binding. Returns nodes created."""
        ps = self.page_size
        node = self._root
        created = 0
        for j in range(n_pages):
            key = tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])
            child = node.children.get(key)
            if child is None:
                p = int(phys_row[j])
                if p in self._by_page:
                    # a live page is indexed at most once; a duplicate
                    # here means the caller handed a stale row — stop
                    # rather than corrupt the reverse map
                    break
                child = _Node(parent=node, key=key, page=p)
                node.children[key] = child
                self._by_page[p] = child
                created += 1
            node = child
        self.interned_total += created
        return created

    def drop_pages(self, pages: Sequence[int]) -> int:
        """Remove freed pages from the index (``PageAllocator.on_free``).
        A dropped node takes its whole subtree out of the index: deeper
        prefixes are only reachable through it, so orphaning them would
        leak unreachable entries. Returns entries removed."""
        removed = 0
        for p in pages:
            node = self._by_page.pop(int(p), None)
            if node is None:
                continue
            removed += 1
            if node.parent is not None:
                node.parent.children.pop(node.key, None)
                node.parent = None
            stack = list(node.children.values())
            node.children = {}
            while stack:
                sub = stack.pop()
                self._by_page.pop(sub.page, None)
                removed += 1
                stack.extend(sub.children.values())
                sub.children = {}
        self.dropped_total += removed
        return removed

    # ---- lookup ----------------------------------------------------------

    def lookup(self, tokens: Sequence[int]) -> PrefixMatch:
        """Longest committed prefix of ``tokens``: full-page walk, then
        the best partial match among the next node's children (the
        longest common prefix of the remaining tokens with any child
        key — that child's page is the COW-able tail)."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        node = self._root
        pages: List[int] = []
        j = 0
        while (j + 1) * ps <= len(toks):
            child = node.children.get(tuple(toks[j * ps:(j + 1) * ps]))
            if child is None:
                break
            pages.append(child.page)
            node = child
            j += 1
        rest = toks[j * ps:(j + 1) * ps]
        tail_page, tail_tokens = None, 0
        if rest:
            for key, child in node.children.items():
                n = 0
                for a, b in zip(rest, key):
                    if a != b:
                        break
                    n += 1
                if n > tail_tokens:
                    tail_tokens, tail_page = n, child.page
        return PrefixMatch(tuple(pages), tail_page, tail_tokens)


def plan_admission(
    match: PrefixMatch,
    prompt_len: int,
    page_size: int,
    prefill_chunk: int,
) -> Optional[AdmissionPlan]:
    """Turn a trie match into the admission recipe, or None on a miss.

    ``resume`` — the first prompt position chunked prefill recomputes —
    is ``matched_tokens`` floored to a ``prefill_chunk`` multiple and
    capped at ``prompt_len - 1`` (the last prompt token always re-runs
    so the first generated token's logits exist). Matched pages then
    split three ways by their span against ``resume``:

    - entirely below ``resume`` → mapped shared, read-only (rc+1);
    - straddling ``resume`` → at most ONE page: mapped then COW'd, its
      rows in ``[page_start, resume)`` survive the copy and the rest are
      deterministically rewritten by the resumed prefill;
    - at or above ``resume`` → discarded (prefill rewrites them whole,
      a copy would be pure waste).
    """
    matched = min(match.matched_tokens(page_size), prompt_len)
    resume = min(matched, prompt_len - 1)
    resume -= resume % prefill_chunk
    if resume <= 0:
        return None
    all_pages = list(match.pages)
    if match.tail_page is not None:
        all_pages.append(match.tail_page)
    n_keep = resume // page_size
    shared = tuple(all_pages[:n_keep])
    cow: Tuple[Tuple[int, int], ...] = ()
    if resume % page_size and n_keep < len(all_pages):
        cow = ((n_keep, all_pages[n_keep]),)
    if not shared and not cow:
        return None
    return AdmissionPlan(shared, cow, resume, matched)


def affinity_ok(
    plan: Optional[AdmissionPlan], prompt_len: int, max_suffix: int
) -> bool:
    """Whether a prefix hit is strong enough for a decode-role replica
    to admit the request directly — the shared pages are already
    resident, so only the divergent suffix (``prompt_len - resume``
    tokens) needs local prefill, and that must stay under
    ``max_suffix`` or the decode fleet re-inherits the chunked-prefill
    interference the prefill/decode split exists to remove."""
    return (
        plan is not None
        and plan.resume > 0
        and prompt_len - plan.resume <= max_suffix
    )
