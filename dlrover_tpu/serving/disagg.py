"""Disaggregated prefill/decode serving: the streaming handoff wire.

Chunked prefill steals decode steps on a unified replica — every
admitted prompt burns ``prefill_chunk``-token steps on the decode
critical path, coupling TTFT to TPOT. The split (ROADMAP 1(b)):
prefill-role engines run chunked prefill ONLY and ship each finished
prompt to a decode-role engine whose step is pure batched decode. The
transport is the PR 14 migration wire — ``RequestSnapshot`` →
checksummed blob → ``import_slot`` — reused verbatim; the only new
things are a *schedule* (fragments stream per committed chunk, overlap
with the next chunk's compute) and a ``page_start`` offset in the
snapshot meta.

One handoff, happy path::

    prefill engine loop                    coordinator worker
    -------------------                    ------------------
    chunk 0 commits ──sink──▶ frag[0,k)──▶ reserve on decode replica
    chunk 1 commits ──sink──▶ frag[k,m)──▶ stage_pages (idempotent)
    ...                                    ...
    last chunk + token 0 ──▶ final frag ─▶ stage + import_slot(commit)
    slot parks phase="handoff"             repoint router, release donor

Because the pages ship exactly as stored and every sampling draw folds
in the absolute position (PR 13), the decode continuation is bitwise
the unified stream — PROVIDED both fleets run the same
``prefill_chunk`` (chunk width changes reduction order).

Exactly-once under faults — the coordinator lock guards a per-request
``committed``/``cancelled`` pair:

- torn fragment (``TornPageTransfer``) → re-export the same logical
  range from the donor (committed pages are immutable) up to
  ``retries`` times, then degrade: abort the reservation, release the
  donor slot, re-dispatch under the ORIGINAL ticket through the
  router's prefill pool.
- dead decode target pre-commit → restart the whole stream on another
  decode replica (staging is offset-addressed, so a replay is a
  harmless rewrite).
- dead prefill donor → ``resolve_dead_donor``: a committed handoff
  returns its owner (the router repoints, no re-admit); an in-flight
  one is cancelled atomically and the router re-dispatches.
- ``local_done`` (prompt finished at its first token) → cancel any
  fragments already streamed, abort the reservation.

Lock protocol (deadlock-free by construction): router lock → coordinator
lock is the only compound order; the donor sink takes ONLY the
coordinator lock; the worker never holds the coordinator lock while
pausing a PREFILL replica (whose loop thread runs the sink) or while
taking the router lock. Decode-replica pauses under the coordinator
lock are safe — decode loops touch neither lock (their bounce lane is
a lock-free deque the router drains).

Fault point: ``serving.handoff`` (rank = donor node_id), checked before
each fragment decode — ``drop_page``/``torn_donation`` specs drive the
torn-stream drills in tests/test_serving_disagg.py.
"""

import queue
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.comm import _backoff_delay
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.elastic.faults import (
    FaultInjector,
    TornDonation,
    get_injector,
)
from dlrover_tpu.observability.tracing import get_tracer
from dlrover_tpu.serving.migration import (
    RequestSnapshot,
    ServingMigrator,
    decode_snapshot,
    encode_snapshot,
    geometry_fingerprint,
)
from dlrover_tpu.serving.scheduler import AdmissionError

logger = get_logger(__name__)


class HandoffError(RuntimeError):
    """A handoff cannot proceed (no decode capacity, donor slot gone):
    the coordinator degrades it to re-prefill — never a lost request."""


def snapshot_fragment(
    engine, i: int, s, start: int, stop: int, *, final: bool
) -> RequestSnapshot:
    """One streaming-handoff fragment: pages ``[start, stop)`` of slot
    ``i`` plus the resume metadata. Mid-stream fragments carry
    ``phase="prefill"`` and no generated tokens; the final fragment
    carries the full resume state (``phase="decode"``, token 0) so the
    receiver commits straight into a decode lane."""
    return RequestSnapshot(
        rid=s.req.rid,
        prompt=[int(t) for t in s.prompt],
        generated=list(s.generated) if final else [],
        n_prefilled=int(s.n_prefilled) if final else 0,
        phase="decode" if final else "prefill",
        max_new_tokens=int(s.req.max_new_tokens),
        seed=int(s.req.sampling.seed),
        page_start=int(start),
        pages=engine.export_pages(i, start, stop),
        **geometry_fingerprint(engine.geom),
    )


class _Handoff:
    """One request's prefill→decode transfer state."""

    __slots__ = (
        "rid", "req", "donor", "slot", "target", "reserved", "shipped",
        "committed", "cancelled", "t0", "bytes", "fragments",
    )

    def __init__(self, rid, req, donor, slot):
        self.rid = rid
        self.req = req
        self.donor = donor          # ServingReplica (prefill role)
        self.slot = slot            # donor slot index
        self.target = None          # ServingReplica (decode role)
        self.reserved = False
        self.shipped = 0            # logical pages exported so far
        self.committed = False
        self.cancelled = False
        self.t0 = time.monotonic()
        self.bytes = 0
        self.fragments = 0


class HandoffCoordinator:
    """Streams finished prompts from the prefill pool into decode-pool
    reservations and commits them exactly once.

    The donor side runs on each prefill engine's loop thread (the
    ``handoff_sink`` hook — export + encode only, no blocking calls);
    a single daemon worker thread does everything with latency or
    locks in it: reservation, CRC verify, staging, commit, degrade.
    """

    def __init__(
        self,
        prefill_pool: List,
        decode_pool: List,
        *,
        router=None,
        faults: Optional[FaultInjector] = None,
        streaming: bool = True,
        reserve_attempts: int = 6,
        retries: int = 1,
        shed_per_attempt: int = 2,
    ):
        self.prefill_pool = list(prefill_pool)
        self.decode_pool = list(decode_pool)
        self.router = router
        self.faults = faults if faults is not None else get_injector()
        self.streaming = streaming
        self.reserve_attempts = reserve_attempts
        self.retries = retries
        self.shed_per_attempt = shed_per_attempt
        self._lock = threading.Lock()
        self._by_rid: Dict[str, _Handoff] = {}
        self._q: "queue.Queue" = queue.Queue()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._disabled = False
        self.degraded = 0           # handoffs that fell to re-prefill
        self.completed = 0          # handoffs committed

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "HandoffCoordinator":
        for rep in self.prefill_pool:
            rep.server.engine.handoff_sink = self._make_sink(rep)
        self._thread = threading.Thread(
            target=self._worker_loop, name="handoff-coordinator", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, join: bool = True) -> None:
        self._stop_evt.set()
        if join and self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def pending(self) -> int:
        with self._lock:
            return sum(
                1 for h in self._by_rid.values()
                if not h.committed and not h.cancelled
            )

    # ---- donor side (prefill engine loop thread) -------------------------

    def _make_sink(self, rep):
        def sink(i, s, event):
            if self._disabled:
                return
            rid = s.req.rid
            if event == "local_done":
                self._q.put(("cancel", rid, None))
                return
            if event == "chunk" and not self.streaming:
                return
            eng = rep.server.engine
            with self._lock:
                h = self._by_rid.get(rid)
                if h is None:
                    h = _Handoff(rid, s.req, rep, i)
                    self._by_rid[rid] = h
                if h.cancelled:
                    return
                start = h.shipped
            if event == "chunk":
                # only FULL pages are immutable mid-prompt; a partial
                # tail page still collects rows from later chunks
                stop = s.n_prefilled // eng.geom.page_size
                final = False
            else:  # "done" — slot just parked in phase="handoff"
                stop = eng.alloc.slot_pages(i)
                final = True
            if stop <= start and not final:
                return
            snap = snapshot_fragment(eng, i, s, start, stop, final=final)
            blob = encode_snapshot(snap)
            with self._lock:
                h.shipped = max(h.shipped, stop)
            self._q.put(("frag", rid, (blob, start, stop, final)))
        return sink

    # ---- worker side -----------------------------------------------------

    def _worker_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                kind, rid, payload = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                if kind == "cancel":
                    self._handle_cancel(rid)
                elif kind == "restart":
                    self._handle_restart(rid)
                else:
                    self._handle_fragment(rid, *payload)
            except Exception as e:  # noqa: BLE001 — degrade, never wedge
                logger.warning("handoff of %s degraded: %s", rid, e)
                self._degrade(rid)

    def _get(self, rid: str) -> Optional[_Handoff]:
        with self._lock:
            h = self._by_rid.get(rid)
            if h is None or h.cancelled or h.committed:
                return None
            return h

    def _handle_fragment(self, rid, blob, start, stop, final) -> None:
        h = self._get(rid)
        if h is None:
            return
        if h.target is None:
            self._reserve(h)
        snap = self._decode_with_retry(h, blob, start, stop, final)
        if snap is None:
            return  # cancelled under our feet
        ServingMigrator._check_geometry(snap, h.target.server.engine)
        with h.target.server.paused() as eng:
            eng.stage_pages(rid, snap.page_start, snap.pages)
        h.bytes += len(blob)
        h.fragments += 1
        h.donor.server.engine.note_handoff_bytes(len(blob))
        if final:
            self._commit(h, snap)

    def _decode_with_retry(self, h, blob, start, stop, final):
        """Verify a fragment blob; a torn one is re-exported from the
        donor (committed pages are immutable, so the re-snapshot is the
        same bytes) up to ``retries`` times."""
        for attempt in range(self.retries + 1):
            try:
                self.faults.at("serving.handoff", rank=h.donor.node_id)
                return decode_snapshot(blob)
            except TornDonation as e:
                if attempt >= self.retries:
                    raise
                logger.info(
                    "torn handoff fragment for %s (attempt %d): %s — "
                    "re-exporting pages [%d, %d)",
                    h.rid, attempt + 1, e, start, stop,
                )
                with h.donor.server.paused() as eng:
                    s = eng.slots[h.slot]
                    if s is None or s.req.rid != h.rid:
                        raise HandoffError(
                            f"donor slot for {h.rid} gone mid-retry"
                        ) from e
                    snap = snapshot_fragment(
                        eng, h.slot, s, start, stop, final=final
                    )
                blob = encode_snapshot(snap)
        return None  # unreachable

    def _pick_target(self):
        live = [r for r in self.decode_pool if r.alive]
        if not live:
            return None
        return max(live, key=lambda r: r.server.engine.alloc.free_pages)

    def _reserve(self, h: _Handoff) -> None:
        """Hold the request's FULL footprint (prompt + generation) on
        the least-loaded live decode replica; page pressure sheds the
        target's lowest-priority queued new admissions and backs off,
        same ladder as the failover migrator."""
        for attempt in range(self.reserve_attempts):
            tgt = self._pick_target()
            if tgt is None:
                raise HandoffError(
                    f"no live decode replica for {h.rid}"
                )
            with tgt.server.paused() as eng:
                ok = eng.alloc.reserve_for_migration(
                    h.rid, h.req.total_tokens
                )
            if ok:
                h.target = tgt
                h.reserved = True
                return
            tgt.server.scheduler.shed_lowest(
                count=self.shed_per_attempt, below_priority=h.req.priority
            )
            self._stop_evt.wait(_backoff_delay(attempt))
        raise HandoffError(
            f"no decode replica could reserve {h.req.total_tokens} tokens "
            f"for {h.rid} in {self.reserve_attempts} attempts"
        )

    def _commit(self, h: _Handoff, snap: RequestSnapshot) -> None:
        """Flip ownership: import the staged reservation into a decode
        lane. Atomic against cancellation (coordinator lock); a full
        lane table retries with backoff — the reservation already holds
        the pages, only a slot index is awaited."""
        t_resume = time.monotonic()
        for attempt in range(self.reserve_attempts):
            with self._lock:
                if h.cancelled:
                    break
                try:
                    with h.target.server.paused() as eng:
                        eng.import_slot(
                            h.req,
                            None,
                            phase="decode",
                            n_prefilled=snap.n_prefilled,
                            generated=snap.generated,
                            reserved_tag=h.rid,
                            handoff=True,
                        )
                    h.committed = True
                except AdmissionError:
                    pass  # no free lane yet — back off below
            if h.committed or h.cancelled:
                break
            self._stop_evt.wait(_backoff_delay(attempt))
        if not h.committed:
            if not h.cancelled:
                raise HandoffError(
                    f"no free decode lane for {h.rid} on {h.target.name}"
                )
            self._abort_reservation(h)
            return
        # --- success path, all outside the coordinator lock ---
        dt_ms = (time.monotonic() - h.t0) * 1e3
        h.target.server.scheduler.record_handoff_ms(dt_ms)
        tr = get_tracer()
        if tr.enabled:
            tr.complete_span(
                "serving.handoff_transfer", h.t0, rid=h.rid,
                donor=h.donor.name, target=h.target.name,
                bytes=h.bytes, fragments=h.fragments,
            )
            tr.complete_span(
                "serving.handoff_resume", t_resume, rid=h.rid,
                replica=h.target.name, n_prefilled=snap.n_prefilled,
            )
        if self.router is not None:
            self.router._repoint(h.rid, h.target)
        with h.donor.server.paused() as eng:
            s = eng.slots[h.slot] if h.slot < len(eng.slots) else None
            if s is not None and s.req.rid == h.rid:
                eng.release_slot(h.slot, reason="handoff_out")
        with self._lock:
            self._by_rid.pop(h.rid, None)
        self.completed += 1
        logger.info(
            "handoff %s: %s → %s, %d fragments, %d bytes, %.1f ms",
            h.rid, h.donor.name, h.target.name, h.fragments, h.bytes, dt_ms,
        )

    def _handle_cancel(self, rid: str) -> None:
        """The prompt finished locally on the prefill replica
        (max_new=1 / instant EOS): unwind any fragments already
        streamed."""
        with self._lock:
            h = self._by_rid.pop(rid, None)
            if h is None or h.committed:
                return
            h.cancelled = True
        self._abort_reservation(h)

    def _handle_restart(self, rid: str) -> None:
        """Replay a stream whose decode target died pre-commit onto a
        fresh target: re-export everything shipped so far from the
        donor (readable even off a dead donor — kill halts the loop,
        the pools stay) and run it through the normal fragment path.
        Staging is offset-addressed, so overlap with late original
        fragments is a harmless rewrite."""
        h = self._get(rid)
        if h is None:
            return
        with h.donor.server.paused() as eng:
            s = eng.slots[h.slot] if h.slot < len(eng.slots) else None
            if s is None or s.req.rid != rid:
                return  # slot already released/completed
            final = s.phase == "handoff"
            if final:
                stop = eng.alloc.slot_pages(h.slot)
            else:
                stop = s.n_prefilled // eng.geom.page_size
            snap = snapshot_fragment(eng, h.slot, s, 0, stop, final=final)
        blob = encode_snapshot(snap)
        with self._lock:
            h.shipped = max(h.shipped, stop)
        self._handle_fragment(rid, blob, 0, stop, final)

    def _abort_reservation(self, h: _Handoff) -> None:
        if h.target is None or not h.reserved:
            return
        with h.target.server.paused() as eng:
            try:
                eng.alloc.abort_migration(h.rid)
            except KeyError:
                pass
        h.reserved = False

    def _degrade(self, rid: str) -> None:
        """The re-prefill tier: abort the reservation, release the donor
        slot, hand the request back to the router under its ORIGINAL
        ticket. The request is never lost (the router re-dispatches or,
        with no router, the donor re-queues it) and never duplicated
        (cancelled-before-commit is atomic)."""
        with self._lock:
            h = self._by_rid.pop(rid, None)
            if h is None or h.committed:
                return
            h.cancelled = True
        self._abort_reservation(h)
        with h.donor.server.paused() as eng:
            s = eng.slots[h.slot] if h.slot < len(eng.slots) else None
            if s is not None and s.req.rid == rid:
                eng.release_slot(h.slot, reason="handoff_abort")
        self.degraded += 1
        if self.router is not None:
            self.router.redispatch(h.req)
        else:
            h.donor.server.re_admit(h.req)

    # ---- failover hooks (called by ReplicaRouter.poll) -------------------

    def resolve_dead_donor(self, rid: str):
        """Exactly-once resolution for a request whose PREFILL replica
        died: returns the decode replica that already owns it (handoff
        committed — repoint, do NOT re-admit) or None after atomically
        cancelling any in-flight transfer (caller re-dispatches; a
        worker mid-commit observes ``cancelled`` and aborts)."""
        with self._lock:
            h = self._by_rid.get(rid)
            if h is None:
                return None
            if h.committed:
                return h.target
            h.cancelled = True
            self._by_rid.pop(rid, None)
        self._abort_reservation(h)
        self.degraded += 1
        return None

    # ---- elastic membership (serving autoscaler) -------------------------

    def attach_prefill(self, rep) -> None:
        """Scale-out: a warm prefill replica joins the donor pool and
        gets its handoff sink installed, mirroring ``start()``."""
        with self._lock:
            if rep in self.prefill_pool:
                return
            self.prefill_pool.append(rep)
        rep.server.engine.handoff_sink = self._make_sink(rep)

    def attach_decode(self, rep) -> None:
        """Scale-out: a warm decode replica becomes a handoff target
        (``_pick_target`` sees it on the next reservation)."""
        with self._lock:
            if rep not in self.decode_pool:
                self.decode_pool.append(rep)

    def detach(self, rep) -> None:
        """Scale-in: stop targeting/sourcing ``rep`` for NEW handoffs.
        Handoffs it is already donating keep streaming until the
        caller's drain completes — the sink stays installed, and a
        stopped loop simply stops calling it. Uncommitted handoffs
        TARGETING a detached decode replica restart elsewhere, same as
        the death path (the donor still holds the pages)."""
        with self._lock:
            if rep in self.prefill_pool:
                self.prefill_pool.remove(rep)
            was_decode = rep in self.decode_pool
            if was_decode:
                self.decode_pool.remove(rep)
        if was_decode:
            self.on_replica_dead(rep)

    def on_replica_dead(self, rep) -> int:
        """A DECODE replica died: every uncommitted handoff targeting it
        restarts on a surviving decode replica (the donor still holds
        the pages). Returns how many restarts were queued."""
        restart = []
        with self._lock:
            for h in self._by_rid.values():
                if h.target is rep and not h.committed and not h.cancelled:
                    h.target = None
                    h.reserved = False  # dead allocator — nothing to abort
                    restart.append(h.rid)
        for rid in restart:
            self._q.put(("restart", rid, None))
        return len(restart)

    def collapse(self) -> List:
        """Fold the fleet back to unified — the last rung of the
        degradation ladder, taken when either pool has no live member.
        In-flight transfers are cancelled and every occupied prefill
        slot is released for re-prefill: a prefill-role slot holds a
        PROMPT-ONLY page footprint, so it cannot decode in place —
        its request must re-admit with the full footprint under its
        original ticket. Returns those orphaned ``Request``s; the
        caller (``ReplicaRouter``) re-dispatches them onto the
        now-unified fleet. The coordinator never takes the router lock
        here — collapse is called under it."""
        with self._lock:
            self._disabled = True
            pending = [
                h for h in self._by_rid.values() if not h.committed
            ]
            for h in pending:
                h.cancelled = True
            self._by_rid.clear()
        for h in pending:
            if h.target is not None and h.target.alive:
                self._abort_reservation(h)
        orphans = []
        for rep in self.prefill_pool:
            with rep.server.paused() as eng:
                eng.handoff_sink = None
                eng.role = "unified"
                for i, s in enumerate(eng.slots):
                    if s is not None and not s.req.future.done():
                        orphans.append(s.req)
                        eng.release_slot(i, reason="handoff_abort")
        for rep in self.decode_pool:
            with rep.server.paused() as eng:
                eng.role = "unified"
        self.degraded += len(orphans)
        # no join: the worker may be waiting on the router lock the
        # caller holds — it observes the stop event and exits on its own
        self.stop(join=False)
        return orphans
