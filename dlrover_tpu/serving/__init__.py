"""Continuous-batching generation serving tier.

Layers (bottom up):

- ``kv_cache``   — paged KV cache: block-table allocator over fixed-size
  pages (refcounted, so committed prefix pages can back several slots),
  int8 storage with per-block scales (``ops/quant.py`` encode) or
  a bf16 reference mode, gather/write helpers that run inside jit.
- ``prefix``     — host-side radix index over committed KV pages:
  interned as chunked prefill commits full prompt pages, consulted at
  admission to map a hot prefix's pages copy-on-write into a new slot
  (zero prefill compute for the matched run, one physical copy).
- ``engine``     — the continuous-batching decode loop: fixed decode
  slots, admit/evict at step boundaries, chunked prefill.
- ``scheduler``  — threaded request queue: priority by arrival,
  admission control, p50/p99 latency accounting → ``ServingRecord``.
- ``server``     — the threaded frontend owning the engine loop.
- ``migration``  — live KV-page migration: a drained/evicted replica's
  held pages (int8 payloads + scales, block-table order, position and
  sampling state) transfer to survivors that reserved the footprint;
  decode resumes mid-stream bitwise, degrading to re-prefill on torn
  or over-deadline transfers.
- ``replica``    — elastic integration: replicas register with the
  master like trainer nodes; a router migrates an evicted replica's
  in-flight requests to survivors (re-admitting when migration is
  unavailable).

Import submodules directly (``from dlrover_tpu.serving import engine``)
— this package init stays import-light so allocator/scheduler unit
tests never pay the model-stack import.
"""
