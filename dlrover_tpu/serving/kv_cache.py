"""Paged KV cache for the serving engine.

vLLM-style paged attention, TPU-native and CPU-testable: physical KV
storage is a pool of fixed-size pages; each decode slot owns a row of a
block table mapping logical page index → physical page. Admission grabs
pages from a free list, eviction returns them — no compaction, no
per-request contiguous buffers, so slot lifetimes can interleave freely.

Two storage modes share one geometry:

- ``bf16`` — reference mode: pages hold the model compute dtype
  verbatim, so a gather reproduces a contiguous ``decoder.init_kv_cache``
  buffer bitwise (the parity baseline).
- ``int8`` — pages hold int8 payloads + per-block f32 scales using the
  same EQuARX-style max/127 block encode as the gradient wire
  (``ops/quant.py`` ``kv_encode_rows``), dequantized per-page INSIDE the
  jitted decode step. A token row of ``kv_heads*head_dim`` bf16 elements
  (2 bytes each) becomes ``row`` int8 bytes + ``row/kv_block`` f32
  scales — ≥1.7× resident-bytes reduction at every real shape (1.94× at
  the tiny row=128, 1.97× at llama rows).

Physical page 0 is the TRASH page: never allocated, the write target
for masked-out lanes (inactive slots, prefill-chunk padding). Gathers
clamp unassigned block-table entries (-1) onto it; whatever lands there
is garbage by construction and every reader masks it by slot position.

Host side (``PageAllocator``) is plain numpy + a free list — the engine
ships ``block_tables()`` into jit each step. Device side (``gather`` /
``write_rows``) is pure jnp so it fuses into the decode step. Live
page migration between replicas (``serving/migration.py``) holds its
survivor-side footprint through the allocator's named reservations
(``reserve_for_migration`` / ``commit_migration`` / ``abort_migration``)
so an in-flight transfer can never lose its landing pages to admission.

Committed pages are shareable: every physical page carries a refcount,
``admit_shared`` maps a prefix of another slot's pages into a new slot's
table row (rc+1, zero prefill compute for those pages), ``cow_page``
gives a slot a private copy of a shared page before it may write into
it, and ``evict`` decrements — a page returns to the free list only at
rc==0. The radix index that decides WHICH pages a new prompt can share
lives in ``serving/prefix.py``; this module only enforces the refcount
discipline.
"""

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.ops import quant

TRASH_PAGE = 0


class PageGeometry(NamedTuple):
    """Static shape/layout contract between allocator, pools and jit."""

    n_layers: int
    kv_heads: int
    head_dim: int
    page_size: int           # tokens per page
    n_pages: int             # physical pages incl. the trash page
    max_pages_per_slot: int  # block-table width
    mode: str                # "bf16" | "int8"
    dtype: str               # model compute dtype (gather output / bf16 pools)
    kv_block: int            # int8 scale-block width (elements)

    @property
    def row_elems(self) -> int:
        return self.kv_heads * self.head_dim

    @property
    def n_blocks(self) -> int:
        return self.row_elems // self.kv_block

    @property
    def max_len(self) -> int:
        """Longest sequence one slot can hold (gather width S_max)."""
        return self.max_pages_per_slot * self.page_size


def make_geometry(
    cfg,
    *,
    n_slots: int,
    max_len: int,
    page_size: int = 16,
    mode: str = "int8",
    slack_pages: int = 0,
) -> PageGeometry:
    """Geometry sized so ``n_slots`` concurrent sequences of ``max_len``
    tokens always fit, plus ``slack_pages`` headroom and the trash page."""
    if mode not in ("bf16", "int8"):
        raise ValueError(f"mode must be bf16|int8, got {mode}")
    max_pages = -(-max_len // page_size)
    row = cfg.kv_heads * cfg.head_dim
    return PageGeometry(
        n_layers=cfg.n_layer,
        kv_heads=cfg.kv_heads,
        head_dim=cfg.head_dim,
        page_size=page_size,
        n_pages=1 + n_slots * max_pages + slack_pages,
        max_pages_per_slot=max_pages,
        mode=mode,
        dtype=str(cfg.dtype),
        kv_block=quant.kv_block_size(row),
    )


def init_pools(geom: PageGeometry) -> Dict[str, jax.Array]:
    """Allocate the physical page pools (layer-leading, so the decoder's
    layer scan can carry gathered views as xs)."""
    g = geom
    if g.mode == "bf16":
        shape = (g.n_layers, g.n_pages, g.page_size, g.kv_heads, g.head_dim)
        dt = jnp.dtype(g.dtype)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    qshape = (g.n_layers, g.n_pages, g.page_size, g.n_blocks, g.kv_block)
    sshape = (g.n_layers, g.n_pages, g.page_size, g.n_blocks)
    return {
        "k_q": jnp.zeros(qshape, jnp.int8),
        "k_scale": jnp.zeros(sshape, jnp.float32),
        "v_q": jnp.zeros(qshape, jnp.int8),
        "v_scale": jnp.zeros(sshape, jnp.float32),
    }


def resident_bytes(geom: PageGeometry) -> int:
    """Resident KV pool bytes at this geometry — the bench memory stat."""
    g = geom
    rows = g.n_layers * g.n_pages * g.page_size
    if g.mode == "bf16":
        return 2 * rows * g.row_elems * jnp.dtype(g.dtype).itemsize
    return 2 * rows * (g.row_elems + 4 * g.n_blocks)


def stored_row_bytes(geom: PageGeometry) -> int:
    """Stored bytes of one token's K+V row (payload + int8 scales)."""
    g = geom
    if g.mode == "bf16":
        return 2 * g.row_elems * jnp.dtype(g.dtype).itemsize
    return 2 * (g.row_elems + 4 * g.n_blocks)


def decode_traffic_bytes(
    geom: PageGeometry, pages_held: int, n_slots: int, paged: bool
) -> int:
    """KV HBM bytes one decode step touches under each kernel — the
    bench's per-token traffic model (``bench.py serve``).

    - ``paged``: every layer reads only the ``pages_held`` pages the
      whole batch holds and writes one row per slot::

          L · (pages_held · page_size + B) · stored_row_bytes

    - gather: every layer reads the FULL ``B · max_pages`` table width
      from the pools, materializes the dequantized compute-dtype copy
      (one write of ``B · S_max`` dense rows), re-reads it in
      attention, and scatters the new row back::

          L · B · S_max · (stored_row_bytes + 2 · dense_row_bytes)
          + L · B · stored_row_bytes

    Model, not measurement: it counts page/row payload traffic and
    ignores Q/O activations (identical under both kernels) — the point
    is the asymptotic split, O(pages held) vs O(table width).
    """
    g = geom
    rb = stored_row_bytes(g)
    dense = 2 * g.row_elems * jnp.dtype(g.dtype).itemsize
    if paged:
        return g.n_layers * (pages_held * g.page_size + n_slots) * rb
    smax = g.max_len
    return g.n_layers * n_slots * (smax * (rb + 2 * dense) + rb)


def gather(
    pools: Dict,
    block_tables: jax.Array,
    geom: PageGeometry,
    *,
    max_pages: int = None,
) -> Dict:
    """Materialize per-slot contiguous caches from the page pools.

    ``block_tables`` [B, max_pages] int32 (-1 = unassigned → trash page)
    → ``{"k","v"}`` [L, B, W·page_size, Hkv, D] in the model compute
    dtype, the exact layout ``decoder.decode_step`` scans. Unassigned/
    garbage positions carry finite trash values; callers mask by slot
    position.

    ``max_pages`` (static under jit) slices the gather to the first
    ``max_pages`` table entries — the host knows how many pages any
    slot actually holds, and pages are assigned in logical order, so
    the dropped tail is all ``-1``-clamped trash. Every reader masks
    by position, and masked slots contribute exact zeros through the
    f32 softmax, so a narrower gather is bitwise-invisible — it just
    stops touching (and dequantizing, in int8 mode) the whole table
    width.
    """
    g = geom
    tables = (
        block_tables if max_pages is None else block_tables[:, :max_pages]
    )
    t = jnp.maximum(tables, 0)
    b = block_tables.shape[0]
    width = t.shape[1] * g.page_size

    def _shape(x):
        return x.reshape(g.n_layers, b, width, g.kv_heads, g.head_dim)

    if g.mode == "bf16":
        return {"k": _shape(pools["k"][:, t]), "v": _shape(pools["v"][:, t])}
    dt = jnp.dtype(g.dtype)
    k = quant.kv_decode_rows(pools["k_q"][:, t], pools["k_scale"][:, t], dt)
    v = quant.kv_decode_rows(pools["v_q"][:, t], pools["v_scale"][:, t], dt)
    return {"k": _shape(k), "v": _shape(v)}


def write_rows(
    pools: Dict,
    block_tables: jax.Array,  # [B, max_pages] int32
    positions: jax.Array,     # [B, C] int32 absolute token positions
    valid: jax.Array,         # [B, C] bool — invalid lanes → trash page
    k_rows: jax.Array,        # [L, B, C, Hkv, D]
    v_rows: jax.Array,        # [L, B, C, Hkv, D]
    geom: PageGeometry,
) -> Dict:
    """Scatter token K/V rows into their slots' pages (jit-side).

    Distinct live (slot, position) pairs that WRITE always map to
    distinct (page, offset) cells: the allocator hands a fresh page to
    exactly one slot, and a shared page (rc > 1, prefix sharing) is
    read-only by contract — the engine COW-duplicates it before any
    sharer may write past the committed prefix. Only trash-page lanes
    may collide, and those are garbage by construction."""
    g = geom
    page_idx = positions // g.page_size
    offs = positions % g.page_size
    phys = jnp.take_along_axis(block_tables, page_idx, axis=1)
    phys = jnp.where(valid, jnp.maximum(phys, 0), TRASH_PAGE)
    offs = jnp.where(valid, offs, 0)
    if g.mode == "bf16":
        dt = pools["k"].dtype
        return {
            "k": pools["k"].at[:, phys, offs].set(k_rows.astype(dt)),
            "v": pools["v"].at[:, phys, offs].set(v_rows.astype(dt)),
        }
    lead = k_rows.shape[:3]
    kq, ks = quant.kv_encode_rows(k_rows.reshape(*lead, g.row_elems),
                                  g.kv_block)
    vq, vs = quant.kv_encode_rows(v_rows.reshape(*lead, g.row_elems),
                                  g.kv_block)
    return {
        "k_q": pools["k_q"].at[:, phys, offs].set(kq),
        "k_scale": pools["k_scale"].at[:, phys, offs].set(ks),
        "v_q": pools["v_q"].at[:, phys, offs].set(vq),
        "v_scale": pools["v_scale"].at[:, phys, offs].set(vs),
    }


class PageAllocator:
    """Host-side block-table allocator over the physical page pool.

    Invariants (pinned by the property test in
    tests/test_serving_kv_cache.py):

    - every physical page's refcount equals the number of (slot, logical)
      table cells mapping it — 1 for a private page, >1 when prefix
      sharing maps one committed page into several slots;
    - page 0 (trash) is never handed out;
    - ``evict`` decrements each held page's refcount and frees only the
      pages that reach rc==0 (sharers keep the rest alive);
    - free + assigned-unique (rc ≥ 1) + reserved is a partition of pages
      1..n_pages-1.

    Reservations are the migration footprint hold: pages moved from the
    free list into a named bucket, invisible to ``can_admit``/``ensure``
    until ``commit_migration`` assigns them to a slot or
    ``abort_migration`` returns them. Mutations are not locked — callers
    serialize through the engine thread (or ``GenerationServer.paused()``).

    ``on_free`` (optional) fires with the list of physical pages whose
    refcount just hit zero — the prefix index hangs its invalidation off
    this so a recycled page can never be offered as a prefix hit.
    """

    def __init__(self, geom: PageGeometry, n_slots: int):
        self.geom = geom
        self.n_slots = n_slots
        # pop() yields ascending physical pages — deterministic layouts
        self._free = list(range(geom.n_pages - 1, TRASH_PAGE, -1))
        self._reserved: Dict[str, List[int]] = {}
        self._tables = np.full(
            (n_slots, geom.max_pages_per_slot), -1, np.int32
        )
        self._n_pages = np.zeros(n_slots, np.int32)
        # per-physical-page refcount: number of (slot, logical) cells
        # mapping the page. Free and reserved pages sit at 0.
        self._rc = np.zeros(geom.n_pages, np.int32)
        # set by every table mutation; the engine consumes it to re-ship
        # the device copy only when something actually changed
        self._dirty = True
        # cached host-side snapshot for block_tables(); invalidated by
        # the same mutations that set _dirty (but cleared independently:
        # consume_dirty() must not force the next block_tables() to copy)
        self._snap: Optional[np.ndarray] = None
        self.on_free: Optional[Callable[[List[int]], None]] = None

    # ---- queries ---------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(int(n_tokens), 0) // self.geom.page_size)

    def can_admit(self, n_tokens: int, n_shared: int = 0) -> bool:
        """True when a slot covering ``n_tokens`` fits. ``n_shared``
        discounts prefix pages that would be MAPPED rather than drawn
        from the free list (a prefix hit's read-only shared pages —
        COW'd tail pages are fresh allocations and get no discount)."""
        need = self.pages_needed(n_tokens)
        return (
            need <= self.geom.max_pages_per_slot
            and need - min(int(n_shared), need) <= len(self._free)
        )

    def slot_pages(self, slot: int) -> int:
        return int(self._n_pages[slot])

    def refcount(self, page: int) -> int:
        return int(self._rc[page])

    @property
    def unique_assigned_pages(self) -> int:
        """Distinct physical pages held by any slot — the denominator of
        the dedup ratio (Σ slot cells / unique pages)."""
        return int(np.count_nonzero(self._rc))

    @property
    def reserved_pages(self) -> int:
        return sum(len(p) for p in self._reserved.values())

    def reservation(self, tag: str) -> Tuple[int, ...]:
        """The physical pages held under ``tag`` (empty if none)."""
        return tuple(self._reserved.get(tag, ()))

    def block_tables(self) -> np.ndarray:
        """A host-side snapshot of the [n_slots, max_pages] table.

        The snapshot is cached between mutations: the common steady
        state (no admit/grow/evict this step) returns the SAME array
        without re-copying. Mutations write ``self._tables`` and drop
        the cache, so a previously returned snapshot never aliases a
        buffer ``evict``/``ensure`` mutates mid-step — callers may hand
        it to jit or keep it across steps."""
        if self._snap is None:
            self._snap = self._tables.copy()
        return self._snap

    def consume_dirty(self) -> bool:
        """True exactly once after any table mutation since the last
        call (admit/grow/evict). Lets the engine skip the per-step
        host-to-device block-table transfer on the (common) steps where
        no slot changed shape."""
        d = self._dirty
        self._dirty = False
        return d

    # ---- transitions -----------------------------------------------------

    def admit(self, slot: int, n_tokens: int) -> bool:
        """Assign pages covering ``n_tokens`` to an EMPTY slot."""
        if self._n_pages[slot]:
            raise ValueError(f"slot {slot} already holds pages")
        return self.ensure(slot, n_tokens)

    def ensure(self, slot: int, n_tokens: int) -> bool:
        """Grow ``slot`` to cover ``n_tokens`` total; False (state
        unchanged) when the free list cannot cover the growth."""
        need = self.pages_needed(n_tokens)
        if need > self.geom.max_pages_per_slot:
            return False
        have = int(self._n_pages[slot])
        grow = need - have
        if grow <= 0:
            return True
        if grow > len(self._free):
            return False
        for i in range(have, need):
            p = self._free.pop()
            self._tables[slot, i] = p
            self._rc[p] = 1
        self._n_pages[slot] = need
        self._dirty = True
        self._snap = None
        return True

    def admit_shared(
        self, slot: int, n_tokens: int, prefix_pages: Sequence[int]
    ) -> bool:
        """Admit an EMPTY slot covering ``n_tokens``, mapping logical
        pages 0..len(prefix_pages)-1 onto EXISTING physical pages
        (rc+1 each — a prefix hit) and drawing the remainder fresh.
        False (state unchanged) when the free list cannot cover the
        unshared suffix. Shared pages are read-only for this slot until
        ``cow_page`` gives it a private copy."""
        if self._n_pages[slot]:
            raise ValueError(f"slot {slot} already holds pages")
        need = self.pages_needed(n_tokens)
        shared = list(prefix_pages)
        if len(shared) > need:
            raise ValueError(
                f"prefix ({len(shared)} pages) exceeds footprint ({need})"
            )
        if need > self.geom.max_pages_per_slot:
            return False
        if need - len(shared) > len(self._free):
            return False
        for p in shared:  # validate BEFORE mutating — no partial maps
            if not (TRASH_PAGE < p < self.geom.n_pages) or self._rc[p] < 1:
                raise ValueError(f"prefix page {p} is not live")
        for i, p in enumerate(shared):
            self._tables[slot, i] = p
            self._rc[p] += 1
        for i in range(len(shared), need):
            p = self._free.pop()
            self._tables[slot, i] = p
            self._rc[p] = 1
        self._n_pages[slot] = need
        if need:
            self._dirty = True
            self._snap = None
        return True

    def cow_page(self, slot: int, logical: int) -> Optional[Tuple[int, int]]:
        """Give ``slot`` a private copy of its ``logical`` page before it
        writes into it. No-op (returns None) when the page is already
        private (rc==1). Otherwise pops a fresh page, remaps the cell,
        and returns ``(src, dst)`` physical pages — the caller copies the
        pool payload device-side. Raises when the free list is empty:
        the admission footprint must already have accounted for the COW
        page (``can_admit`` gives shared discounts only to read-only
        prefix pages)."""
        if not 0 <= logical < int(self._n_pages[slot]):
            raise ValueError(f"slot {slot} has no logical page {logical}")
        src = int(self._tables[slot, logical])
        if self._rc[src] == 1:
            return None
        if not self._free:
            raise RuntimeError("cow_page: free list empty (footprint bug)")
        dst = self._free.pop()
        self._tables[slot, logical] = dst
        self._rc[src] -= 1
        self._rc[dst] = 1
        self._dirty = True
        self._snap = None
        return src, dst

    def evict(self, slot: int) -> int:
        """Release every page the slot holds (rc−1 each; pages reaching
        rc==0 return to the free list); returns the CELL count released
        — the slot's logical footprint, not the pages actually freed."""
        n = int(self._n_pages[slot])
        freed: List[int] = []
        for i in range(n):
            p = int(self._tables[slot, i])
            self._rc[p] -= 1
            if self._rc[p] == 0:
                self._free.append(p)
                freed.append(p)
        self._tables[slot, :] = -1
        self._n_pages[slot] = 0
        if n:
            self._dirty = True
            self._snap = None
        if freed and self.on_free is not None:
            self.on_free(freed)
        return n

    # ---- migration reservations ------------------------------------------

    def reserve_for_migration(self, tag: str, n_tokens: int) -> bool:
        """Hold the full page footprint for an incoming migrated request
        under ``tag``. False (state unchanged) when the free list cannot
        cover it — the migrator sheds/backs off and retries."""
        if tag in self._reserved:
            raise ValueError(f"migration tag {tag!r} already reserved")
        need = self.pages_needed(n_tokens)
        if need > self.geom.max_pages_per_slot or need > len(self._free):
            return False
        self._reserved[tag] = [self._free.pop() for _ in range(need)]
        return True

    def commit_migration(self, tag: str, slot: int) -> List[int]:
        """Assign the reservation's pages to an EMPTY slot's table row,
        in reservation order (logical page i → reserved page i). Returns
        the physical pages so the importer can scatter payloads."""
        if tag not in self._reserved:
            raise KeyError(f"no migration reservation {tag!r}")
        if self._n_pages[slot]:
            raise ValueError(f"slot {slot} already holds pages")
        pages = self._reserved.pop(tag)
        for i, p in enumerate(pages):
            self._tables[slot, i] = p
            self._rc[p] = 1
        self._n_pages[slot] = len(pages)
        if pages:
            self._dirty = True
            self._snap = None
        return list(pages)

    def abort_migration(self, tag: str) -> int:
        """Return a reservation's pages to the free list (torn transfer,
        fallback to re-prefill). Missing tag is a no-op — abort must be
        safe to call from any phase's unwind."""
        pages = self._reserved.pop(tag, [])
        self._free.extend(pages)
        return len(pages)
