"""Continuous-batching decode engine over the paged KV cache.

Orca/vLLM-style iteration-level scheduling on a FIXED decode batch of
``n_slots`` lanes: requests are admitted into free slots and evicted at
step boundaries — never mid-step — so the jitted decode step compiles
once and every iteration runs the full batch with a per-lane ``valid``
mask. Each step is:

1. finish: resolve slots that hit ``max_new_tokens``/EOS, free pages;
2. admit: pop queued requests into free slots (head-of-line admission —
   the scheduler's top request waits for pages rather than being jumped);
3. prefill one chunk: ONE slot advances its prompt by ``prefill_chunk``
   tokens per engine step (chunked prefill — long prompts interleave
   with decode instead of stalling the whole batch);
4. decode: one token for every decoding slot in a single jitted call.

Greedy decoding only: the argmax lives in-graph so each step ships one
int32 per slot to the host. Sampling (per-request temperature, top-k)
needs per-slot rng plumbing through the fixed batch and is a documented
follow-on in docs/serving.md.

Two decode kernels share the loop (``paged`` ctor flag):

- **paged** (default) — ``decoder.decode_step_paged`` /
  ``prefill_chunk_paged``: steps are ``pools → paged step → pools``.
  K/V rows commit straight to their page cells and attention walks the
  block table (``ops/pallas_paged.py``), so no contiguous
  ``[L, B, S_max, ...]`` cache is ever materialized and per-token KV
  traffic is O(pages held). The page walk is bounded by a power-of-two
  bucket of the max pages any slot holds (a STATIC jit arg — a handful
  of compiles over a slot's lifetime, each reading less of the table).
- **gather** (``paged=False``) — the original
  gather → decode → scatter round trip, kept as the parity reference
  (bf16 outputs are bitwise identical between the two).

The block-table device array is re-shipped only when the allocator
reports a mutation (``consume_dirty``) — steady-state decode steps
reuse the cached device copy.

Alignment invariant: the slot capacity ``S_max`` must be a multiple of
``prefill_chunk``. Chunk starts are always multiples of the chunk width,
and ``lax.dynamic_slice`` CLAMPS out-of-bounds starts — an unaligned
tail window would silently shift the slice and corrupt earlier cache
rows. ``__init__`` enforces it.
"""

import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import decoder
from dlrover_tpu.serving import kv_cache as kvc
from dlrover_tpu.serving.scheduler import Request, Scheduler


@dataclass
class _Slot:
    """Host-side state of one decode lane."""

    req: Request
    phase: str                  # "prefill" | "decode"
    prompt: np.ndarray          # int32 [P]
    n_prefilled: int = 0
    generated: List[int] = field(default_factory=list)


class ServingEngine:
    """Single-replica continuous-batching engine (host loop + 2 jits)."""

    def __init__(
        self,
        params,
        cfg,
        scheduler: Scheduler,
        *,
        n_slots: int = 4,
        max_len: int = 128,
        page_size: int = 16,
        mode: str = "int8",
        prefill_chunk: int = 8,
        slack_pages: int = 0,
        paged: bool = True,
        page_bucketing: bool = True,
    ):
        self.params = params
        self.cfg = cfg
        self.scheduler = scheduler
        self.n_slots = n_slots
        self.prefill_chunk = prefill_chunk
        self.paged = bool(paged)
        self.page_bucketing = bool(page_bucketing)
        self.geom = kvc.make_geometry(
            cfg, n_slots=n_slots, max_len=max_len, page_size=page_size,
            mode=mode, slack_pages=slack_pages,
        )
        if self.geom.max_len % prefill_chunk:
            raise ValueError(
                f"slot capacity {self.geom.max_len} (pages*page_size) must "
                f"be a multiple of prefill_chunk={prefill_chunk}: chunk "
                "starts are chunk-aligned and dynamic_slice clamps "
                "out-of-bounds starts, which would corrupt earlier rows"
            )
        self.alloc = kvc.PageAllocator(self.geom, n_slots)
        self.pools = kvc.init_pools(self.geom)
        self.slots: List[Optional[_Slot]] = [None] * n_slots
        self._tokens = 0
        self._t0: Optional[float] = None
        self._tables_dev = None   # cached device block tables
        self._table_ships = 0     # host→device table transfers
        self._step_time = 0.0     # wall seconds inside jitted steps

        geom = self.geom
        chunk_w = prefill_chunk
        # buffer donation is a no-op (with a warning) on the CPU backend
        donate = (1,) if jax.default_backend() != "cpu" else ()

        if paged:

            def decode_fn(params, pools, tables, tokens, pos, valid,
                          max_pages):
                """One token for every slot, pools → pools: rows commit
                straight to page cells, attention walks the block table
                (no contiguous-cache gather anywhere in the trace)."""
                logits, pools = decoder.decode_step_paged(
                    params, tokens, pools, tables, pos, valid, cfg,
                    max_pages=max_pages,
                )
                return jnp.argmax(logits, -1).astype(jnp.int32), pools

            def chunk_fn(params, pools, tables, tokens, start, chunk_len,
                         max_pages):
                """One prefill chunk for ONE slot (batch dim kept at 1),
                pools → pools; argmax at the last VALID position (only
                meaningful on the final chunk, where it is token 0 of
                the continuation)."""
                logits, pools = decoder.prefill_chunk_paged(
                    params, tokens, pools, tables, start, chunk_len, cfg,
                    max_pages=max_pages,
                )
                last = jnp.take_along_axis(
                    logits, (chunk_len - 1)[:, None, None], axis=1
                )[:, 0]
                return jnp.argmax(last, -1).astype(jnp.int32), pools

        else:

            def decode_fn(params, pools, tables, tokens, pos, valid,
                          max_pages):
                """One token for every slot: gather pages → decode_step →
                scatter the new K/V row back (invalid lanes → trash page).
                The parity reference for the paged kernel; the gather is
                sliced to ``max_pages`` held pages."""
                views = kvc.gather(pools, tables, geom, max_pages=max_pages)
                logits, new_cache = decoder.decode_step(
                    params, tokens, views, pos, cfg, prefilled=True
                )
                take = jax.vmap(
                    lambda c, p: jax.lax.dynamic_slice_in_dim(
                        c, p, 1, axis=1
                    )[:, 0],
                    in_axes=(1, 0),
                    out_axes=1,
                )
                rows_k = take(new_cache["k"], pos)[:, :, None]
                rows_v = take(new_cache["v"], pos)[:, :, None]
                pools = kvc.write_rows(
                    pools, tables, pos[:, None], valid[:, None],
                    rows_k, rows_v, geom,
                )
                return jnp.argmax(logits, -1).astype(jnp.int32), pools

            def chunk_fn(params, pools, tables, tokens, start, chunk_len,
                         max_pages):
                """Gather-mode prefill chunk (see decode_fn above)."""
                views = kvc.gather(pools, tables, geom, max_pages=max_pages)
                logits, new_cache = decoder.prefill_chunk(
                    params, tokens, views, start, cfg
                )
                take = jax.vmap(
                    lambda c, s: jax.lax.dynamic_slice_in_dim(
                        c, s, chunk_w, axis=1
                    ),
                    in_axes=(1, 0),
                    out_axes=1,
                )
                rows_k = take(new_cache["k"], start)
                rows_v = take(new_cache["v"], start)
                positions = (
                    start[:, None] + jnp.arange(chunk_w, dtype=jnp.int32)
                )
                valid = jnp.arange(chunk_w)[None, :] < chunk_len[:, None]
                pools = kvc.write_rows(
                    pools, tables, positions, valid, rows_k, rows_v, geom,
                )
                last = jnp.take_along_axis(
                    logits, (chunk_len - 1)[:, None, None], axis=1
                )[:, 0]
                return jnp.argmax(last, -1).astype(jnp.int32), pools

        self._decode_fn = jax.jit(
            decode_fn, donate_argnums=donate, static_argnums=(6,)
        )
        self._chunk_fn = jax.jit(
            chunk_fn, donate_argnums=donate, static_argnums=(6,)
        )

    # ---- queries ---------------------------------------------------------

    @property
    def max_len(self) -> int:
        """Longest prompt+generation one slot can hold."""
        return self.geom.max_len

    def active_slots(self) -> int:
        return sum(s is not None for s in self.slots)

    def stats(self) -> dict:
        dt = time.monotonic() - self._t0 if self._t0 else 0.0
        return {
            "active_slots": self.active_slots(),
            "free_pages": self.alloc.free_pages,
            "tokens_generated": self._tokens,
            "tokens_per_s": self._tokens / dt if dt > 0 else 0.0,
            "decode_kernel": "paged" if self.paged else "gather",
            "table_ships": self._table_ships,
            "step_time_s": self._step_time,
            "host_time_s": max(0.0, dt - self._step_time),
        }

    def resident_kv_bytes(self) -> int:
        return kvc.resident_bytes(self.geom)

    # ---- device-side inputs ----------------------------------------------

    def _device_tables(self):
        """The block tables as a device array, re-shipped only when the
        allocator mutated since the last ship (the dirty flag) — a
        steady-state decode step reuses the cached copy instead of
        paying a host→device transfer per step."""
        if self.alloc.consume_dirty() or self._tables_dev is None:
            self._tables_dev = jnp.asarray(self.alloc.block_tables())
            self._table_ships += 1
        return self._tables_dev

    def _pages_bucket(self) -> int:
        """STATIC page-walk width for the jitted steps: the next power
        of two ≥ the max pages any slot holds, floored at 4 so tiny
        geometries don't churn compiles. Bounds the attention walk (and
        the gather reference's width) by what is actually resident
        while keeping recompiles to a handful over a slot's lifetime."""
        held = max(
            (self.alloc.slot_pages(i) for i in range(self.n_slots)),
            default=1,
        )
        b = 4
        while b < held:
            b *= 2
        if not self.page_bucketing:  # ablation: legacy full-pool width
            return self.geom.max_pages_per_slot
        return min(b, self.geom.max_pages_per_slot)

    # ---- the step loop ---------------------------------------------------

    def step(self) -> bool:
        """One engine iteration; returns False when fully idle (the
        server thread uses that to sleep instead of spinning)."""
        worked = self._finish_and_evict()
        worked = self._admit() or worked
        if self._t0 is None and any(self.slots):
            self._t0 = time.monotonic()
        worked = self._prefill_one() or worked
        worked = self._decode_batch() or worked
        return worked

    def drain(self, timeout: float = 120.0) -> None:
        """Step until queue and slots are empty (tests / bench)."""
        deadline = time.monotonic() + timeout
        while self.scheduler.queue_depth() or self.active_slots():
            self.step()
            if time.monotonic() > deadline:
                raise TimeoutError("engine did not drain in time")

    def _finish_and_evict(self) -> bool:
        worked = False
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "decode":
                continue
            req = s.req
            done = len(s.generated) >= req.max_new_tokens or (
                req.eos_id is not None
                and s.generated
                and s.generated[-1] == req.eos_id
            )
            if not done:
                continue
            self.scheduler.complete(
                req, [int(t) for t in s.prompt] + s.generated
            )
            self.alloc.evict(i)
            self.slots[i] = None
            worked = True
        return worked

    def _admit(self) -> bool:
        worked = False
        while True:
            try:
                idx = self.slots.index(None)
            except ValueError:
                return worked

            def can(req):
                # oversize requests pass so they can be popped and FAILED
                # (they would block the head of the line forever)
                if req.total_tokens > self.geom.max_len:
                    return True
                return self.alloc.can_admit(req.total_tokens)

            req = self.scheduler.pop_next(can)
            if req is None:
                return worked
            if req.total_tokens > self.geom.max_len:
                self.scheduler.fail(req, ValueError(
                    f"request {req.rid} needs {req.total_tokens} tokens "
                    f"> slot capacity {self.geom.max_len}"
                ))
                continue
            # reserve the FULL prompt+generation footprint up front so a
            # decoding slot can never deadlock waiting for pages
            self.alloc.admit(idx, req.total_tokens)
            self.slots[idx] = _Slot(
                req=req, phase="prefill",
                prompt=np.asarray(req.prompt, np.int32),
            )
            worked = True

    def _prefill_one(self) -> bool:
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "prefill":
                continue
            p = len(s.prompt)
            clen = min(self.prefill_chunk, p - s.n_prefilled)
            chunk = np.zeros(self.prefill_chunk, np.int32)
            chunk[:clen] = s.prompt[s.n_prefilled:s.n_prefilled + clen]
            tables = self._device_tables()[i:i + 1]
            t0 = time.monotonic()
            tok0, self.pools = self._chunk_fn(
                self.params, self.pools, tables,
                jnp.asarray(chunk[None]),
                jnp.asarray([s.n_prefilled], jnp.int32),
                jnp.asarray([clen], jnp.int32),
                self._pages_bucket(),
            )
            tok0 = np.asarray(tok0)
            self._step_time += time.monotonic() - t0
            s.n_prefilled += clen
            if s.n_prefilled == p:
                s.generated = [int(tok0[0])]
                s.phase = "decode"
                self.scheduler.record_first_token(s.req)
                self._tokens += 1
            return True
        return False

    def _decode_batch(self) -> bool:
        live = [
            i for i, s in enumerate(self.slots)
            if s is not None and s.phase == "decode"
        ]
        if not live:
            return False
        tokens = np.zeros(self.n_slots, np.int32)
        pos = np.zeros(self.n_slots, np.int32)
        valid = np.zeros(self.n_slots, bool)
        for i in live:
            s = self.slots[i]
            tokens[i] = s.generated[-1]
            pos[i] = len(s.prompt) + len(s.generated) - 1
            valid[i] = True
        t0 = time.monotonic()
        tok, self.pools = self._decode_fn(
            self.params, self.pools, self._device_tables(),
            jnp.asarray(tokens), jnp.asarray(pos), jnp.asarray(valid),
            self._pages_bucket(),
        )
        tok = np.asarray(tok)
        self._step_time += time.monotonic() - t0
        for i in live:
            self.slots[i].generated.append(int(tok[i]))
            self._tokens += 1
        return True
