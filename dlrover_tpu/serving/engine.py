"""Continuous-batching decode engine over the paged KV cache.

Orca/vLLM-style iteration-level scheduling on a FIXED decode batch of
``n_slots`` lanes: requests are admitted into free slots and evicted at
step boundaries — never mid-step — so the jitted decode step compiles
once and every iteration runs the full batch with a per-lane ``valid``
mask. Each step is:

1. finish: resolve slots that hit ``max_new_tokens``/EOS, free pages;
2. admit: pop queued requests into free slots (head-of-line admission —
   the scheduler's top request waits for pages rather than being jumped);
3. prefill one chunk: ONE slot advances its prompt by ``prefill_chunk``
   tokens per engine step (chunked prefill — long prompts interleave
   with decode instead of stalling the whole batch);
4. decode: one token for every decoding slot in a single jitted call —
   or, with speculative decoding enabled (``spec_k > 0``), one VERIFY
   chunk that can commit up to ``spec_k + 1`` tokens per slot per step.

Per-request sampling is first-class: every ``Request`` carries
``SamplingParams(temperature, top_k, top_p, seed)`` and the fused
in-step sampler draws ``categorical(warp_logits(...))`` with a per-slot
threefry key folded by ABSOLUTE buffer position — deterministic given
the seed and stable across admit/evict reordering and router failover
re-admission (a re-prefilled request re-derives the identical draws).
``temperature=0`` stays the in-graph argmax, bitwise identical to the
historical greedy engine.

Speculative decoding (``spec_k``, prompt-lookup drafts by default):
each decoding slot proposes up to ``spec_k`` continuation tokens from
an n-gram suffix match over its own history (no second model — the
``DraftModel`` hook accepts one), and one jitted verify step scores
``[last token, drafts...]`` against the paged cache with DEFERRED K/V
writes. Acceptance is gumbel-coupled rejection sampling: position j's
target token is drawn exactly as the sequential sampler would draw it,
a draft survives iff it EQUALS that draw, and the first mismatch emits
the target draw — so the output stream is token-for-token the
spec-off stream (exactly the target-model distribution; greedy is the
temperature=0 case). Only the accepted prefix of chunk K/V rows is
committed to the pools — rejected draft rows never reach page storage,
so encode-on-write int8 needs no rollback.

Two decode kernels share the loop (``paged`` ctor flag):

- **paged** (default) — ``decoder.decode_step_paged`` /
  ``prefill_chunk_paged``: steps are ``pools → paged step → pools``.
  K/V rows commit straight to their page cells and attention walks the
  block table (``ops/pallas_paged.py``), so no contiguous
  ``[L, B, S_max, ...]`` cache is ever materialized and per-token KV
  traffic is O(pages held). The page walk is bounded by a power-of-two
  bucket of the max pages any slot holds (a STATIC jit arg — a handful
  of compiles over a slot's lifetime, each reading less of the table).
- **gather** (``paged=False``) — the original
  gather → decode → scatter round trip, kept as the parity reference
  (bf16 outputs are bitwise identical between the two).

The block-table device array is re-shipped only when the allocator
reports a mutation (``consume_dirty``) — steady-state decode steps
reuse the cached device copy.

Prefix sharing (``prefix_sharing=True``): committed prompt pages are
interned into a radix index (``serving/prefix.py``) as chunked prefill
fills them, and admission consults the index — on a hit the new slot's
block-table prefix maps the SAME physical pages (refcounted in the
allocator), prefill resumes at the first divergent chunk boundary, and
a partially-matched tail page is copy-on-write duplicated before the
slot may write into it. Shared pages are read-only through both decode
kernels for free: attention reads via block tables, and every write the
engine issues lands at positions ≥ the resume point, which the plan
keeps strictly above the shared pages. ``admission_lookahead`` lets the
scheduler admit a later request whose (prefix-discounted) footprint
fits past a blocked cold head-of-line request.

Disaggregated prefill/decode (``role`` ctor flag, serving/disagg.py):

- ``unified`` (default) — today's engine, bitwise-unchanged.
- ``prefill`` — chunked prefill ONLY: every prefill slot advances one
  chunk per step (large effective chunk) and the decode/spec batch is
  never traced. Admission reserves a PROMPT-ONLY footprint (the
  generation pages live on the decode replica), and a completed prompt
  leaves through ``handoff_sink`` — fired after each committed chunk
  (``"chunk"``, streaming page shipment overlapped with the next
  chunk's compute), at completion (``"done"``), or when the request
  finishes at its first token with nothing to hand off
  (``"local_done"``).
- ``decode`` — pure batched decode: raw prompts are never
  chunk-prefilled. Work arrives as handoffs (``import_slot`` with a
  staged reservation) or as prefix-affinity admissions whose radix-index
  plan covers all but ``affinity_suffix_max`` trailing prompt tokens
  (the short divergent suffix is the only prefill this engine runs). A
  popped request whose plan degraded is parked on ``bounced`` for the
  router to re-dispatch through the prefill pool.

Alignment invariant: the slot capacity ``S_max`` must be a multiple of
``prefill_chunk``. Chunk starts are always multiples of the chunk width,
and ``lax.dynamic_slice`` CLAMPS out-of-bounds starts — an unaligned
tail window would silently shift the slice and corrupt earlier cache
rows. ``__init__`` enforces it.
"""

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import decoder, generate
from dlrover_tpu.observability.tracing import get_tracer
from dlrover_tpu.ops import pallas_paged, quant
from dlrover_tpu.serving import kv_cache as kvc
from dlrover_tpu.serving import prefix as prefix_mod
from dlrover_tpu.serving.scheduler import AdmissionError, Request, Scheduler


class DraftModel:
    """Draft-token proposer hook for speculative decoding.

    ``propose(history, k)`` returns up to ``k`` candidate continuation
    tokens for a slot whose committed stream is ``history``
    (prompt + generated so far). Runs on the host between jitted steps;
    returning ``[]`` makes the slot fall back to plain decode for that
    step. Acceptance is handled by the engine's verify step, so a
    proposer can be arbitrarily wrong without affecting the output
    distribution — only the accept rate."""

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError


class PromptLookupDraft(DraftModel):
    """Prompt-lookup (n-gram) drafting — no second model.

    Finds the most recent EARLIER occurrence of the history's trailing
    n-gram (longest first, ``max_ngram`` down to ``min_ngram``) and
    proposes the tokens that followed it. Input-grounded workloads
    (summarization, code edits, retrieval) repeat long prompt spans
    verbatim, which is exactly what this matches."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError("need max_ngram >= min_ngram >= 1")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        hist = [int(t) for t in history]
        if k <= 0 or len(hist) < 2:
            return []
        top = min(self.max_ngram, len(hist) - 1)
        for n in range(top, self.min_ngram - 1, -1):
            pat = hist[-n:]
            for i in range(len(hist) - n - 1, -1, -1):
                if hist[i:i + n] == pat:
                    # i + n <= len-1, so there is always >= 1 token here
                    return hist[i + n:i + n + k]
        return []


@dataclass
class _Slot:
    """Host-side state of one decode lane."""

    req: Request
    phase: str                  # "prefill" | "decode" | "handoff"
    prompt: np.ndarray          # int32 [P]
    key_data: np.ndarray        # uint32 [2] — threefry key for sampling
    n_prefilled: int = 0
    generated: List[int] = field(default_factory=list)
    span: object = None         # open "serving.decode" trace span, if any
    interned_pages: int = 0     # full prompt pages already in the trie


class ServingEngine:
    """Single-replica continuous-batching engine (host loop + 2 jits)."""

    def __init__(
        self,
        params,
        cfg,
        scheduler: Scheduler,
        *,
        n_slots: int = 4,
        max_len: int = 128,
        page_size: int = 16,
        mode: str = "int8",
        prefill_chunk: int = 8,
        slack_pages: int = 0,
        paged: bool = True,
        page_bucketing: bool = True,
        spec_k: int = 0,
        draft: Optional[DraftModel] = None,
        prefix_sharing: bool = False,
        admission_lookahead: int = 0,
        role: str = "unified",
        affinity_suffix_max: Optional[int] = None,
    ):
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be 'unified', 'prefill' or 'decode', got {role!r}"
            )
        self.role = role
        # disaggregation hook (serving/disagg.py): a prefill-role engine
        # calls sink(slot_idx, slot, event) with event "chunk" after
        # every committed chunk, "done" at prefill completion, and
        # "local_done" when the request finished at its first token
        self.handoff_sink = None
        # decode-role bounce lane: popped requests whose prefix-affinity
        # plan no longer qualifies park here for the router to re-dispatch
        # through the prefill pool — a decode-role engine never
        # chunk-prefills a cold prompt
        self.bounced: deque = deque()
        if affinity_suffix_max is None:
            affinity_suffix_max = 2 * prefill_chunk if role == "decode" else 0
        self.affinity_suffix_max = int(affinity_suffix_max)
        self.params = params
        self.cfg = cfg
        self.scheduler = scheduler
        self.n_slots = n_slots
        self.prefill_chunk = prefill_chunk
        self.paged = bool(paged)
        self.page_bucketing = bool(page_bucketing)
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        self.spec_k = int(spec_k)
        self.draft = draft if draft is not None else PromptLookupDraft()
        self.geom = kvc.make_geometry(
            cfg, n_slots=n_slots, max_len=max_len, page_size=page_size,
            mode=mode, slack_pages=slack_pages,
        )
        if self.geom.max_len % prefill_chunk:
            raise ValueError(
                f"slot capacity {self.geom.max_len} (pages*page_size) must "
                f"be a multiple of prefill_chunk={prefill_chunk}: chunk "
                "starts are chunk-aligned and dynamic_slice clamps "
                "out-of-bounds starts, which would corrupt earlier rows"
            )
        self.alloc = kvc.PageAllocator(self.geom, n_slots)
        self.pools = kvc.init_pools(self.geom)
        self.prefix_sharing = bool(prefix_sharing)
        self.admission_lookahead = int(admission_lookahead)
        self.trie: Optional[prefix_mod.PrefixIndex] = None
        if self.prefix_sharing:
            self.trie = prefix_mod.PrefixIndex(page_size)
            # pages whose refcount hits zero leave the index atomically
            # with their free-list return
            self.alloc.on_free = self.trie.drop_pages
        self.slots: List[Optional[_Slot]] = [None] * n_slots
        self.draining = False     # planned drain: stop admitting new work
        self._tokens = 0
        self._t0: Optional[float] = None
        self._tables_dev = None   # cached device block tables
        self._table_ships = 0     # host→device table transfers
        self._step_time = 0.0     # wall seconds inside jitted steps
        self._draft_tokens = 0    # drafts proposed to the verify step
        self._accepted_tokens = 0  # drafts that survived acceptance
        self._prefill_tokens = 0  # prompt tokens run through the chunk fn
        self._prefill_chunks = 0  # chunk_fn invocations (the compute unit)
        self._migrated_in = 0     # requests adopted as live KV pages
        self._migrated_out = 0    # requests donated as live KV pages
        self._handoffs_in = 0     # disagg handoffs committed into a slot
        self._handoffs_out = 0    # prefilled requests released downstream
        self._handoff_bytes = 0   # wire bytes shipped/staged (both roles)
        self._affinity_bounced = 0  # decode-role pops with a degraded plan
        self._prefix_hits = 0     # admissions that mapped shared pages
        self._prefix_misses = 0   # sharing-on admissions with no usable hit
        self._prefill_tokens_saved = 0  # prompt tokens skipped via hits
        self._cow_pages = 0       # tail pages copy-on-write duplicated
        self._peak_dedup = 1.0    # peak Σ slot cells / unique pages

        self._slack_pages = int(slack_pages)
        self._build_step_fns()

    def _build_step_fns(self) -> None:
        """(Re)build the three jitted step closures from the current
        geometry + knobs. Called at construction and again by
        :meth:`retune` when a value a closure captured changes
        (``prefill_chunk`` is baked into the gather-mode chunk slice;
        the geometry behind ``n_slots`` shapes everything) — a retune
        is a closure rebuild at a step boundary, never a process
        restart, and recompiles lazily on first use."""
        geom = self.geom
        cfg = self.cfg
        paged = self.paged
        chunk_w = self.prefill_chunk

        def _draw_rows(logits, keys, draw_pos, temp, top_k, top_p):
            """Fused per-slot sampler: one token per row of ``logits``
            [B, V], drawn with ``fold_in(slot key, absolute position of
            the token being drawn)`` — the SAME stream the offline
            ``generate.sample`` consumes, which is what pins engine
            sampling against the single-request reference. Greedy rows
            (temperature 0) take the bitwise-pinned argmax."""
            base = jax.random.wrap_key_data(keys)
            draw_keys = jax.vmap(jax.random.fold_in)(base, draw_pos)
            return jax.vmap(generate.draw_token)(
                logits, draw_keys, temp, top_k, top_p
            )

        def _accept_and_emit(logits, tokens, start, valid, n_draft,
                             keys, temp, top_k, top_p):
            """Gumbel-coupled rejection sampling over a verify chunk.

            Row j's logits predict position start+j+1; its target token
            is drawn exactly as the sequential sampler at that position
            would draw it. Draft d_j (chunk row j) survives iff it
            EQUALS the target draw from row j-1, acceptance stops at
            the first mismatch, and the mismatching position emits the
            target draw itself — so the emitted stream is bitwise the
            spec-off stream, and in distribution it is exactly the
            target model's (standard rejection-sampling guarantee for a
            deterministic proposer). Returns (targets [B, C], n_emit
            [B], commit mask [B, C] covering rows 0..n_accepted)."""
            b, c = tokens.shape
            positions = (
                start[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
            )
            base = jax.random.wrap_key_data(keys)
            draw_keys = jax.vmap(
                lambda kk, ps: jax.vmap(
                    lambda p: jax.random.fold_in(kk, p)
                )(ps)
            )(base, positions + 1)
            draw = jax.vmap(
                jax.vmap(
                    generate.draw_token, in_axes=(0, 0, None, None, None)
                )
            )
            tgt = draw(logits, draw_keys, temp, top_k, top_p)
            drafts = tokens[:, 1:]
            draft_ok = jnp.arange(c - 1)[None, :] < n_draft[:, None]
            match = (drafts == tgt[:, :-1]) & draft_ok
            n_acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(1)
            commit = (
                jnp.arange(c)[None, :] <= n_acc[:, None]
            ) & valid[:, None]
            return tgt, n_acc + 1, commit

        def _as_committed_rows(rows):
            """What a chunk K/V row [B, C, Hkv, D] reads back as AFTER
            a pool commit — the int8 block codec round-trip (bf16
            pools: identity). Keeps gather-mode verify acceptance math
            independent of commit timing."""
            if geom.mode == "bf16":
                return rows
            lead = rows.shape[:2]
            qv, sc = quant.kv_encode_rows(
                rows.reshape(*lead, geom.row_elems), geom.kv_block
            )
            return quant.kv_decode_rows(qv, sc, rows.dtype).reshape(
                rows.shape
            )

        # buffer donation is a no-op (with a warning) on the CPU backend
        donate = (1,) if jax.default_backend() != "cpu" else ()

        if paged:

            def decode_fn(params, pools, tables, tokens, pos, valid,
                          keys, temp, top_k, top_p, max_pages):
                """One token for every slot, pools → pools: rows commit
                straight to page cells, attention walks the block table
                (no contiguous-cache gather anywhere in the trace)."""
                logits, pools = decoder.decode_step_paged(
                    params, tokens, pools, tables, pos, valid, cfg,
                    max_pages=max_pages,
                )
                tok = _draw_rows(logits, keys, pos + 1, temp, top_k, top_p)
                return tok, pools

            def chunk_fn(params, pools, tables, tokens, start, chunk_len,
                         keys, temp, top_k, top_p, max_pages):
                """One prefill chunk for ONE slot (batch dim kept at 1),
                pools → pools; token 0 of the continuation drawn at the
                last VALID position (only meaningful on the final
                chunk)."""
                logits, pools = decoder.prefill_chunk_paged(
                    params, tokens, pools, tables, start, chunk_len, cfg,
                    max_pages=max_pages,
                )
                last = jnp.take_along_axis(
                    logits, (chunk_len - 1)[:, None, None], axis=1
                )[:, 0]
                tok = _draw_rows(
                    last, keys, start + chunk_len, temp, top_k, top_p
                )
                return tok, pools

            def verify_fn(params, pools, tables, tokens, start, valid,
                          n_draft, keys, temp, top_k, top_p, max_pages):
                """Speculative verify for every decoding slot: chunk =
                [last token, drafts...]; K/V writes are DEFERRED — the
                paged attention folds the in-flight rows as extra keys,
                and only rows 0..n_accepted commit to the pools after
                the acceptance rule runs. Rejected draft rows never
                reach page storage."""
                logits, ck, cv = decoder.verify_chunk_paged(
                    params, tokens, pools, tables, start, cfg,
                    max_pages=max_pages,
                )
                tgt, n_emit, commit = _accept_and_emit(
                    logits, tokens, start, valid, n_draft,
                    keys, temp, top_k, top_p,
                )
                c = tokens.shape[1]
                positions = (
                    start[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
                )

                def wr(_, inp):
                    pools_l, k_l, v_l = inp
                    return None, pallas_paged.write_page_rows(
                        pools_l, tables, positions, commit, k_l, v_l
                    )

                _, pools = jax.lax.scan(wr, None, (pools, ck, cv))
                return tgt, n_emit, pools

        else:

            def decode_fn(params, pools, tables, tokens, pos, valid,
                          keys, temp, top_k, top_p, max_pages):
                """One token for every slot: gather pages → decode_step →
                scatter the new K/V row back (invalid lanes → trash page).
                The parity reference for the paged kernel; the gather is
                sliced to ``max_pages`` held pages."""
                views = kvc.gather(pools, tables, geom, max_pages=max_pages)
                logits, new_cache = decoder.decode_step(
                    params, tokens, views, pos, cfg, prefilled=True
                )
                take = jax.vmap(
                    lambda c, p: jax.lax.dynamic_slice_in_dim(
                        c, p, 1, axis=1
                    )[:, 0],
                    in_axes=(1, 0),
                    out_axes=1,
                )
                rows_k = take(new_cache["k"], pos)[:, :, None]
                rows_v = take(new_cache["v"], pos)[:, :, None]
                pools = kvc.write_rows(
                    pools, tables, pos[:, None], valid[:, None],
                    rows_k, rows_v, geom,
                )
                tok = _draw_rows(logits, keys, pos + 1, temp, top_k, top_p)
                return tok, pools

            def chunk_fn(params, pools, tables, tokens, start, chunk_len,
                         keys, temp, top_k, top_p, max_pages):
                """Gather-mode prefill chunk (see decode_fn above)."""
                views = kvc.gather(pools, tables, geom, max_pages=max_pages)
                logits, new_cache = decoder.prefill_chunk(
                    params, tokens, views, start, cfg
                )
                take = jax.vmap(
                    lambda c, s: jax.lax.dynamic_slice_in_dim(
                        c, s, chunk_w, axis=1
                    ),
                    in_axes=(1, 0),
                    out_axes=1,
                )
                rows_k = take(new_cache["k"], start)
                rows_v = take(new_cache["v"], start)
                positions = (
                    start[:, None] + jnp.arange(chunk_w, dtype=jnp.int32)
                )
                valid = jnp.arange(chunk_w)[None, :] < chunk_len[:, None]
                pools = kvc.write_rows(
                    pools, tables, positions, valid, rows_k, rows_v, geom,
                )
                last = jnp.take_along_axis(
                    logits, (chunk_len - 1)[:, None, None], axis=1
                )[:, 0]
                tok = _draw_rows(
                    last, keys, start + chunk_len, temp, top_k, top_p
                )
                return tok, pools

            def verify_fn(params, pools, tables, tokens, start, valid,
                          n_draft, keys, temp, top_k, top_p, max_pages):
                """Gather-mode verify: no write into the view — each
                chunk row rides as a per-query key (earlier rows
                as-committed through the pool codec, own row raw, the
                sequential loop's exact mix), then only the accepted
                prefix of RAW rows commits back to the pools. Rejected
                draft rows still never reach page storage."""
                c = tokens.shape[1]
                views = kvc.gather(pools, tables, geom, max_pages=max_pages)
                logits, rows_k, rows_v = decoder.verify_chunk(
                    params, tokens, views, start, cfg,
                    as_committed=_as_committed_rows,
                )
                tgt, n_emit, commit = _accept_and_emit(
                    logits, tokens, start, valid, n_draft,
                    keys, temp, top_k, top_p,
                )
                positions = (
                    start[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
                )
                pools = kvc.write_rows(
                    pools, tables, positions, commit, rows_k, rows_v, geom,
                )
                return tgt, n_emit, pools

        self._decode_fn = jax.jit(
            decode_fn, donate_argnums=donate, static_argnums=(10,)
        )
        self._chunk_fn = jax.jit(
            chunk_fn, donate_argnums=donate, static_argnums=(10,)
        )
        self._verify_fn = jax.jit(
            verify_fn, donate_argnums=donate, static_argnums=(11,)
        )

    # ---- queries ---------------------------------------------------------

    @property
    def max_len(self) -> int:
        """Longest prompt+generation one slot can hold."""
        return self.geom.max_len

    def active_slots(self) -> int:
        return sum(s is not None for s in self.slots)

    # ---- live retuning ---------------------------------------------------

    def retune(
        self,
        *,
        spec_k: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        page_bucketing: Optional[bool] = None,
        n_slots: Optional[int] = None,
    ) -> dict:
        """Apply a brain tuning revision (cluster/brain.py TuningPlan
        serving knobs) at a step boundary, without a restart.

        Every knob preserves the bitwise-parity invariants: sampling is
        keyed by ``fold_in(slot key, absolute position)``, so the token
        stream is independent of spec_k (spec-on == spec-off), chunk
        width, page bucketing, and slot count at the same seeds.

        Application classes:

        - ``spec_k`` / ``page_bucketing`` — host-side reads, effective
          on the next step with no rebuild.
        - ``prefill_chunk`` — baked into the gather-mode chunk closure,
          so the step fns are rebuilt. Chunk starts must stay aligned:
          the new width must divide slot capacity AND every in-flight
          prefill's resume point; a misaligned request defers the knob
          (returned under ``"deferred"``) for the caller's next
          boundary rather than corrupting a live slot.
        - ``n_slots`` — sizes the geometry, allocator, pools and block
          tables; applied only when the engine is fully idle (resident
          KV cannot survive a pool reshape). Busy engines defer.

        Returns ``{"applied": {knob: new}, "deferred": {knob: why}}``.
        """
        applied: dict = {}
        deferred: dict = {}
        if spec_k is not None:
            if spec_k < 0:
                raise ValueError(f"spec_k must be >= 0, got {spec_k}")
            if int(spec_k) != self.spec_k:
                self.spec_k = int(spec_k)
                applied["spec_k"] = self.spec_k
        if page_bucketing is not None:
            if bool(page_bucketing) != self.page_bucketing:
                self.page_bucketing = bool(page_bucketing)
                # bucket width changed: the cached device tables were
                # padded to the old bucket
                self._tables_dev = None
                applied["page_bucketing"] = self.page_bucketing
        rebuild = False
        if n_slots is not None and int(n_slots) != self.n_slots:
            n_new = int(n_slots)
            if n_new < 1:
                raise ValueError(f"n_slots must be >= 1, got {n_new}")
            if self.active_slots():
                deferred["n_slots"] = (
                    f"{self.active_slots()} slots hold live KV; pools "
                    "cannot reshape under them"
                )
            else:
                g = self.geom
                self.geom = kvc.make_geometry(
                    self.cfg, n_slots=n_new, max_len=g.max_len,
                    page_size=g.page_size, mode=g.mode,
                    slack_pages=self._slack_pages,
                )
                self.alloc = kvc.PageAllocator(self.geom, n_new)
                self.pools = kvc.init_pools(self.geom)
                self.slots = [None] * n_new
                self.n_slots = n_new
                self._tables_dev = None
                if self.prefix_sharing:
                    # shared pages died with the old pools
                    self.trie = prefix_mod.PrefixIndex(g.page_size)
                    self.alloc.on_free = self.trie.drop_pages
                rebuild = True
                applied["n_slots"] = n_new
        if prefill_chunk is not None and int(prefill_chunk) != self.prefill_chunk:
            pc = int(prefill_chunk)
            if pc < 1:
                raise ValueError(f"prefill_chunk must be >= 1, got {pc}")
            if self.geom.max_len % pc:
                raise ValueError(
                    f"slot capacity {self.geom.max_len} must be a "
                    f"multiple of prefill_chunk={pc} (chunk starts are "
                    "chunk-aligned; dynamic_slice clamps out-of-bounds "
                    "starts)"
                )
            misaligned = [
                i for i, s in enumerate(self.slots)
                if s is not None and s.phase == "prefill"
                and s.n_prefilled % pc
            ]
            if misaligned:
                deferred["prefill_chunk"] = (
                    f"slots {misaligned} mid-prefill at non-multiples "
                    f"of {pc}"
                )
            else:
                self.prefill_chunk = pc
                rebuild = True
                applied["prefill_chunk"] = pc
        if rebuild:
            self._build_step_fns()
        return {"applied": applied, "deferred": deferred}

    def stats(self) -> dict:
        dt = time.monotonic() - self._t0 if self._t0 else 0.0
        return {
            "active_slots": self.active_slots(),
            "free_pages": self.alloc.free_pages,
            "tokens_generated": self._tokens,
            "tokens_per_s": self._tokens / dt if dt > 0 else 0.0,
            "decode_kernel": "paged" if self.paged else "gather",
            "table_ships": self._table_ships,
            "step_time_s": self._step_time,
            "host_time_s": max(0.0, dt - self._step_time),
            "spec_k": self.spec_k,
            "draft_tokens": self._draft_tokens,
            "accepted_tokens": self._accepted_tokens,
            "spec_accept_rate": (
                self._accepted_tokens / self._draft_tokens
                if self._draft_tokens else 0.0
            ),
            # migration accounting: the drill's zero-re-prefill assertion
            # reads prefill_tokens before/after a failover
            "prefill_tokens": self._prefill_tokens,
            "prefill_chunks": self._prefill_chunks,
            "migrated_in": self._migrated_in,
            "migrated_out": self._migrated_out,
            # disaggregation: replica role and handoff accounting
            # (serving/disagg.py mutates the byte counter from its pump
            # thread — telemetry-grade, not a synchronization point)
            "role": self.role,
            "handoffs_in": self._handoffs_in,
            "handoffs_out": self._handoffs_out,
            "handoff_bytes": self._handoff_bytes,
            "affinity_bounced": self._affinity_bounced,
            # prefix sharing: hit rate over sharing-on admissions, prompt
            # tokens whose prefill was skipped, COW duplications, live
            # trie size, and the dedup ratio (slot cells per unique
            # physical page — 1.0 means nothing is shared)
            "prefix_hit_rate": (
                self._prefix_hits / (self._prefix_hits + self._prefix_misses)
                if (self._prefix_hits + self._prefix_misses) else 0.0
            ),
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "prefill_tokens_saved": self._prefill_tokens_saved,
            "cow_pages": self._cow_pages,
            "trie_pages": (
                self.trie.n_pages if self.trie is not None else 0
            ),
            "dedup_ratio": self.dedup_ratio(),
            "peak_dedup_ratio": self._peak_dedup,
        }

    def dedup_ratio(self) -> float:
        """Σ slot cells / unique assigned pages — how many logical pages
        each resident physical page serves (resident-bytes dedup)."""
        unique = self.alloc.unique_assigned_pages
        if not unique:
            return 1.0
        cells = sum(self.alloc.slot_pages(i) for i in range(self.n_slots))
        return cells / unique

    def resident_kv_bytes(self) -> int:
        return kvc.resident_bytes(self.geom)

    def observability_snapshot(self) -> dict:
        """The state the serving watchdog freezes into a capture
        artifact when an SLO anomaly fires: the engine's wall-time
        phase split, the scheduler's depth + drop counters, and the
        PageAllocator's occupancy — enough to tell 'engine got slow'
        from 'queue backed up' from 'out of pages'."""
        es = self.stats()
        return {
            "phase_split": {
                "step_time_s": round(es["step_time_s"], 4),
                "host_time_s": round(es["host_time_s"], 4),
                "table_ships": es["table_ships"],
            },
            "scheduler": {
                "queue_depth": self.scheduler.queue_depth(),
                "admitted": self.scheduler.admitted,
                "completed": self.scheduler.completed,
                "shed": self.scheduler.shed,
                "rejected": self.scheduler.rejected,
                "timed_out": self.scheduler.timed_out,
                "poisoned": self.scheduler.poisoned,
            },
            "allocator": {
                "free_pages": self.alloc.free_pages,
                "reserved_pages": self.alloc.reserved_pages,
                "n_pages": self.geom.n_pages,
                "pages_per_slot": [
                    self.alloc.slot_pages(i) for i in range(self.n_slots)
                ],
            },
            "active_slots": es["active_slots"],
            "tokens_per_s": round(es["tokens_per_s"], 2),
            "spec_accept_rate": round(es["spec_accept_rate"], 4),
            # trie stats ride along so a watchdog capture can tell
            # "out of pages" from "dedup regressed" (hot prefixes
            # falling out of the index under churn)
            # disaggregation: which role this replica plays and how many
            # requests are parked mid-handoff (phase "handoff" = prefill
            # finished, pages still streaming to the decode replica)
            "handoff": {
                "role": self.role,
                "handoffs_in": es["handoffs_in"],
                "handoffs_out": es["handoffs_out"],
                "handoff_bytes": es["handoff_bytes"],
                "pending": sum(
                    1 for s in self.slots
                    if s is not None and s.phase == "handoff"
                ),
                "affinity_bounced": es["affinity_bounced"],
            },
            "prefix": {
                "sharing": self.prefix_sharing,
                "hit_rate": round(es["prefix_hit_rate"], 4),
                "trie_pages": es["trie_pages"],
                "trie": (
                    self.trie.stats() if self.trie is not None else {}
                ),
                "dedup_ratio": round(es["dedup_ratio"], 4),
                "prefill_tokens_saved": es["prefill_tokens_saved"],
                "cow_pages": es["cow_pages"],
            },
        }

    # ---- device-side inputs ----------------------------------------------

    def _device_tables(self):
        """The block tables as a device array, re-shipped only when the
        allocator mutated since the last ship (the dirty flag) — a
        steady-state decode step reuses the cached copy instead of
        paying a host→device transfer per step."""
        if self.alloc.consume_dirty() or self._tables_dev is None:
            self._tables_dev = jnp.asarray(self.alloc.block_tables())
            self._table_ships += 1
        return self._tables_dev

    def _pages_bucket(self) -> int:
        """STATIC page-walk width for the jitted steps: the next power
        of two ≥ the max pages any slot holds, floored at 4 so tiny
        geometries don't churn compiles. Bounds the attention walk (and
        the gather reference's width) by what is actually resident
        while keeping recompiles to a handful over a slot's lifetime."""
        held = max(
            (self.alloc.slot_pages(i) for i in range(self.n_slots)),
            default=1,
        )
        b = 4
        while b < held:
            b *= 2
        if not self.page_bucketing:  # ablation: legacy full-pool width
            return self.geom.max_pages_per_slot
        return min(b, self.geom.max_pages_per_slot)

    # ---- the step loop ---------------------------------------------------

    def step(self) -> bool:
        """One engine iteration; returns False when fully idle (the
        server thread uses that to sleep instead of spinning)."""
        worked = self._finish_and_evict()
        worked = self._admit() or worked
        if self._t0 is None and any(self.slots):
            self._t0 = time.monotonic()
        if self.role == "prefill":
            # prefill-only replica: EVERY prefill slot advances one chunk
            # per step (large effective chunk) and the decode/spec batch
            # is never traced — finished prompts leave via handoff_sink
            return self._prefill_all() or worked
        worked = self._prefill_one() or worked
        if self.spec_k:
            worked = self._spec_batch() or worked
        else:
            worked = self._decode_batch() or worked
        return worked

    def drain(self, timeout: float = 120.0) -> None:
        """Step until queue and slots are empty (tests / bench)."""
        deadline = time.monotonic() + timeout
        while self.scheduler.queue_depth() or self.active_slots():
            self.step()
            if time.monotonic() > deadline:
                raise TimeoutError("engine did not drain in time")

    @staticmethod
    def _slot_done(s: _Slot) -> bool:
        req = s.req
        return len(s.generated) >= req.max_new_tokens or (
            req.eos_id is not None
            and bool(s.generated)
            and s.generated[-1] == req.eos_id
        )

    def _finish_and_evict(self) -> bool:
        worked = False
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "decode":
                continue
            if not self._slot_done(s):
                continue
            req = s.req
            if s.span is not None:
                s.span.end(tokens=len(s.generated), reason="completed")
                s.span = None
            self.scheduler.complete(
                req, [int(t) for t in s.prompt] + s.generated
            )
            self.alloc.evict(i)
            self.slots[i] = None
            worked = True
        return worked

    def _prefix_plan(self, req) -> Optional["prefix_mod.AdmissionPlan"]:
        """The admission recipe for ``req`` under prefix sharing: which
        committed pages its prompt can map, where prefill resumes. None
        when sharing is off or the trie has no usable match."""
        if self.trie is None:
            return None
        match = self.trie.lookup(req.prompt)
        if not match.pages and not match.tail_tokens:
            return None
        return prefix_mod.plan_admission(
            match, len(req.prompt), self.geom.page_size, self.prefill_chunk
        )

    def _footprint_tokens(self, req) -> int:
        """Tokens of page footprint an admission reserves. A
        prefill-role engine holds PROMPT-ONLY pages — generated tokens'
        K/V rows are written on the decode replica, so reserving them
        here would halve the prefill pool's concurrency for nothing.
        (The sampled first token is drawn from logits, never written.)"""
        if self.role == "prefill":
            return len(req.prompt)
        return req.total_tokens

    def _admit(self) -> bool:
        worked = False
        if self.draining:
            return worked
        while True:
            try:
                idx = self.slots.index(None)
            except ValueError:
                return worked

            def can(req):
                # oversize requests pass so they can be popped and FAILED
                # (they would block the head of the line forever)
                if req.total_tokens > self.geom.max_len:
                    return True
                # hit-aware footprint: read-only shared prefix pages are
                # mapped, not drawn from the free list — a hot-prefix
                # request can fit where a cold one of the same length
                # cannot (COW pages are fresh and get no discount)
                plan = self._prefix_plan(req)
                if self.role == "decode" and not prefix_mod.affinity_ok(
                    plan, len(req.prompt), self.affinity_suffix_max
                ):
                    return True  # popped to BOUNCE — takes no pages
                n_shared = len(plan.shared) if plan else 0
                return self.alloc.can_admit(
                    self._footprint_tokens(req), n_shared
                )

            req = self.scheduler.pop_next(
                can, lookahead=self.admission_lookahead
            )
            if req is None:
                return worked
            if req.total_tokens > self.geom.max_len:
                self.scheduler.count_rejected()
                self.scheduler.fail(req, AdmissionError(
                    f"request {req.rid} needs {req.total_tokens} tokens "
                    f"> slot capacity {self.geom.max_len}"
                ))
                continue
            # validate sampling params HERE so a poisoned request fails
            # its own future instead of raising in the step-loop thread
            try:
                req.sampling.validate()
                key_data = np.asarray(
                    jax.random.key_data(
                        jax.random.key(int(req.sampling.seed))
                    )
                )
            except Exception as exc:  # noqa: BLE001 — poisoned objects
                err = exc if isinstance(exc, AdmissionError) else (
                    AdmissionError(
                        f"request {req.rid} has invalid sampling "
                        f"params: {exc}"
                    )
                )
                self.scheduler.count_poisoned()
                self.scheduler.fail(req, err)
                continue
            # reserve the FULL prompt+generation footprint up front so a
            # decoding slot can never deadlock waiting for pages (a
            # prefill-role engine reserves prompt-only: the generation
            # pages live on the decode replica); on a prefix hit the
            # matched prefix maps existing pages instead of drawing
            # fresh ones, and prefill resumes at the plan's
            # chunk-aligned resume point
            plan = self._prefix_plan(req)
            if self.role == "decode" and not prefix_mod.affinity_ok(
                plan, len(req.prompt), self.affinity_suffix_max
            ):
                # the plan the router saw degraded (donor pages churned
                # out of the trie): bounce for re-dispatch through the
                # prefill pool rather than chunk-prefilling a cold
                # prompt here
                self._affinity_bounced += 1
                self.bounced.append(req)
                worked = True
                continue
            resume = 0
            if plan is not None:
                self.alloc.admit_shared(
                    idx, self._footprint_tokens(req), plan.prefix_pages
                )
                for logical, _src in plan.cow:
                    pair = self.alloc.cow_page(idx, logical)
                    if pair is not None:
                        self._copy_page(*pair)
                        self._cow_pages += 1
                resume = plan.resume
                self._prefix_hits += 1
                self._prefill_tokens_saved += resume
            else:
                self.alloc.admit(idx, self._footprint_tokens(req))
                if self.prefix_sharing:
                    self._prefix_misses += 1
            self._peak_dedup = max(self._peak_dedup, self.dedup_ratio())
            self.slots[idx] = _Slot(
                req=req, phase="prefill",
                prompt=np.asarray(req.prompt, np.int32),
                key_data=key_data,
                n_prefilled=resume,
                interned_pages=len(plan.shared) if plan else 0,
            )
            self.scheduler.record_admitted(req)
            tr = get_tracer()
            if tr.enabled:
                tr.instant(
                    "serving.admit", rid=req.rid,
                    replica=self.scheduler.replica, slot=idx,
                    re_admits=req.re_admits, prefix_resume=resume,
                )
            worked = True

    # ---- prefix sharing helpers ------------------------------------------

    def _copy_page(self, src: int, dst: int) -> None:
        """Copy one physical page's payload across every pool array —
        the device half of a COW duplication (all layers, one page)."""
        for k, v in self.pools.items():
            self.pools[k] = v.at[:, dst].set(v[:, src])

    def _intern_full_pages(self, i: int, s: _Slot) -> None:
        """Index the slot's newly COMMITTED full prompt pages. Only
        pages that are pure prompt — ``(j+1)*page_size <= len(prompt)``
        — and fully prefilled are eligible: a page carrying generated
        tokens (or an uncommitted tail) is not a reusable prefix."""
        if self.trie is None:
            return
        ps = self.geom.page_size
        full = min(int(s.n_prefilled), len(s.prompt)) // ps
        if full <= s.interned_pages:
            return
        row = self.alloc.block_tables()[i]
        self.trie.intern(s.prompt, full, row)
        s.interned_pages = full

    # ---- live KV-page migration (serving/migration.py) -------------------

    def export_pages(
        self, i: int, start: int = 0, stop: Optional[int] = None
    ) -> Dict[str, np.ndarray]:
        """Host copies of the physical pages slot ``i`` holds, in
        LOGICAL order — the donor half of a live migration. Pages ship
        exactly as stored (int8 payloads + per-block f32 scales, or
        bf16 rows), so the survivor's continuation attends to
        bitwise-identical cache state. ``start``/``stop`` slice the
        logical page range (a streaming handoff ships only the pages
        the last chunk committed). Read-only: the slot keeps its pages
        until :meth:`release_slot`, so a torn transfer can
        re-snapshot."""
        n = self.alloc.slot_pages(i)
        if stop is None:
            stop = n
        if not 0 <= start <= stop <= n:
            raise ValueError(
                f"page range [{start}, {stop}) outside the {n} pages "
                f"slot {i} holds"
            )
        phys = [int(p) for p in self.alloc.block_tables()[i, start:stop]]
        return {k: np.asarray(v[:, phys]) for k, v in self.pools.items()}

    def stage_pages(
        self, tag: str, page_start: int, pages: Dict[str, np.ndarray]
    ) -> None:
        """Scatter streamed handoff payloads into the physical pages of
        migration reservation ``tag`` BEFORE it commits — the decode
        side of a streaming handoff warms its reservation fragment by
        fragment, so ``import_slot(..., pages=None)`` at the end only
        rebuilds host state. Reserved pages are off the free list and
        in no block table, so no jitted step can read them; writes are
        idempotent per logical range (a restarted stream re-stages the
        same payloads into the same cells). Call under
        ``server.paused()`` — pool arrays are swapped."""
        phys = self.alloc.reservation(tag)
        if not phys:
            raise KeyError(f"no migration reservation {tag!r}")
        if set(pages) != set(self.pools):
            raise ValueError(
                f"staged pages carry pools {sorted(pages)}; this engine "
                f"stores {sorted(self.pools)} (mode={self.geom.mode})"
            )
        n = next(iter(pages.values())).shape[1]
        if n == 0:
            return
        if page_start + n > len(phys):
            raise ValueError(
                f"fragment pages [{page_start}, {page_start + n}) exceed "
                f"the {len(phys)}-page reservation {tag!r}"
            )
        tgt = jnp.asarray(phys[page_start:page_start + n], jnp.int32)
        for k, v in self.pools.items():
            self.pools[k] = v.at[:, tgt].set(jnp.asarray(pages[k], v.dtype))

    def note_handoff_bytes(self, n: int) -> None:
        """Account wire bytes a handoff shipped from/into this engine
        (the coordinator encodes off-thread, so the engine cannot see
        the blob sizes itself)."""
        self._handoff_bytes += int(n)

    def release_slot(self, i: int, *, reason: str = "migrated_out") -> None:
        """Drop a slot whose request moved out: free its pages without
        resolving the request's future (whoever owns the request now
        finishes it). ``reason`` is ``"migrated_out"`` (failover
        migration), ``"handoff_out"`` (committed prefill→decode
        handoff) or ``"handoff_abort"`` (degraded handoff — the request
        re-prefills elsewhere, so neither success counter moves)."""
        s = self.slots[i]
        if s is None:
            return
        if s.span is not None:
            s.span.end(tokens=len(s.generated), reason=reason)
            s.span = None
        self.alloc.evict(i)
        self.slots[i] = None
        if reason == "handoff_out":
            self._handoffs_out += 1
        elif reason == "migrated_out":
            self._migrated_out += 1

    def import_slot(
        self,
        req: Request,
        pages: Optional[Dict[str, np.ndarray]],
        *,
        phase: str,
        n_prefilled: int,
        generated: Sequence[int],
        reserved_tag: Optional[str] = None,
        handoff: bool = False,
    ) -> int:
        """Adopt a migrated (or handed-off) request mid-stream into a
        free slot.

        Commits the pages reserved under ``reserved_tag`` (or admits a
        fresh footprint when None), scatters the donated page payloads
        verbatim into those physical pages, and rebuilds the lane
        exactly where the donor stopped — same absolute positions, same
        generated prefix, sampling key re-derived from the request's
        seed. Because every sampling draw folds in the absolute buffer
        position, the continuation emits the never-evicted stream.

        ``pages=None`` (requires ``reserved_tag``) commits a reservation
        whose payloads were already streamed in via :meth:`stage_pages`
        — the final fragment of a streaming handoff only flips host
        state, no device scatter.

        Raises ``AdmissionError`` (with a retry-after hint) when no lane
        is free, and ``ValueError`` on a footprint/geometry mismatch —
        both leave the caller on the re-prefill fallback ladder.
        """
        if pages is None and reserved_tag is None:
            raise ValueError(
                "import_slot(pages=None) needs a reserved_tag whose pages "
                "were staged via stage_pages"
            )
        try:
            idx = self.slots.index(None)
        except ValueError:
            raise AdmissionError(
                f"no free slot for migrated request {req.rid}",
                retry_after_s=self.scheduler.retry_after_hint(),
            ) from None
        if pages is not None and set(pages) != set(self.pools):
            raise ValueError(
                f"migrated pages carry pools {sorted(pages)}; this engine "
                f"stores {sorted(self.pools)} (mode={self.geom.mode})"
            )
        if reserved_tag is not None:
            phys = self.alloc.commit_migration(reserved_tag, idx)
        else:
            if not self.alloc.can_admit(req.total_tokens):
                raise AdmissionError(
                    f"no pages for migrated request {req.rid}",
                    retry_after_s=self.scheduler.retry_after_hint(),
                )
            self.alloc.admit(idx, req.total_tokens)
            n = self.alloc.slot_pages(idx)
            phys = [int(p) for p in self.alloc.block_tables()[idx, :n]]
        if pages is not None:
            n_held = next(iter(pages.values())).shape[1]
            if n_held > len(phys):
                self.alloc.evict(idx)
                raise ValueError(
                    f"migrated request {req.rid} holds {n_held} pages but "
                    f"the reservation covers {len(phys)} — geometry mismatch"
                )
            tgt = jnp.asarray(phys[:n_held], jnp.int32)
            for k, v in self.pools.items():
                self.pools[k] = v.at[:, tgt].set(jnp.asarray(pages[k], v.dtype))
        key_data = np.asarray(
            jax.random.key_data(jax.random.key(int(req.sampling.seed)))
        )
        slot = _Slot(
            req=req,
            phase=phase,
            prompt=np.asarray(req.prompt, np.int32),
            key_data=key_data,
            n_prefilled=int(n_prefilled),
            generated=[int(t) for t in generated],
        )
        tr = get_tracer()
        if tr.enabled and phase == "decode":
            slot.span = tr.begin(
                "serving.decode", rid=req.rid,
                replica=self.scheduler.replica, slot=idx, resumed=True,
            )
        self.slots[idx] = slot
        # re-intern the imported prompt pages: the survivor's trie has
        # never seen them (sharing structure does not travel the wire —
        # the donor ships private payload copies), so future hot-prefix
        # requests on this replica can share them
        self._intern_full_pages(idx, slot)
        if self._t0 is None:
            self._t0 = time.monotonic()
        if handoff:
            self._handoffs_in += 1
        else:
            self._migrated_in += 1
        return idx

    def _sampling_arrays(self, lanes):
        """Per-lane sampling inputs for the jitted steps: threefry key
        data, temperature, top_k, top_p. Idle lanes carry defaults
        (greedy, zero key) so their — masked — draws are well-defined."""
        n = len(lanes)
        keys = np.zeros((n, 2), np.uint32)
        temp = np.zeros(n, np.float32)
        top_k = np.zeros(n, np.int32)
        top_p = np.ones(n, np.float32)
        for j, i in enumerate(lanes):
            s = self.slots[i]
            if s is None:
                continue
            keys[j] = s.key_data
            sp = s.req.sampling
            temp[j] = sp.temperature
            top_k[j] = sp.top_k
            top_p[j] = sp.top_p
        return (
            jnp.asarray(keys), jnp.asarray(temp),
            jnp.asarray(top_k), jnp.asarray(top_p),
        )

    def _prefill_one(self) -> bool:
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "prefill":
                continue
            self._prefill_slot(i, s)
            return True
        return False

    def _prefill_all(self) -> bool:
        """Prefill-role stepping: every prefill slot advances one chunk
        this step — with no decode batch to interleave with, there is
        nothing to yield to."""
        todo = [
            (i, s) for i, s in enumerate(self.slots)
            if s is not None and s.phase == "prefill"
        ]
        for i, s in todo:
            self._prefill_slot(i, s)
        return bool(todo)

    def _prefill_slot(self, i: int, s: _Slot) -> None:
        """Advance one slot by one prefill chunk (all roles share this
        body; the roles differ only in where a finished prompt goes)."""
        p = len(s.prompt)
        clen = min(self.prefill_chunk, p - s.n_prefilled)
        chunk = np.zeros(self.prefill_chunk, np.int32)
        chunk[:clen] = s.prompt[s.n_prefilled:s.n_prefilled + clen]
        tables = self._device_tables()[i:i + 1]
        tr = get_tracer()
        sp = None
        if tr.enabled:
            sp = tr.begin(
                "serving.prefill_chunk", rid=s.req.rid,
                replica=self.scheduler.replica, slot=i,
                start=s.n_prefilled, tokens=clen,
            )
        t0 = time.monotonic()
        tok0, self.pools = self._chunk_fn(
            self.params, self.pools, tables,
            jnp.asarray(chunk[None]),
            jnp.asarray([s.n_prefilled], jnp.int32),
            jnp.asarray([clen], jnp.int32),
            *self._sampling_arrays([i]),
            self._pages_bucket(),
        )
        tok0 = np.asarray(tok0)
        self._step_time += time.monotonic() - t0
        if sp is not None:
            sp.end()
        s.n_prefilled += clen
        self._prefill_tokens += clen
        self._prefill_chunks += 1
        self._intern_full_pages(i, s)
        if s.n_prefilled < p:
            if self.role == "prefill" and self.handoff_sink is not None:
                # streaming handoff: the chunk just committed may have
                # filled whole pages — ship them now, overlapped with
                # the next chunk's compute
                self.handoff_sink(i, s, "chunk")
            return
        s.generated = [int(tok0[0])]
        self.scheduler.record_first_token(s.req)
        self._tokens += 1
        if self.role == "prefill":
            if self._slot_done(s):
                # finished at its first token (max_new=1, or EOS drawn):
                # nothing to decode downstream — complete locally and
                # cancel any fragments already streamed
                self.scheduler.complete(
                    s.req, [int(t) for t in s.prompt] + s.generated
                )
                self.alloc.evict(i)
                self.slots[i] = None
                if self.handoff_sink is not None:
                    self.handoff_sink(i, s, "local_done")
                return
            if self.handoff_sink is None:
                raise RuntimeError(
                    f"prefill-role engine finished {s.req.rid} with no "
                    "handoff sink attached — wire a HandoffCoordinator "
                    "(serving/disagg.py) or run role='unified'"
                )
            # park until the decode replica commits; the coordinator
            # releases the slot (release_slot) after the handoff lands
            s.phase = "handoff"
            self.handoff_sink(i, s, "done")
            return
        s.phase = "decode"
        if tr.enabled:
            # the long occupancy span: first token → finish or
            # migrate-out; the survivor re-opens it resumed=True
            s.span = tr.begin(
                "serving.decode", rid=s.req.rid,
                replica=self.scheduler.replica, slot=i,
            )

    def _decode_batch(self) -> bool:
        # a slot can complete within the step that finishes its prefill
        # (max_new=1, or EOS on the prefill token): it must not decode
        # an extra token before the next _finish_and_evict sees it
        live = [
            i for i, s in enumerate(self.slots)
            if s is not None and s.phase == "decode"
            and not self._slot_done(s)
        ]
        if not live:
            return False
        tokens = np.zeros(self.n_slots, np.int32)
        pos = np.zeros(self.n_slots, np.int32)
        valid = np.zeros(self.n_slots, bool)
        for i in live:
            s = self.slots[i]
            tokens[i] = s.generated[-1]
            pos[i] = len(s.prompt) + len(s.generated) - 1
            valid[i] = True
        t0 = time.monotonic()
        tok, self.pools = self._decode_fn(
            self.params, self.pools, self._device_tables(),
            jnp.asarray(tokens), jnp.asarray(pos), jnp.asarray(valid),
            *self._sampling_arrays(range(self.n_slots)),
            self._pages_bucket(),
        )
        tok = np.asarray(tok)
        self._step_time += time.monotonic() - t0
        for i in live:
            self.slots[i].generated.append(int(tok[i]))
            self._tokens += 1
        return True

    def _spec_batch(self) -> bool:
        """Speculative variant of ``_decode_batch``: every decoding slot
        contributes a verify chunk ``[last token, drafts..., pad]`` and
        the jitted verify step commits 1..spec_k+1 tokens per slot.
        Falls back to plain decode on steps where NO slot has a draft
        (the verify chunk would just be a wider decode)."""
        live = [
            i for i, s in enumerate(self.slots)
            if s is not None and s.phase == "decode"
            and not self._slot_done(s)
        ]
        if not live:
            return False
        c = self.spec_k + 1
        tokens = np.zeros((self.n_slots, c), np.int32)
        start = np.zeros(self.n_slots, np.int32)
        valid = np.zeros(self.n_slots, bool)
        n_draft = np.zeros(self.n_slots, np.int32)
        for i in live:
            s = self.slots[i]
            # never draft past the request's budget: the LAST emitted
            # token must be the one that hits max_new_tokens, so drafts
            # beyond remaining-1 could commit K/V rows the allocator
            # never reserved. k_eff keeps every commit inside the
            # admission footprint.
            remaining = s.req.max_new_tokens - len(s.generated)
            k_eff = max(0, min(self.spec_k, remaining - 1))
            drafts = list(
                self.draft.propose(
                    list(s.prompt) + s.generated, k_eff
                )
            )[:k_eff]
            tokens[i, 0] = s.generated[-1]
            tokens[i, 1:1 + len(drafts)] = drafts
            start[i] = len(s.prompt) + len(s.generated) - 1
            valid[i] = True
            n_draft[i] = len(drafts)
        if not n_draft.any():
            return self._decode_batch()
        tr = get_tracer()
        sp = None
        if tr.enabled:
            sp = tr.begin(
                "serving.spec_verify", replica=self.scheduler.replica,
                n_live=len(live), drafts=int(n_draft.sum()),
                rids=",".join(self.slots[i].req.rid for i in live),
            )
        t0 = time.monotonic()
        tgt, n_emit, self.pools = self._verify_fn(
            self.params, self.pools, self._device_tables(),
            jnp.asarray(tokens), jnp.asarray(start), jnp.asarray(valid),
            jnp.asarray(n_draft),
            *self._sampling_arrays(range(self.n_slots)),
            self._pages_bucket(),
        )
        tgt = np.asarray(tgt)
        n_emit = np.asarray(n_emit)
        self._step_time += time.monotonic() - t0
        if sp is not None:
            sp.end(emitted=int(n_emit.sum()))
        for i in live:
            s = self.slots[i]
            n = int(n_emit[i])
            self._draft_tokens += int(n_draft[i])
            self._accepted_tokens += n - 1
            for j in range(n):
                s.generated.append(int(tgt[i, j]))
                self._tokens += 1
                if len(s.generated) >= s.req.max_new_tokens or (
                    s.req.eos_id is not None
                    and s.generated[-1] == s.req.eos_id
                ):
                    break
        return True
