"""Threaded generation server: one engine, one scheduler, one loop.

``GenerationServer`` owns a ``Scheduler`` (request intake, latency
accounting) and a ``ServingEngine`` (continuous batching over the paged
KV cache) and drives the engine from a background thread. User threads
call ``submit`` (non-blocking, returns a ``concurrent.futures.Future``)
or ``generate`` (blocking convenience); the engine loop sleeps briefly
when fully idle instead of spinning.

``kill`` stops the loop abruptly WITHOUT resolving in-flight futures —
that is the eviction drill: a replica dying mid-stream leaves its
requests dangling until ``ReplicaRouter.poll`` migrates their live KV
pages to a survivor, or re-admits them when migration is unavailable
(serving/replica.py, serving/migration.py).

``paused()`` is the migration-side concurrency contract: the engine's
pools/allocator/slots are only ever mutated on the loop thread, so a
migrator that needs to reserve pages or import a slot parks the loop at
a step boundary first and gets exclusive access for the duration.
"""

import contextlib
import threading
import time

from dlrover_tpu.observability.tracing import get_tracer
from dlrover_tpu.serving.engine import ServingEngine
from dlrover_tpu.serving.scheduler import (
    AdmissionError, Request, SamplingParams, Scheduler,
)


class GenerationServer:
    """Single-replica serving front end (threaded loop around the engine)."""

    def __init__(
        self,
        params,
        cfg,
        *,
        hub=None,
        replica: str = "replica-0",
        max_queue: int = 256,
        publish_every: float = 0.5,
        idle_sleep: float = 0.002,
        step_period_s: float = 0.0,
        watchdog=None,
        **engine_kw,
    ):
        self.replica = replica
        self.scheduler = Scheduler(
            max_queue=max_queue, hub=hub, replica=replica
        )
        self.engine = self._build_engine(
            params, cfg, self.scheduler, **engine_kw
        )
        # optional SLO watchdog (observability/watchdog.ServingWatchdog):
        # observed per published record; its capture snapshot defaults
        # to this engine's frozen observability state
        self.watchdog = watchdog
        if watchdog is not None and watchdog.snapshot_fn is None:
            watchdog.snapshot_fn = self.engine.observability_snapshot
        self.publish_every = publish_every
        self.idle_sleep = idle_sleep
        # minimum wall time per WORKED step (0 = run free). Benches and
        # drills that model a multi-host fleet on one machine set this
        # to pace each replica like a fixed-rate accelerator host —
        # otherwise co-located engine loops share the same cores and
        # adding a "replica" adds no capacity, inverting every
        # scale-out comparison the fleet tier wants to make.
        self.step_period_s = step_period_s
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._pause_lock = threading.Lock()   # serializes paused() users
        self._pause_req = threading.Event()   # ask the loop to park
        self._pause_ack = threading.Event()   # loop parked at a boundary

    def _build_engine(self, params, cfg, scheduler, **engine_kw):
        """Engine factory hook: subclasses (serving/sparse_engine.py's
        recommendation server) swap the engine while inheriting the
        loop, pause protocol, and drain semantics unchanged."""
        return ServingEngine(params, cfg, scheduler, **engine_kw)

    # ---- lifecycle -------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "GenerationServer":
        if self.alive:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"serving-{self.replica}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful: finish nothing extra, just stop the loop and join."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def kill(self) -> None:
        """Abrupt stop simulating a host eviction: the loop halts at the
        next step boundary and in-flight futures stay UNRESOLVED — the
        router's failover path picks them up."""
        self.stop()

    @contextlib.contextmanager
    def paused(self, timeout: float = 30.0):
        """Exclusive engine access at a step boundary.

        Parks the loop thread (it acknowledges between steps), yields,
        then resumes it. When the loop is dead (killed replica — the
        migration donor case) this is a pass-through: the caller already
        has exclusive access. Ack timeout falls through rather than
        deadlocking a migration on a wedged loop."""
        with self._pause_lock:
            if not self.alive:
                yield self.engine
                return
            self._pause_ack.clear()
            self._pause_req.set()
            self._pause_ack.wait(timeout)
            try:
                yield self.engine
            finally:
                self._pause_req.clear()

    def begin_drain(self) -> None:
        """Planned drain: stop admitting queued work so in-flight slots
        finish or migrate out; the queue itself is re-routed by the
        caller (ReplicaRouter / migrator)."""
        self.engine.draining = True

    def _loop(self) -> None:
        last_pub = time.monotonic()
        while not self._stop_evt.is_set():
            if self._pause_req.is_set():
                # re-ack every tick: a second paused() user can clear
                # the ack and re-raise the request before this thread
                # observes the gap between them — still parked at the
                # same step boundary, so acking again is always valid
                while self._pause_req.is_set() and not self._stop_evt.is_set():
                    self._pause_ack.set()
                    time.sleep(0.001)
                continue
            t_step = time.monotonic()
            worked = self.engine.step()
            if worked and self.step_period_s > 0.0:
                rem = self.step_period_s - (time.monotonic() - t_step)
                if rem > 0:
                    self._stop_evt.wait(rem)
            now = time.monotonic()
            if now - last_pub >= self.publish_every:
                self._publish()
                last_pub = now
            if not worked:
                self._stop_evt.wait(self.idle_sleep)
        # final snapshot so short-lived servers still leave telemetry
        self._publish()

    def _publish(self) -> None:
        stats = self.engine.stats()
        rec = self.scheduler.publish(stats)
        if self.watchdog is not None:
            self.watchdog.observe(rec)
        tr = get_tracer()
        if tr.enabled:
            tr.counter(
                f"serving.occupancy.{self.replica}",
                active_slots=stats["active_slots"],
                queue_depth=rec.queue_depth,
                free_pages=stats["free_pages"],
            )

    # ---- intake ----------------------------------------------------------

    def submit(
        self, prompt, max_new_tokens: int, eos_id=None, priority: int = 0,
        sampling: SamplingParams | None = None,
        deadline_s: float | None = None,
    ) -> Request:
        if len(prompt) + max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({max_new_tokens}) "
                f"exceeds slot capacity {self.engine.max_len}"
            )
        return self.scheduler.submit(
            prompt, max_new_tokens, eos_id=eos_id, priority=priority,
            sampling=sampling, deadline_s=deadline_s,
        )

    @property
    def role(self) -> str:
        """This replica's pool in a disaggregated fleet:
        ``"prefill"`` | ``"decode"`` | ``"unified"``."""
        return self.engine.role

    def re_admit(self, req: Request) -> None:
        """Re-prefill failover intake — the migration ladder's fallback
        tier: requeue another replica's in-flight request under its
        original admission ticket; generation restarts from the prompt.
        ``req.sampling`` rides along, and position-indexed draws make
        the re-prefilled continuation identical to the original.

        Refused on a decode-role replica: a raw re-admission means a
        full chunked prefill on the decode critical path — exactly the
        interference the prefill/decode split removes. Role-aware
        callers (ReplicaRouter's migrator override) route the ticket
        through the prefill pool instead."""
        if self.engine.role == "decode":
            raise AdmissionError(
                f"decode-role replica {self.replica} cannot re-prefill "
                f"{req.rid} — route it through the prefill pool"
            )
        self.scheduler.re_admit(req)

    def generate(
        self, prompt, max_new_tokens: int, eos_id=None,
        timeout: float = 120.0, sampling: SamplingParams | None = None,
    ):
        """Blocking convenience: submit and wait for the full sequence."""
        return self.submit(
            prompt, max_new_tokens, eos_id=eos_id, sampling=sampling
        ).future.result(timeout)
