"""Threaded generation server: one engine, one scheduler, one loop.

``GenerationServer`` owns a ``Scheduler`` (request intake, latency
accounting) and a ``ServingEngine`` (continuous batching over the paged
KV cache) and drives the engine from a background thread. User threads
call ``submit`` (non-blocking, returns a ``concurrent.futures.Future``)
or ``generate`` (blocking convenience); the engine loop sleeps briefly
when fully idle instead of spinning.

``kill`` stops the loop abruptly WITHOUT resolving in-flight futures —
that is the eviction drill: a replica dying mid-stream leaves its
requests dangling until ``ReplicaRouter.poll`` re-admits them on a
survivor (serving/replica.py).
"""

import threading
import time

from dlrover_tpu.serving.engine import ServingEngine
from dlrover_tpu.serving.scheduler import (
    Request, SamplingParams, Scheduler,
)


class GenerationServer:
    """Single-replica serving front end (threaded loop around the engine)."""

    def __init__(
        self,
        params,
        cfg,
        *,
        hub=None,
        replica: str = "replica-0",
        max_queue: int = 256,
        publish_every: float = 0.5,
        idle_sleep: float = 0.002,
        **engine_kw,
    ):
        self.replica = replica
        self.scheduler = Scheduler(
            max_queue=max_queue, hub=hub, replica=replica
        )
        self.engine = ServingEngine(params, cfg, self.scheduler, **engine_kw)
        self.publish_every = publish_every
        self.idle_sleep = idle_sleep
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- lifecycle -------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "GenerationServer":
        if self.alive:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"serving-{self.replica}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful: finish nothing extra, just stop the loop and join."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def kill(self) -> None:
        """Abrupt stop simulating a host eviction: the loop halts at the
        next step boundary and in-flight futures stay UNRESOLVED — the
        router's failover path picks them up."""
        self.stop()

    def _loop(self) -> None:
        last_pub = time.monotonic()
        while not self._stop_evt.is_set():
            worked = self.engine.step()
            now = time.monotonic()
            if now - last_pub >= self.publish_every:
                self.scheduler.publish(self.engine.stats())
                last_pub = now
            if not worked:
                self._stop_evt.wait(self.idle_sleep)
        # final snapshot so short-lived servers still leave telemetry
        self.scheduler.publish(self.engine.stats())

    # ---- intake ----------------------------------------------------------

    def submit(
        self, prompt, max_new_tokens: int, eos_id=None, priority: int = 0,
        sampling: SamplingParams | None = None,
    ) -> Request:
        if len(prompt) + max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({max_new_tokens}) "
                f"exceeds slot capacity {self.engine.max_len}"
            )
        return self.scheduler.submit(
            prompt, max_new_tokens, eos_id=eos_id, priority=priority,
            sampling=sampling,
        )

    def re_admit(self, req: Request) -> None:
        """Failover intake: requeue another replica's in-flight request
        under its original admission ticket (generation restarts from
        the prompt — live-page migration is the documented follow-on).
        ``req.sampling`` rides along, and position-indexed draws make
        the re-prefilled continuation identical to the original."""
        self.scheduler.re_admit(req)

    def generate(
        self, prompt, max_new_tokens: int, eos_id=None,
        timeout: float = 120.0, sampling: SamplingParams | None = None,
    ):
        """Blocking convenience: submit and wait for the full sequence."""
        return self.submit(
            prompt, max_new_tokens, eos_id=eos_id, sampling=sampling
        ).future.result(timeout)
