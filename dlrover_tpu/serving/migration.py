"""Live KV-page migration between serving replicas.

The serving tier's answer to the training tier's canonical-coordinate
donation (elastic/resharding.py): on a planned drain or detected
eviction, each victim request's *held KV pages* — int8 payload pages +
per-block f32 scales (the ``ops/quant.py`` block encode the pools store,
shipped verbatim) or bf16 rows, plus block-table order, position and
sampling state — transfer to a survivor that has RESERVED the same page
footprint, and the survivor resumes mid-decode at the original
position. Because every sampling draw folds in the absolute buffer
position (PR 13), the migrated continuation is bitwise the never-evicted
stream; nothing re-prefills.

Phase machine (reusing :class:`~dlrover_tpu.elastic.resharding.LiveResharder`
under per-phase :class:`PhaseBudgets`):

1. **detect**   — halt the victim (planned drain stops its loop; a kill
   already did), inventory its in-flight slots and queued requests.
2. **plan**     — obtain a versioned serving-reshard directive (master
   ``ServingEvictionNotice``/``ServingReshardDirective`` flow when a
   client is attached, a local monotonic version otherwise) and assign
   each victim request to the survivor with the most free pages.
3. **reserve**  — hold each request's full footprint on its survivor via
   ``PageAllocator.reserve_for_migration`` under ``server.paused()``.
   Overload-graceful: when pages are short, shed the survivor's
   lowest-priority queued NEW admissions (never re-admitted ones) with a
   retry-after-carrying ``AdmissionError``, back off with jitter, and
   retry inside the phase budget — a failover storm degrades p99
   instead of collapsing the loop.
4. **transfer** — snapshot each slot read-only on the donor, encode to
   the checksummed wire blob, decode on the survivor side. A truncated
   or corrupt blob raises :class:`TornPageTransfer` (a ``TornDonation``,
   so the resharder retries it with backoff before falling back).
5. **resume**   — commit the reservation into a free survivor slot and
   rebuild the lane exactly where the donor stopped
   (``ServingEngine.import_slot``), then release the donor slot.

Ladder semantics: a torn or over-deadline migration degrades to the
re-prefill path (abort reservations, ``re_admit`` every non-resumed
request under its original ticket) — NEVER to a lost request. The final
``reshard_recovery`` telemetry event carries ``path=live|fallback``.

Prefix sharing (refcounted pages) composes without special cases here:
``export_pages`` ships a slot's pages BY VALUE, so two victim requests
sharing prefix pages each carry a private copy and land independently;
the survivor's ``import_slot`` re-interns imported full prompt pages so
the hot prefix is immediately shareable again, and releasing the donor
slots goes through the refcounted evict — shared pages decrement once
per holder and return to the free list exactly once (the shared-pages
drill in tests/test_serving_prefix.py pins both allocators' refcount
conservation at drill end).

Fault injection points: ``serving.detect`` / ``serving.plan`` /
``serving.reserve`` / ``serving.transfer`` / ``serving.resume`` with
``rank`` = the acting replica's node_id (donor for detect/plan/transfer,
survivor for reserve/resume); see docs/fault_drills.md for the grammar.

This module deliberately does not import ``serving.replica`` — victim /
survivors are duck-typed (``.name``, ``.node_id``, ``.server``), so the
router can depend on the migrator without a cycle.
"""

import itertools
import json
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.comm import _backoff_delay
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.elastic.faults import (
    FaultInjector,
    TornDonation,
    get_injector,
)
from dlrover_tpu.elastic.resharding import (
    LiveResharder,
    MigrationError,
    PhaseBudgets,
    ReshardOutcome,
)
from dlrover_tpu.observability.tracing import get_tracer
from dlrover_tpu.serving.scheduler import AdmissionError, Request

logger = get_logger(__name__)

_MAGIC = b"DTKV1\n"
_local_directive = itertools.count(1)


class TornPageTransfer(TornDonation):
    """A page blob arrived truncated or corrupt (checksum/shape
    mismatch). Retryable: the donor snapshot is read-only, so the
    resharder re-runs the transfer phase before degrading."""


@dataclass
class RequestSnapshot:
    """Everything a survivor needs to resume one request mid-decode.

    ``pages`` maps pool key → host array ``[L, n_held, page_size, ...]``
    in LOGICAL page order, exactly as stored (int8 payloads + f32
    scales, or bf16 rows) — shipping the stored representation verbatim
    is what makes the continuation bitwise. The geometry fingerprint
    fields let the survivor refuse an incompatible donor (different
    page_size/mode/shape) and fall back to re-prefill instead of
    importing garbage.

    The ``Request`` OBJECT travels in-process alongside the snapshot
    (its future must resolve for the original caller); the metadata
    here duplicates what a cross-host receiver would need to rebuild
    one.
    """

    rid: str
    prompt: List[int]
    generated: List[int]
    n_prefilled: int
    phase: str                   # "prefill" | "decode"
    max_new_tokens: int
    seed: int
    # geometry fingerprint
    mode: str
    page_size: int
    n_layers: int
    kv_heads: int
    head_dim: int
    kv_block: int
    # logical index of the first page in ``pages`` — a whole-slot
    # migration ships 0; a streaming-handoff fragment ships the offset
    # its chunk committed at (serving/disagg.py)
    page_start: int = 0
    pages: Dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def n_pages(self) -> int:
        if not self.pages:
            return 0
        return next(iter(self.pages.values())).shape[1]

    @property
    def tokens_resident(self) -> int:
        """Tokens of compute a re-prefill would redo (the savings)."""
        return self.n_prefilled + len(self.generated)


def geometry_fingerprint(geom) -> Dict[str, Any]:
    return {
        "mode": geom.mode,
        "page_size": geom.page_size,
        "n_layers": geom.n_layers,
        "kv_heads": geom.kv_heads,
        "head_dim": geom.head_dim,
        "kv_block": geom.kv_block,
    }


def snapshot_slot(engine, i: int) -> RequestSnapshot:
    """Read-only donor-side snapshot of slot ``i`` (engine halted or
    paused). Safe to call repeatedly — a torn transfer re-snapshots."""
    s = engine.slots[i]
    if s is None:
        raise ValueError(f"slot {i} is empty")
    return RequestSnapshot(
        rid=s.req.rid,
        prompt=[int(t) for t in s.prompt],
        generated=list(s.generated),
        n_prefilled=int(s.n_prefilled),
        phase=s.phase,
        max_new_tokens=int(s.req.max_new_tokens),
        seed=int(s.req.sampling.seed),
        pages=engine.export_pages(i),
        **geometry_fingerprint(engine.geom),
    )


# ------------------------------------------------------------------ wire


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 & friends register with numpy via ml_dtypes (jax dep)
        import jax.numpy as jnp

        return np.dtype(getattr(jnp, name))


def encode_snapshot(snap: RequestSnapshot) -> bytes:
    """Serialize to the migration wire blob: JSON header (metadata +
    per-array dtype/shape manifest + payload CRC) followed by the raw
    page bytes. int8 pools ship payload pages + per-block f32 scales
    exactly as the ``ops/quant.py`` block encode stored them."""
    keys = sorted(snap.pages)
    payload = b"".join(
        np.ascontiguousarray(snap.pages[k]).tobytes() for k in keys
    )
    header = json.dumps({
        "meta": {
            "rid": snap.rid,
            "prompt": snap.prompt,
            "generated": snap.generated,
            "n_prefilled": snap.n_prefilled,
            "phase": snap.phase,
            "max_new_tokens": snap.max_new_tokens,
            "seed": snap.seed,
            "page_start": snap.page_start,
            "mode": snap.mode,
            "page_size": snap.page_size,
            "n_layers": snap.n_layers,
            "kv_heads": snap.kv_heads,
            "head_dim": snap.head_dim,
            "kv_block": snap.kv_block,
        },
        "arrays": [
            {
                "key": k,
                "dtype": snap.pages[k].dtype.name,
                "shape": list(snap.pages[k].shape),
            }
            for k in keys
        ],
        "payload_len": len(payload),
        "crc": zlib.crc32(payload),
    }).encode()
    return _MAGIC + struct.pack("<I", len(header)) + header + payload


def decode_snapshot(data: bytes) -> RequestSnapshot:
    """Parse and VERIFY a wire blob. Any truncation, bad magic, length
    or CRC mismatch raises :class:`TornPageTransfer` — the retryable
    torn-transfer signal, never a silent partial import."""
    try:
        if data[: len(_MAGIC)] != _MAGIC:
            raise TornPageTransfer("bad magic — not a migration blob")
        off = len(_MAGIC)
        (hlen,) = struct.unpack("<I", data[off:off + 4])
        off += 4
        raw = data[off:off + hlen]
        if len(raw) != hlen:
            raise TornPageTransfer("truncated header")
        header = json.loads(raw)
        off += hlen
        payload = data[off:]
        if len(payload) != header["payload_len"]:
            raise TornPageTransfer(
                f"truncated payload: {len(payload)} of "
                f"{header['payload_len']} bytes"
            )
        if zlib.crc32(payload) != header["crc"]:
            raise TornPageTransfer("payload checksum mismatch")
        pages: Dict[str, np.ndarray] = {}
        pos = 0
        for spec in header["arrays"]:
            dt = _np_dtype(spec["dtype"])
            shape = tuple(spec["shape"])
            n = dt.itemsize * int(np.prod(shape))
            pages[spec["key"]] = np.frombuffer(
                payload[pos:pos + n], dtype=dt
            ).reshape(shape)
            pos += n
        m = header["meta"]
        return RequestSnapshot(pages=pages, **m)
    except TornPageTransfer:
        raise
    except Exception as e:  # struct/json/shape errors are torn too
        raise TornPageTransfer(f"undecodable migration blob: {e}") from e


# ------------------------------------------------------------ phase machine


@dataclass
class MigrationReport:
    """What one :meth:`ServingMigrator.migrate` call did."""

    outcome: ReshardOutcome
    placements: Dict[str, str]        # rid → survivor name (live-migrated)
    re_prefilled: Dict[str, str]      # rid → survivor name (fallback tier)
    re_routed: Dict[str, str]         # queued-only rids → survivor name
    directive_version: int = 0
    bytes_moved: int = 0
    tokens_saved: int = 0             # prefill+decode compute not redone

    @property
    def path(self) -> str:
        return self.outcome.path


class _Assignment:
    """One victim request's migration state across phases."""

    __slots__ = ("slot", "req", "survivor", "snap", "reserved", "resumed")

    def __init__(self, slot: int, req: Request, survivor):
        self.slot = slot
        self.req = req
        self.survivor = survivor
        self.snap: Optional[RequestSnapshot] = None
        self.reserved = False
        self.resumed = False


class ServingMigrator:
    """Drives one victim's drain/eviction through the migration ladder.

    ``master_client`` (optional, any object with
    ``report_serving_eviction``/``get_serving_reshard``) threads the
    directive through the master; without one the migrator versions
    directives locally — the in-process drill path.
    """

    def __init__(
        self,
        budgets: Optional[PhaseBudgets] = None,
        faults: Optional[FaultInjector] = None,
        master_client=None,
        retries: int = 2,
        shed_per_attempt: int = 2,
        reserve_attempts: int = 6,
        re_admit=None,
    ):
        self.budgets = budgets or PhaseBudgets()
        self.faults = faults if faults is not None else get_injector()
        self.master_client = master_client
        self.retries = retries
        self.shed_per_attempt = shed_per_attempt
        self.reserve_attempts = reserve_attempts
        # ``re_admit(req, survivor) -> str`` override for the fallback
        # ladder's raw re-admission. A disaggregated router installs a
        # role-aware version here: a decode-only survivor must never be
        # handed an un-prefilled request (it would chunk-prefill it and
        # recreate the interference the split removed), so the override
        # re-dispatches through the prefill pool and returns the name of
        # the replica that actually took the ticket.
        self.re_admit = re_admit

    def _re_admit(self, req: Request, survivor) -> str:
        if self.re_admit is not None:
            return self.re_admit(req, survivor)
        survivor.server.re_admit(req)
        return survivor.name

    # ---- phases (each closes over one migration's context) ---------------

    def migrate(self, victim, survivors: Sequence) -> MigrationReport:
        """Move every in-flight request off ``victim`` onto
        ``survivors``; queued-but-never-admitted requests are re-routed
        (nothing to migrate). Never raises for torn/over-deadline
        transfers — those degrade to re-prefill; an ``InjectedKill``
        (replica-scope kill drill) propagates."""
        survivors = [s for s in survivors if s.server.alive or s is victim]
        if not survivors or all(s is victim for s in survivors):
            raise ValueError("migration needs at least one live survivor")
        survivors = [s for s in survivors if s is not victim]

        ctx: Dict[str, Any] = {
            "assignments": [],      # List[_Assignment]
            "queued": [],           # List[Request]
            "version": 0,
            "bytes": 0,
            "placements": {},
            "re_prefilled": {},
            "re_routed": {},
            "tokens_saved": 0,
        }
        rr = itertools.count()

        def detect(_prev):
            self.faults.at("serving.detect", rank=victim.node_id)
            srv = victim.server
            ctx["reason"] = "drain" if srv.alive else "evict"
            if srv.alive:
                # planned drain: stop admitting, then halt the loop at a
                # step boundary — in-HBM pool state survives the stop
                srv.begin_drain()
                srv.stop()
            eng = srv.engine
            in_flight = [
                (i, s.req)
                for i, s in enumerate(eng.slots)
                if s is not None and not s.req.future.done()
            ]
            while True:
                q = srv.scheduler.pop_next()
                if q is None:
                    break
                ctx["queued"].append(q)
            if not in_flight and not ctx["queued"]:
                return ctx
            return {"in_flight": in_flight}

        def plan(detected):
            self.faults.at("serving.plan", rank=victim.node_id)
            in_flight = (detected or {}).get("in_flight", [])
            if self.master_client is not None:
                self.master_client.report_serving_eviction(
                    victim.name,
                    in_flight=len(in_flight),
                    deadline_s=self.budgets.transfer_s,
                    reason=ctx.get("reason", "evict"),
                )
                directive = self.master_client.get_serving_reshard()
                ctx["version"] = int(directive.version)
            else:
                ctx["version"] = next(_local_directive)
            # most-free-pages-first placement, debited as we assign
            headroom = {
                id(s): s.server.engine.alloc.free_pages for s in survivors
            }
            for slot, req in in_flight:
                tgt = max(survivors, key=lambda s: headroom[id(s)])
                headroom[id(tgt)] -= tgt.server.engine.alloc.pages_needed(
                    req.total_tokens
                )
                ctx["assignments"].append(_Assignment(slot, req, tgt))
            return ctx["assignments"]

        def reserve(assignments):
            t0 = time.monotonic()
            budget = self.budgets.for_phase("reserve")
            for a in assignments:
                self.faults.at("serving.reserve", rank=a.survivor.node_id)
                sched = a.survivor.server.scheduler
                for attempt in range(self.reserve_attempts):
                    with a.survivor.server.paused() as eng:
                        a.reserved = eng.alloc.reserve_for_migration(
                            a.req.rid, a.req.total_tokens
                        )
                    if a.reserved:
                        break
                    # overload-graceful: shed the survivor's lowest-
                    # priority queued NEW admissions (never re-admits),
                    # then jittered backoff while running slots retire
                    shed = sched.shed_lowest(
                        count=self.shed_per_attempt,
                        below_priority=a.req.priority,
                    )
                    remaining = budget - (time.monotonic() - t0)
                    if remaining <= 0:
                        break
                    time.sleep(min(_backoff_delay(attempt), remaining))
                    logger.info(
                        "reserve retry %d for %s on %s (shed %d)",
                        attempt + 1, a.req.rid, a.survivor.name, len(shed),
                    )
                if not a.reserved:
                    raise MigrationError(
                        f"survivor {a.survivor.name} cannot reserve "
                        f"{a.req.total_tokens} tokens for {a.req.rid} "
                        f"within the {budget:.1f}s reserve budget"
                    )
            return assignments

        def transfer(assignments):
            eng = victim.server.engine
            tr = get_tracer()
            ctx["bytes"] = 0
            for a in assignments:
                sp = None
                if tr.enabled:
                    sp = tr.begin(
                        "serving.migrate_transfer", rid=a.req.rid,
                        victim=victim.name, survivor=a.survivor.name,
                    )
                snap = snapshot_slot(eng, a.slot)
                blob = encode_snapshot(snap)
                self.faults.at("serving.transfer", rank=victim.node_id)
                a.snap = decode_snapshot(blob)
                ctx["bytes"] += len(blob)
                if sp is not None:
                    sp.end(bytes=len(blob))
            return assignments

        def resume(assignments):
            tr = get_tracer()
            for a in assignments:
                sp = None
                if tr.enabled:
                    sp = tr.begin(
                        "serving.migrate_resume", rid=a.req.rid,
                        victim=victim.name, survivor=a.survivor.name,
                    )
                self.faults.at("serving.resume", rank=a.survivor.node_id)
                snap = a.snap
                try:
                    self._check_geometry(snap, a.survivor.server.engine)
                    with a.survivor.server.paused() as eng:
                        eng.import_slot(
                            a.req,
                            snap.pages,
                            phase=snap.phase,
                            n_prefilled=snap.n_prefilled,
                            generated=snap.generated,
                            reserved_tag=a.req.rid,
                        )
                except (AdmissionError, ValueError, KeyError) as e:
                    # per-request ladder: this one re-prefills, the rest
                    # of the batch still migrates live
                    logger.warning(
                        "resume of %s on %s degraded to re-prefill: %s",
                        a.req.rid, a.survivor.name, e,
                    )
                    with a.survivor.server.paused() as eng:
                        eng.alloc.abort_migration(a.req.rid)
                    ctx["re_prefilled"][a.req.rid] = self._re_admit(
                        a.req, a.survivor
                    )
                    if sp is not None:
                        sp.end(path="re_prefill")
                else:
                    a.resumed = True
                    ctx["placements"][a.req.rid] = a.survivor.name
                    ctx["tokens_saved"] += snap.tokens_resident
                    if sp is not None:
                        sp.end(path="live")
                victim.server.engine.release_slot(a.slot)
            self._route_queued(ctx, survivors, rr)
            return assignments

        def fallback(exc):
            """The re-prefill tier: abort every reservation, re-admit
            every non-resumed in-flight request under its original
            ticket. No request is lost; none is duplicated (resumed
            ones keep their survivor slot)."""
            for a in ctx["assignments"]:
                if a.resumed:
                    continue
                with a.survivor.server.paused() as eng:
                    eng.alloc.abort_migration(a.req.rid)
                ctx["re_prefilled"][a.req.rid] = self._re_admit(
                    a.req, a.survivor
                )
            self._route_queued(ctx, survivors, rr)
            return ctx["assignments"]

        resharder = LiveResharder(
            budgets=self.budgets,
            faults=self.faults,
            retries=self.retries,
        )
        outcome = resharder.execute(
            [
                ("detect", detect),
                ("plan", plan),
                ("reserve", reserve),
                ("transfer", transfer),
                ("resume", resume),
            ],
            fallback=fallback,
        )
        report = MigrationReport(
            outcome=outcome,
            placements=dict(ctx["placements"]),
            re_prefilled=dict(ctx["re_prefilled"]),
            re_routed=dict(ctx["re_routed"]),
            directive_version=ctx["version"],
            bytes_moved=ctx["bytes"],
            tokens_saved=ctx["tokens_saved"],
        )
        logger.info(
            "migration of %s: path=%s live=%d fallback=%d re_routed=%d "
            "v%d %.0f bytes, %d tokens saved, %.3fs",
            victim.name, report.path, len(report.placements),
            len(report.re_prefilled), len(report.re_routed),
            report.directive_version, report.bytes_moved,
            report.tokens_saved, outcome.recovery_s,
        )
        return report

    # ---- helpers ---------------------------------------------------------

    @staticmethod
    def _check_geometry(snap: RequestSnapshot, engine) -> None:
        want = geometry_fingerprint(engine.geom)
        got = {k: getattr(snap, k) for k in want}
        if got != want:
            raise ValueError(
                f"donor geometry {got} incompatible with survivor {want}"
            )

    def _route_queued(self, ctx, survivors, rr) -> None:
        """Queued-but-never-admitted victim requests re-route round-robin
        (original tickets; nothing resident to migrate). Idempotent —
        drains ctx['queued'] so resume and fallback can both call it."""
        while ctx["queued"]:
            req = ctx["queued"].pop(0)
            tgt = survivors[next(rr) % len(survivors)]
            ctx["re_routed"][req.rid] = self._re_admit(req, tgt)
