"""Failure diagnosis: collect, classify, prescribe.

Reference: dlrover/python/master/diagnosis/ (DiagnosisManager
diagnosis.py:31, diagnostician.py) + monitor/error_monitor.py:22 (failure
classification) + the hang detection in dist_job_manager.py:802.

Collects agent-reported failures and resource stats, classifies them into
known TPU failure modes, and emits actions the master/agents execute
(restart process, relaunch node, abort job).
"""

import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


class DiagnosisAction:
    NONE = "none"
    RESTART_WORKER = "restart_worker"
    RELAUNCH_NODE = "relaunch_node"
    ABORT_JOB = "abort_job"


# error-signature → (classification, action)
_FAILURE_RULES = [
    # XLA/TPU level
    (r"RESOURCE_EXHAUSTED|out of memory|OOM", "oom", DiagnosisAction.ABORT_JOB),
    (
        r"(slice|ICI|interconnect).*(fail|error|down)",
        "hardware_error",
        DiagnosisAction.RELAUNCH_NODE,
    ),
    (
        r"(DEADLINE_EXCEEDED|barrier timeout|heartbeat)",
        "hang",
        DiagnosisAction.RESTART_WORKER,
    ),
    (
        r"(UNAVAILABLE|coordination service|preempt)",
        "preempted",
        DiagnosisAction.RELAUNCH_NODE,
    ),
    (
        r"(SyntaxError|ImportError|ModuleNotFoundError|TypeError)",
        "user_error",
        DiagnosisAction.ABORT_JOB,
    ),
]


@dataclass
class FailureRecord:
    node_id: int
    error_data: str
    level: str
    classification: str = "unknown"
    action: str = DiagnosisAction.NONE
    timestamp: float = field(default_factory=time.time)


def classify_failure(error_data: str) -> tuple:
    for pattern, cls, action in _FAILURE_RULES:
        if re.search(pattern, error_data, re.IGNORECASE):
            return cls, action
    return "unknown", DiagnosisAction.RESTART_WORKER


class DiagnosisManager:
    def __init__(self, hang_cpu_percent: float = 5.0, window: int = 512):
        self._lock = threading.Lock()
        self.failures: Deque[FailureRecord] = deque(maxlen=window)
        self.resource_history: Dict[int, Deque] = {}
        self.diagnosis_data: Dict[int, Deque] = {}
        self._hang_cpu_percent = hang_cpu_percent
        self._window = window
        # node_id → actions queued for that node's next heartbeat
        self._pending_actions: Dict[int, List[str]] = {}

    # ---- telemetry-bus subscription --------------------------------------

    def attach(self, hub) -> None:
        """Subscribe to the master's telemetry bus instead of being
        hand-wired per report type: resource records feed the hang
        detector's history, straggler flags, numeric incidents, worker
        anomalies, and cross-host health verdicts land as diagnosis
        evidence."""
        hub.subscribe(
            self._on_record,
            types=(
                "AnomalyRecord",
                "HealthSummary",
                "NumericEvent",
                "ResourceRecord",
                "StragglerRecord",
            ),
        )

    def _on_record(self, record) -> None:
        tname = type(record).__name__
        if tname == "ResourceRecord":
            with self._lock:
                hist = self.resource_history.setdefault(
                    record.node_id, deque(maxlen=64)
                )
                hist.append(
                    {
                        "t": time.time(),
                        "cpu": record.cpu_percent,
                        "mem_mb": record.mem_mb,
                        "hbm_mb": record.hbm_mb,
                        "hbm_peak_mb": record.hbm_peak_mb,
                    }
                )
        elif tname == "StragglerRecord":
            self.collect_diagnosis_data(
                record.node_id,
                f"straggler: step={record.step} max_step={record.max_step}"
                f" lag={record.lag_steps} ratio={record.ratio:.2f}",
            )
        elif tname == "NumericEvent":
            # NumericEvent carries no node id (worker-originated via the
            # wire); filed under the synthetic node -1 job bucket
            self.collect_diagnosis_data(
                -1,
                f"numeric {record.kind} at step {record.step}: "
                f"value={record.value} {record.detail}",
            )
        elif tname == "AnomalyRecord":
            self.collect_diagnosis_data(
                record.node_id,
                f"anomaly {record.kind} at step {record.step}: "
                f"value={record.value} {record.detail}"
                + (f" capture={record.capture}" if record.capture else ""),
            )
        elif tname == "HealthSummary":
            # the correlated verdict: filed job-wide AND per affected
            # rank so a node's evidence trail shows the attribution
            content = (
                f"health {record.kind}: verdict={record.verdict} "
                f"ranks=[{record.ranks}] of world={record.world}, "
                f"first bad step {record.first_step}"
            )
            self.collect_diagnosis_data(-1, content)
            for rank in record.ranks.split(","):
                if rank.strip():
                    self.collect_diagnosis_data(int(rank), content)

    # ---- collection ------------------------------------------------------

    def collect_failure(self, msg, worker_alive: bool = False) -> FailureRecord:
        cls, action = classify_failure(msg.error_data)
        # RESTART_WORKER is the agent's own default reaction to a dead
        # worker; queueing it again would double-restart. Only queue it when
        # the worker is still alive (hang reports), and always queue the
        # stronger actions (abort / node relaunch).
        queue_action = action
        if action == DiagnosisAction.RESTART_WORKER and not worker_alive:
            queue_action = DiagnosisAction.NONE
        rec = FailureRecord(
            node_id=msg.node_id,
            error_data=msg.error_data,
            level=msg.level,
            classification=cls,
            action=action,
        )
        with self._lock:
            self.failures.append(rec)
            if queue_action != DiagnosisAction.NONE:
                self._pending_actions.setdefault(msg.node_id, []).append(
                    queue_action
                )
        logger.info(
            "diagnosed node %d failure as %s → %s",
            msg.node_id,
            cls,
            action,
        )
        return rec

    def collect_diagnosis_data(self, node_id: int, content: str):
        """Store agent collector payloads (log tails, stacks, proc state)
        as evidence for later diagnosis — no failure side-effects."""
        with self._lock:
            hist = self.diagnosis_data.setdefault(
                node_id, deque(maxlen=32)
            )
            hist.append({"t": time.time(), "content": content[:8000]})

    def collect_resource(self, msg):
        with self._lock:
            hist = self.resource_history.setdefault(
                msg.node_id, deque(maxlen=64)
            )
            hist.append(
                {
                    "t": time.time(),
                    "cpu": msg.cpu_percent,
                    "mem_mb": msg.used_memory_mb,
                    "hbm_mb": msg.hbm_used_mb,
                }
            )

    # ---- queries ---------------------------------------------------------

    def queue_action_for(self, node_ids, action: str):
        """Queue an action for a set of nodes (abort fan-out, hang kick)."""
        with self._lock:
            for nid in node_ids:
                pending = self._pending_actions.setdefault(nid, [])
                if action not in pending:
                    pending.append(action)

    def take_actions(self, node_id: int) -> List[str]:
        """Drain queued actions; delivered via heartbeat responses."""
        with self._lock:
            return self._pending_actions.pop(node_id, [])

    # idle window before a job-wide hang verdict; also how far goodput
    # accounting backdates the stall (progress stopped at window start)
    HANG_WINDOW_S = 600.0

    def all_nodes_hanged(self, min_duration_s: float = HANG_WINDOW_S) -> bool:
        """Every node's CPU has been ~idle for the window → job hang
        (reference: dist_job_manager.py:802 all_running_node_hanged)."""
        now = time.time()
        with self._lock:
            if not self.resource_history:
                return False
            for hist in self.resource_history.values():
                recent = [
                    h for h in hist if now - h["t"] <= min_duration_s
                ]
                if not recent or any(
                    h["cpu"] > self._hang_cpu_percent for h in recent
                ):
                    return False
                if hist and now - hist[0]["t"] < min_duration_s:
                    return False
            return True

    def failure_summary(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for rec in self.failures:
                out[rec.classification] = out.get(rec.classification, 0) + 1
            return out
