"""Job masters: compose managers + transport; supervision loop.

Reference: dlrover/python/master/dist_master.py:86 (DistributedJobMaster),
local_master.py:38 (LocalJobMaster for single-node ``run`` CLI). One master
process per job; agents talk to it over the typed gRPC transport.
"""

import os
import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.comm import MasterTransportServer
from dlrover_tpu.common.constants import (
    DefaultValues,
    GraftEnv,
    JobExitReason,
    RendezvousName,
)
from dlrover_tpu.observability import telemetry, tracing
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.node_manager import JobManager, Scaler
from dlrover_tpu.master.rdzv_manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
)
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.sync_service import SyncService
from dlrover_tpu.master.task_manager import TaskManager

logger = get_logger(__name__)


class JobMaster:
    """Composition root; subclasses pick scaler/watcher flavors."""

    def __init__(
        self,
        port: int = 0,
        num_workers: int = 1,
        max_workers: Optional[int] = None,
        node_unit: int = 1,
        scaler: Optional[Scaler] = None,
        enable_auto_scaling: Optional[bool] = None,
        optimize_mode: str = "single-job",
        brain_addr: str = "",
        job_name: str = "",
        job_kind: str = "",
    ):
        # validate BEFORE any server construction: raising after the
        # transport bound its port would leak the socket + thread pool
        # on the error path (repo convention: a constructed-but-never-
        # run master must not hold a port)
        if optimize_mode == "cluster" and not brain_addr:
            raise ValueError(
                "optimize_mode='cluster' needs brain_addr "
                "(host:port of a dlrover-tpu-brain)"
            )
        ctx = get_context()
        self.optimize_mode = optimize_mode
        self.brain_addr = brain_addr
        self.job_name = job_name
        self.job_kind = job_kind
        self.speed_monitor = SpeedMonitor()
        self.job_manager = JobManager(
            num_workers=num_workers,
            relaunch_budget=ctx.relaunch_budget,
            heartbeat_timeout_s=ctx.heartbeat_timeout_s,
            pending_timeout_s=ctx.pending_timeout_s,
            scaler=scaler,
        )
        self.task_manager = TaskManager(shard_timeout_s=ctx.shard_timeout_s)
        self.task_manager.speed_monitor = self.speed_monitor
        self.rdzv_managers: Dict[str, object] = {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(),
            RendezvousName.NETWORK_CHECK: NetworkCheckRendezvousManager(),
        }
        max_w = max_workers or num_workers
        for mgr in self.rdzv_managers.values():
            mgr.update_rdzv_params(
                min_nodes=num_workers,
                max_nodes=max_w,
                waiting_timeout=ctx.rdzv_wait_extra_nodes_s,
                node_unit=node_unit,
            )
        self.kv_store = KVStoreService()
        self.sync_service = SyncService()
        self.sync_service.set_world_size_fn(
            lambda: len(self.job_manager.running_nodes()) or 1
        )
        from dlrover_tpu.diagnosis.manager import DiagnosisManager
        from dlrover_tpu.master.job_metrics import (
            GoodputTracker,
            JobMetricCollector,
            MetricsHTTPServer,
        )

        self.diagnosis_manager = DiagnosisManager()
        self.metric_collector = JobMetricCollector()
        self.goodput_tracker = GoodputTracker()
        self.metric_collector.goodput_tracker = self.goodput_tracker
        self.metrics_server = MetricsHTTPServer(self.metric_collector, port=0)
        # master-side telemetry bus: the servicer translates wire reports
        # onto it; metrics export + diagnosis subscribe rather than being
        # hand-wired call-by-call.  A master-local hub (not the process
        # singleton) so tests composing several masters don't cross wires.
        self.telemetry_hub = telemetry.TelemetryHub()
        self.telemetry_hub.add_sink(
            telemetry.MetricsSink(self.metric_collector)
        )
        tdir = os.getenv(GraftEnv.TELEMETRY_DIR)
        if tdir:
            self.telemetry_hub.add_sink(
                telemetry.JsonlSink(
                    os.path.join(
                        tdir, f"telemetry-master-{os.getpid()}.jsonl"
                    )
                )
            )
        self.diagnosis_manager.attach(self.telemetry_hub)
        self.speed_monitor.attach_hub(self.telemetry_hub)
        # cross-host anomaly correlation: worker AnomalyRecords arriving
        # over the wire (MasterSink → report_telemetry) fold into
        # HealthSummary verdicts the diagnosis manager subscribes to
        from dlrover_tpu.observability.watchdog import HealthAggregator

        self.health_aggregator = HealthAggregator(
            hub=self.telemetry_hub, world=num_workers
        )
        # flight-recorder spans: real tracer only when a trace dir is
        # set, the pinned null tracer otherwise
        self.tracer = (
            tracing.configure_tracer("master")
            if os.getenv(GraftEnv.TRACE_DIR)
            else tracing.get_tracer()
        )
        from dlrover_tpu.master.elastic_ps import ElasticPsService

        self.ps_service = ElasticPsService()
        self.servicer = MasterServicer(
            job_manager=self.job_manager,
            task_manager=self.task_manager,
            rdzv_managers=self.rdzv_managers,
            kv_store=self.kv_store,
            sync_service=self.sync_service,
            speed_monitor=self.speed_monitor,
            diagnosis_manager=self.diagnosis_manager,
            ps_service=self.ps_service,
            goodput_tracker=self.goodput_tracker,
            metric_collector=self.metric_collector,
            telemetry_hub=self.telemetry_hub,
        )
        self.server = MasterTransportServer(self.servicer, port=port)

        # auto-scaler runs whenever the job declared an elastic range
        from dlrover_tpu.master.auto_scaler import JobAutoScaler

        if enable_auto_scaling is None:
            enable_auto_scaling = max_w > num_workers
        # optimize_mode=cluster: plans come from the shared Brain wire
        # service instead of the local heuristic (reference:
        # resource/brain_optimizer.py consuming go/brain over gRPC).
        # Built only when an auto-scaler will consume it — otherwise the
        # client would sit unused holding an open channel; closed in
        # stop() either way via self._brain_client.
        optimizer = None
        self._brain_client = None
        if optimize_mode == "cluster":
            if not enable_auto_scaling:
                logger.warning(
                    "optimize_mode='cluster' has no effect without auto "
                    "scaling (max_workers == num_workers); brain %s "
                    "will not be consulted",
                    brain_addr,
                )
            else:
                from dlrover_tpu.cluster.brain import BrainClient

                optimizer = BrainClient(brain_addr)
                optimizer.bind_job(job_name or "job", job_kind)
                self._brain_client = optimizer
        self.auto_scaler: Optional[JobAutoScaler] = None
        if enable_auto_scaling:
            self.auto_scaler = JobAutoScaler(
                self.job_manager,
                self.speed_monitor,
                self.job_manager._scaler,
                rdzv_managers=self.rdzv_managers,
                optimizer=optimizer,
                min_workers=num_workers,
                max_workers=max_w,
                node_unit=node_unit,
                interval_s=ctx.autoscale_interval_s,
                ps_service=self.ps_service,
            )
        self._stop = threading.Event()
        self._last_hang_kick = 0.0
        self.exit_reason = ""

        # wire elastic event callbacks through the pluggable registry
        # (reference: event_callback.py:42): shard reschedule + rdzv
        # prune from the stock observers, master-local accounting from a
        # private one. Users can append their own NodeEventCallback.
        from dlrover_tpu.master.event_callback import (
            ClusterContext,
            default_callbacks,
        )

        self.job_manager.cluster_context = ClusterContext(
            self.job_manager,
            task_manager=self.task_manager,
            rdzv_managers=self.rdzv_managers,
            speed_monitor=self.speed_monitor,
        )
        self.job_manager.event_callbacks.extend(
            default_callbacks(
                task_manager=self.task_manager,
                rdzv_managers=self.rdzv_managers,
                on_job_failed=self._fail_job,
            )
        )
        # PS-typed node lifecycle drives the versioned sparse server set
        # (workers reroute via sync_with_master)
        from dlrover_tpu.master.elastic_ps import PsClusterCallback

        self.job_manager.event_callbacks.append(
            PsClusterCallback(self.ps_service)
        )
        self.job_manager.node_failed_callbacks.append(self._on_node_down)

    def _fail_job(self, reason: str):
        self.exit_reason = JobExitReason.RELAUNCH_BUDGET_EXHAUSTED
        logger.error("job failed: %s", reason)
        self._stop.set()

    def _on_node_down(self, node):
        # master-local accounting (shard requeue + rdzv prune live in
        # the registry callbacks above)
        self.speed_monitor.reset_running_speed()
        self.speed_monitor.drop_node(node.id)
        self.metric_collector.inc("node_failures_total")
        # goodput: lost time runs from here until a step report ADVANCES
        # past the step training had reached when the node died
        self.goodput_tracker.mark_stalled(
            at_step=self.speed_monitor.global_step
        )
        # flight recorder: the master's detect mark anchors the failover
        # timeline (heartbeat timeout means the node itself may never
        # have gotten a span out)
        self.tracer.instant(
            "failover.detect", node=node.id, source="heartbeat_timeout"
        )
        if self.telemetry_hub.enabled:
            self.telemetry_hub.publish(
                telemetry.ElasticEvent(
                    kind="node_down",
                    node_id=node.id,
                    detail="heartbeat_timeout",
                )
            )

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def addr(self) -> str:
        return f"localhost:{self.server.port}"

    def prepare(self):
        self.server.start()
        self.metrics_server.start()
        logger.info("metrics endpoint on port %d", self.metrics_server.port)
        self.task_manager.start()
        self.job_manager.start()
        if self.auto_scaler is not None:
            self.auto_scaler.start()

    def run(self, poll_interval_s: Optional[float] = None) -> str:
        """Supervision loop (reference: dist_master.py:211)."""
        ctx = get_context()
        interval = poll_interval_s or ctx.supervise_interval_s
        try:
            while not self._stop.wait(interval):
                self.metric_collector.collect_runtime(
                    self.speed_monitor.global_step,
                    self.speed_monitor.running_speed,
                    len(self.job_manager.running_nodes()),
                )
                if self.task_manager.finished():
                    self.exit_reason = JobExitReason.SUCCEEDED
                    # Drain: workers still run their final step, persist
                    # checkpoints, and report status after the last shard is
                    # done — keep serving RPCs until they exit (bounded).
                    # Evaluators gate the drain too (reference:
                    # EvaluatorManager wait-then-finish).
                    self._wait_workers_drain(ctx.worker_drain_timeout_s)
                    break
                if (
                    self.job_manager.all_workers_exited()
                    and self.job_manager.all_evaluators_exited()
                ):
                    if self.job_manager.all_workers_succeeded():
                        self.exit_reason = JobExitReason.SUCCEEDED
                    else:
                        self.exit_reason = (
                            JobExitReason.RELAUNCH_BUDGET_EXHAUSTED
                        )
                    break
                if self.job_manager.pending_timeout():
                    self.exit_reason = JobExitReason.PENDING_TIMEOUT
                    break
                if (
                    self.diagnosis_manager is not None
                    and time.time() - self._last_hang_kick
                    > ctx.hang_kick_cooldown_s
                    and self.diagnosis_manager.all_nodes_hanged()
                ):
                    # job-wide hang (reference: dist_job_manager.py:802):
                    # kick every node to checkpoint-restart its worker.
                    # Cooldown: ckpt + re-rendezvous takes a while before
                    # fresh CPU samples land — don't re-kick every tick.
                    self._last_hang_kick = time.time()
                    # progress stopped at the START of the idle window,
                    # not at kick time — backdate the lost-time
                    # accounting (clamped inside the tracker)
                    self.goodput_tracker.mark_stalled(
                        at_step=self.speed_monitor.global_step,
                        accounted_from=time.monotonic()
                        - self.diagnosis_manager.HANG_WINDOW_S,
                    )
                    logger.warning("all nodes idle — prescribing restart")
                    self.diagnosis_manager.queue_action_for(
                        [n.id for n in self.job_manager.running_nodes()],
                        "restart_worker",
                    )
        finally:
            self.stop()
        logger.info("master exiting: %s", self.exit_reason)
        return self.exit_reason

    def _wait_workers_drain(self, timeout_s: float):
        deadline = time.time() + timeout_s
        while time.time() < deadline and not self._stop.is_set():
            if (
                self.job_manager.all_workers_exited()
                and self.job_manager.all_evaluators_exited()
            ):
                return
            time.sleep(1.0)

    def request_stop(self, reason: str = ""):
        self.exit_reason = reason or self.exit_reason
        self._stop.set()

    def stop(self):
        self._stop.set()
        if self.auto_scaler is not None:
            self.auto_scaler.stop()
        if self._brain_client is not None:
            self._brain_client.close()
        self.task_manager.stop()
        self.job_manager.stop()
        self.metrics_server.stop()
        self.server.stop()


class LocalJobMaster(JobMaster):
    """In-process/subprocess master for single-host ``dlrover-tpu-run``."""


class DistributedJobMaster(JobMaster):
    """Multi-host master; platform scaler/watcher attach here."""


def run_master_forever(master: JobMaster):
    master.prepare()
    return master.run()
