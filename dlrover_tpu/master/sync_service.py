"""Named barriers across workers (reference: sync_service.py:26)."""

import threading
from typing import Dict, Set


class SyncService:
    def __init__(self):
        self._lock = threading.Lock()
        self._syncs: Dict[str, Set[int]] = {}
        self._finished: Set[str] = set()
        self._world_size_fn = lambda: 1  # wired by the master

    def set_world_size_fn(self, fn):
        self._world_size_fn = fn

    def join_sync(self, sync_name: str, node_rank: int) -> bool:
        with self._lock:
            members = self._syncs.setdefault(sync_name, set())
            members.add(node_rank)
            if len(members) >= self._world_size_fn():
                self._finished.add(sync_name)
            return True

    def sync_finished(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished

    def barrier(self, sync_name: str) -> bool:
        """Explicitly mark a sync finished (master-driven barrier release)."""
        with self._lock:
            self._finished.add(sync_name)
            return True
