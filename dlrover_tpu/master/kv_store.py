"""In-master KV store (reference: elastic_training/kv_store_service.py:18).

Backs distributed bootstrap handshakes (the reference uses it as the c10d
Store; here agents use it to exchange the jax.distributed coordinator and
checkpoint metadata).
"""

import threading
import time
from typing import Dict, Optional


class KVStoreService:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, str] = {}
        self._cond = threading.Condition(self._lock)

    def set(self, key: str, value: str):
        with self._cond:
            self._store[key] = value
            self._cond.notify_all()

    def get(self, key: str) -> str:
        with self._lock:
            return self._store.get(key, "")

    def add(self, key: str, delta: int = 1) -> int:
        with self._cond:
            val = int(self._store.get(key, "0")) + delta
            self._store[key] = str(val)
            self._cond.notify_all()
            return val

    def wait(self, key: str, timeout_s: float = 60.0) -> Optional[str]:
        deadline = time.time() + timeout_s
        with self._cond:
            while key not in self._store:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
            return self._store[key]

    def delete(self, key: str):
        with self._lock:
            self._store.pop(key, None)

    def clear(self):
        with self._lock:
            self._store.clear()
