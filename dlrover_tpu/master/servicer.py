"""MasterServicer: demux the two-RPC surface onto the managers.

Reference: dlrover/python/master/servicer.py:71 (single report/get pair
demuxed on message type). Exceptions never cross the RPC edge — the
transport returns Response(success=False).
"""

import time
from typing import Optional

from dlrover_tpu.common import messages as msgs
from dlrover_tpu.common.constants import NodeStatus, RendezvousName
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.observability import telemetry

logger = get_logger(__name__)


class MasterServicer:
    def __init__(
        self,
        job_manager=None,
        task_manager=None,
        rdzv_managers=None,
        kv_store=None,
        sync_service=None,
        speed_monitor=None,
        diagnosis_manager=None,
        ps_service=None,
        goodput_tracker=None,
        metric_collector=None,
        telemetry_hub=None,
    ):
        self.job_manager = job_manager
        self.task_manager = task_manager
        self.rdzv_managers = rdzv_managers or {}
        self.kv_store = kv_store
        self.sync_service = sync_service
        self.speed_monitor = speed_monitor
        self.diagnosis_manager = diagnosis_manager
        self.ps_service = ps_service
        self.goodput_tracker = goodput_tracker
        self.metric_collector = metric_collector
        self.telemetry_hub = telemetry_hub
        self._ckpt_steps = {}  # node_rank -> step (flash-ckpt rank sync)

    # ---- report: fire-and-forget ----------------------------------------

    def report(self, msg) -> bool:
        handler = self._REPORT_HANDLERS.get(type(msg).__name__)
        if handler is None:
            logger.warning("no report handler for %s", type(msg).__name__)
            return False
        return bool(handler(self, msg))

    def _report_heartbeat(self, m: msgs.HeartbeatReport) -> bool:
        if self.job_manager:
            self.job_manager.handle_heartbeat(m.node_id)
        return True

    def _get_heartbeat(self, m: msgs.HeartbeatReport):
        """Heartbeat via get: response carries queued diagnosis actions."""
        if self.job_manager:
            self.job_manager.handle_heartbeat(m.node_id)
        actions = (
            self.diagnosis_manager.take_actions(m.node_id)
            if self.diagnosis_manager
            else []
        )
        return msgs.HeartbeatResponse(actions=actions)

    def _report_node_status(self, m: msgs.NodeStatusReport) -> bool:
        if self.job_manager:
            self.job_manager.handle_status_report(
                m.node_id, m.status, m.exit_reason
            )
        if m.status == NodeStatus.SUCCEEDED and self.goodput_tracker:
            # a worker ran to its final step: training is over, so stop
            # goodput lost-time accounting — a peer-death detected after
            # this point (heartbeat timeout racing job teardown) has no
            # training left to stall, and its stall could never be
            # closed by a step report anyway
            self.goodput_tracker.mark_completed()
        return True

    def _report_worker_restart(self, m: msgs.WorkerRestartReport) -> bool:
        """Voluntary worker kill+respawn (membership change, restart
        prescription): re-queue the node's in-flight shards — a leaked
        lease can never complete and deadlocks the dataset's tail —
        and open a goodput stall (training IS stopped until the
        restarted world's first advancing step report)."""
        logger.info(
            "node %d restarting its worker (%s)", m.node_id, m.reason
        )
        if self.task_manager:
            self.task_manager.recover_worker_tasks(m.node_id)
        if self.goodput_tracker:
            self.goodput_tracker.mark_stalled(
                at_step=(
                    self.speed_monitor.global_step
                    if self.speed_monitor
                    else None
                )
            )
        return True

    def _report_node_failure(self, m: msgs.NodeFailureReport) -> bool:
        if m.level == "diagnosis":
            # routine diagnosis payloads (log tails, proc state, stack
            # dumps from agent collectors) are evidence, NOT failures:
            # no task re-queue, no failure classification — a healthy
            # worker whose log merely contains an old error string must
            # not trigger recovery actions
            if self.diagnosis_manager:
                self.diagnosis_manager.collect_diagnosis_data(
                    m.node_id, m.error_data
                )
            logger.info(
                "diagnosis data from node %d: %s",
                m.node_id,
                m.error_data[:200],
            )
            return True
        if self.goodput_tracker:
            # worker-crash restarts (the common recovery path) stall the
            # job until a post-restart step report advances past here
            self.goodput_tracker.mark_stalled(
                at_step=self.speed_monitor.global_step
                if self.speed_monitor
                else None
            )
        if self.diagnosis_manager:
            rec = self.diagnosis_manager.collect_failure(m)
            # an abort is a job-level verdict — every node must stop, not
            # just the one that reported (the others would otherwise churn
            # in re-rendezvous forever)
            if rec.action == "abort_job":
                ids = {m.node_id}
                if self.job_manager:
                    ids.update(
                        n.id for n in self.job_manager.running_nodes()
                    )
                self.diagnosis_manager.queue_action_for(ids, rec.action)
        # the restarting worker lost its in-flight shards — re-queue them
        # (at-least-once delivery; reference: task_manager re-queue on death)
        if self.task_manager:
            self.task_manager.recover_worker_tasks(m.node_id)
        logger.warning(
            "node %d failure (level=%s restart=%d): %s",
            m.node_id,
            m.level,
            m.restart_count,
            m.error_data[:500],
        )
        return True

    def _report_resource(self, m: msgs.ResourceStats) -> bool:
        if self.telemetry_hub is not None and self.telemetry_hub.enabled:
            # diagnosis (and any other consumer) subscribes to the bus;
            # the servicer only translates wire → record
            self.telemetry_hub.publish(
                telemetry.ResourceRecord(
                    node_id=m.node_id,
                    cpu_percent=m.cpu_percent,
                    mem_mb=m.used_memory_mb,
                    hbm_mb=m.hbm_used_mb,
                    hbm_peak_mb=m.hbm_peak_mb,
                )
            )
        elif self.diagnosis_manager:
            # no bus wired (unit tests building a bare servicer): keep
            # the direct path so resource history still accumulates
            self.diagnosis_manager.collect_resource(m)
        return True

    def _report_telemetry(self, m: msgs.TelemetryEventReport) -> bool:
        if self.telemetry_hub is None or not self.telemetry_hub.enabled:
            return True  # accepted, nobody listening
        try:
            record = telemetry.from_json(m.payload)
        except (KeyError, ValueError) as e:
            logger.warning(
                "undecodable telemetry record from node %d: %s", m.node_id, e
            )
            return False
        self.telemetry_hub.publish(record)
        return True

    def _report_task_result(self, m: msgs.TaskResult) -> bool:
        if self.task_manager:
            self.task_manager.report_task_status(
                m.dataset_name, m.task_id, m.success, m.worker_id
            )
        return True

    def _report_dataset(self, m: msgs.DatasetShardParams) -> bool:
        if self.task_manager:
            self.task_manager.new_dataset(
                m.dataset_name,
                m.dataset_size,
                m.shard_size,
                num_epochs=m.num_epochs,
                shuffle=m.shuffle,
                storage_type=m.storage_type,
                task_type=m.task_type,
            )
        return True

    def _report_global_step(self, m: msgs.GlobalStepRecord) -> bool:
        if self.speed_monitor:
            self.speed_monitor.collect_global_step(
                m.global_step, m.timestamp or time.time(), node_id=m.node_id
            )
        if self.goodput_tracker:
            # a step report means training is making forward progress —
            # closes any stall opened by startup or a node failure, but
            # only for steps TAKEN after the stall opened and ADVANCING
            # past the stall point (in-flight/stale reports from
            # surviving ranks must not hide the recovery span)
            self.goodput_tracker.mark_productive(
                step=m.global_step, report_ts=m.timestamp or None
            )
        return True

    def _report_network_check(self, m: msgs.NetworkCheckResult) -> bool:
        mgr = self.rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if mgr:
            mgr.report_network_check_result(
                m.node_id, m.succeeded, m.elapsed_time
            )
        return True

    def _report_eviction(self, m: msgs.EvictionNotice) -> bool:
        """A worker announced departing dp ranks: issue the live-reshard
        directive so survivors migrate in-HBM state instead of
        restarting from a checkpoint."""
        mgr = self.rdzv_managers.get(RendezvousName.TRAINING)
        if mgr is None:
            return False
        try:
            version = mgr.plan_reshard(
                m.lost_dp_ranks,
                m.dp_size,
                deadline_s=m.deadline_s,
                reason=m.reason,
            )
        except ValueError as e:
            logger.warning(
                "rejecting eviction notice from node %d: %s", m.node_id, e
            )
            return False
        if self.telemetry_hub is not None and self.telemetry_hub.enabled:
            self.telemetry_hub.publish(
                telemetry.ElasticEvent(
                    kind="eviction_notice",
                    node_id=m.node_id,
                    detail=(
                        f"v{version} lost={m.lost_dp_ranks} "
                        f"dp={m.dp_size} {m.reason}"
                    ).strip(),
                )
            )
        return True

    def _report_serving_eviction(self, m: msgs.ServingEvictionNotice) -> bool:
        """A serving replica is leaving (planned drain or detected
        eviction): issue the page-migration directive so survivors adopt
        its in-flight requests' live KV pages instead of re-prefilling."""
        if self.job_manager is None:
            return False
        version = self.job_manager.plan_serving_reshard(
            m.replica, deadline_s=m.deadline_s, reason=m.reason
        )
        if self.telemetry_hub is not None and self.telemetry_hub.enabled:
            self.telemetry_hub.publish(
                telemetry.ElasticEvent(
                    kind="serving_eviction_notice",
                    node_id=m.node_id,
                    detail=(
                        f"v{version} victim={m.replica} "
                        f"in_flight={m.in_flight} {m.reason}"
                    ).strip(),
                )
            )
        return True

    def _report_serving_scale(self, m: msgs.ServingScaleNotice) -> bool:
        """The serving autoscaler reports one scale decision: version
        it as a serving-scale directive and surface it on the elastic
        event stream, same shape as the eviction path."""
        if self.job_manager is None:
            return False
        version = self.job_manager.plan_serving_scale(
            m.role, m.n_after, reason=m.reason or m.signal
        )
        if self.telemetry_hub is not None and self.telemetry_hub.enabled:
            self.telemetry_hub.publish(
                telemetry.ElasticEvent(
                    kind="serving_scale_notice",
                    node_id=m.node_id,
                    detail=(
                        f"v{version} role={m.role} {m.direction} "
                        f"{m.n_before}->{m.n_after} {m.signal}"
                    ).strip(),
                )
            )
        return True

    def _report_tuning_plan(self, m: msgs.TuningPlanNotice) -> bool:
        """The brain tuner reports one cold-start plan or revision:
        version it as a tuning directive (trainers pick it up through
        the ParallelConfig poll) and surface it on the elastic event
        stream, same shape as the serving-scale path."""
        if self.job_manager is None:
            return False
        version = self.job_manager.plan_tuning(
            m.plan_json, reason=m.reason or m.signal
        )
        if self.telemetry_hub is not None and self.telemetry_hub.enabled:
            self.telemetry_hub.publish(
                telemetry.ElasticEvent(
                    kind="tuning_plan_notice",
                    node_id=m.node_id,
                    detail=f"v{version} {m.signal} {m.reason}".strip(),
                )
            )
        return True

    def _report_kv(self, m: msgs.KeyValuePair) -> bool:
        if self.kv_store:
            self.kv_store.set(m.key, m.value)
        return True

    def _report_sync_join(self, m: msgs.SyncJoin) -> bool:
        if self.sync_service:
            return self.sync_service.join_sync(m.sync_name, m.node_rank)
        return False

    def _report_ckpt_step(self, m: msgs.CheckpointStepSync) -> bool:
        self._ckpt_steps[m.node_rank] = m.step
        return True

    def _report_shard_ckpt(self, m: msgs.ShardCheckpoint) -> bool:
        if self.task_manager:
            self.task_manager.restore_checkpoint(m.dataset_name, m.content)
        return True

    def _report_ps_version(self, m: msgs.PsVersionReport) -> bool:
        if not self.ps_service:
            return False
        if m.version_type == "global":
            self.ps_service.bump_global_version()
        else:
            self.ps_service.set_node_version(m.node_id, m.version)
        return True

    def _report_model_info(self, m: msgs.ModelInfoReport) -> bool:
        if self.metric_collector:
            # partial update: unset (zero/empty) fields must not clobber
            # values another reporter already provided
            kw = {
                k: v
                for k, v in (
                    ("model_name", m.model_name),
                    ("num_params", m.num_params),
                    ("flops_per_token", m.flops_per_token),
                    ("global_batch_size", m.global_batch_size),
                    ("seq_len", m.seq_len),
                    ("strategy_json", m.strategy_json),
                )
                if v
            }
            self.metric_collector.set_job_meta(**kw)
        return True

    _REPORT_HANDLERS = {
        "ModelInfoReport": _report_model_info,
        "PsVersionReport": _report_ps_version,
        "HeartbeatReport": _report_heartbeat,
        "NodeStatusReport": _report_node_status,
        "WorkerRestartReport": _report_worker_restart,
        "NodeFailureReport": _report_node_failure,
        "ResourceStats": _report_resource,
        "TelemetryEventReport": _report_telemetry,
        "TaskResult": _report_task_result,
        "DatasetShardParams": _report_dataset,
        "GlobalStepRecord": _report_global_step,
        "NetworkCheckResult": _report_network_check,
        "EvictionNotice": _report_eviction,
        "ServingEvictionNotice": _report_serving_eviction,
        "ServingScaleNotice": _report_serving_scale,
        "TuningPlanNotice": _report_tuning_plan,
        "KeyValuePair": _report_kv,
        "SyncJoin": _report_sync_join,
        "CheckpointStepSync": _report_ckpt_step,
        "ShardCheckpoint": _report_shard_ckpt,
    }

    # ---- get: request → response ----------------------------------------

    def get(self, msg):
        handler = self._GET_HANDLERS.get(type(msg).__name__)
        if handler is None:
            logger.warning("no get handler for %s", type(msg).__name__)
            return None
        return handler(self, msg)

    def _get_register(self, m: msgs.NodeRegisterRequest):
        if self.job_manager and m.meta:
            node = self.job_manager.register_node(m.meta, m.restart_count)
            for mgr in self.rdzv_managers.values():
                mgr.add_alive_node(node.rank_index)
            # a (re)registration is a FRESH incarnation: prescriptions
            # queued against its dead predecessor (e.g. relaunch_node
            # from the failure diagnosis) must not be delivered to the
            # replacement — obeying them would kill the very node the
            # relaunch asked for, looping the recovery
            if self.diagnosis_manager:
                self.diagnosis_manager.take_actions(node.id)
            return msgs.NodeRegisterResponse(
                success=True,
                node_rank=node.rank_index,
                node_num=self.job_manager.worker_num,
            )
        return msgs.NodeRegisterResponse(success=False)

    def _get_join_rdzv(self, m: msgs.JoinRendezvousRequest):
        mgr = self.rdzv_managers.get(m.rdzv_name)
        if mgr is None:
            return None
        node = (
            self.job_manager.get_node(m.node_id) if self.job_manager else None
        )
        host = node.host_addr if node else ""
        rdzv_round = mgr.join_rendezvous(
            m.node_id, m.node_rank, m.local_world_size, host_addr=host
        )
        return msgs.JoinRendezvousResponse(round=rdzv_round)

    def _get_comm_world(self, m: msgs.CommWorldRequest):
        mgr = self.rdzv_managers.get(m.rdzv_name)
        if mgr is None:
            return None
        rdzv_round, group, world, coord = mgr.get_comm_world(m.node_id)
        return msgs.CommWorldResponse(
            rdzv_round=rdzv_round,
            group=group,
            world={str(k): v for k, v in world.items()},
            coordinator=coord,
        )

    def _get_reshard_plan(self, m: msgs.ReshardPlanRequest):
        mgr = self.rdzv_managers.get(m.rdzv_name)
        if mgr is None:
            return msgs.ReshardPlanResponse()
        plan = mgr.get_reshard_plan()
        if not plan.get("version"):
            return msgs.ReshardPlanResponse()
        return msgs.ReshardPlanResponse(
            version=plan["version"],
            rdzv_round=plan["rdzv_round"],
            dp_old=plan["dp_old"],
            dp_new=plan["dp_new"],
            lost_ranks=list(plan["lost_ranks"]),
            deadline_s=plan["deadline_s"],
            reason=plan["reason"],
        )

    def _get_serving_reshard(self, m: msgs.ServingReshardRequest):
        if self.job_manager is None:
            return msgs.ServingReshardDirective()
        plan = self.job_manager.get_serving_reshard()
        if not plan.get("version"):
            return msgs.ServingReshardDirective()
        return msgs.ServingReshardDirective(
            version=plan["version"],
            victim=plan["victim"],
            survivors=list(plan["survivors"]),
            deadline_s=plan["deadline_s"],
            reason=plan["reason"],
        )

    def _get_serving_scale(self, m: msgs.ServingScaleRequest):
        if self.job_manager is None:
            return msgs.ServingScaleDirective()
        plan = self.job_manager.get_serving_scale(m.role)
        if not plan.get("version"):
            return msgs.ServingScaleDirective()
        return msgs.ServingScaleDirective(
            version=plan["version"],
            role=plan["role"],
            target=plan["target"],
            reason=plan["reason"],
        )

    def _get_num_nodes_waiting(self, m: msgs.NumNodesWaitingRequest):
        mgr = self.rdzv_managers.get(m.rdzv_name)
        n = mgr.num_nodes_waiting() if mgr else 0
        return msgs.NumNodesWaitingResponse(waiting_num=n)

    def _get_network_status(self, m: msgs.NetworkCheckStatusRequest):
        mgr = self.rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if mgr is None:
            return msgs.NetworkCheckStatusResponse()
        fault, _ = mgr.check_fault_node()
        stragglers, _ = mgr.get_stragglers()
        return msgs.NetworkCheckStatusResponse(
            normal=m.node_id not in fault,
            fault_nodes=fault,
            stragglers=stragglers,
        )

    def _get_task(self, m: msgs.TaskRequest):
        if self.task_manager is None:
            return msgs.Task()
        task = self.task_manager.get_task(m.dataset_name, m.worker_id)
        return msgs.Task(
            task_id=task.task_id,
            task_type=task.task_type,
            dataset_name=m.dataset_name,
            shard_start=task.shard.start,
            shard_end=task.shard.end,
            epoch=task.epoch,
            record_indices=list(task.shard.record_indices),
        )

    def _get_shard_ckpt(self, m: msgs.ShardCheckpointRequest):
        if self.task_manager is None:
            return msgs.ShardCheckpoint()
        return msgs.ShardCheckpoint(
            dataset_name=m.dataset_name,
            content=self.task_manager.checkpoint(m.dataset_name),
        )

    def _get_epoch(self, m: msgs.DatasetEpochRequest):
        epoch = (
            self.task_manager.get_epoch(m.dataset_name)
            if self.task_manager
            else 0
        )
        return msgs.DatasetEpochResponse(epoch=epoch)

    def _get_kv(self, m: msgs.KeyRequest):
        value = self.kv_store.get(m.key) if self.kv_store else ""
        return msgs.KeyValuePair(key=m.key, value=value)

    def _get_sync(self, m: msgs.SyncRequest):
        ok = (
            self.sync_service.sync_finished(m.sync_name)
            if self.sync_service
            else False
        )
        return msgs.SyncResponse(success=ok)

    def _get_ckpt_step(self, m: msgs.CheckpointStepRequest):
        if not self._ckpt_steps:
            return msgs.CheckpointStepResponse(step=0)
        return msgs.CheckpointStepResponse(
            step=min(self._ckpt_steps.values())
        )

    def _get_paral_config(self, m: msgs.ParallelConfigRequest):
        node = (
            self.job_manager.get_node(m.node_id) if self.job_manager else None
        )
        cfg = node.paral_config if node else {}
        out = msgs.ParallelConfig(**cfg) if cfg else msgs.ParallelConfig()
        # fold the job-level tuning directive into the per-node config
        # so one poll carries both (the tuner gates on the version PAIR)
        if self.job_manager is not None:
            plan = self.job_manager.get_tuning()
            if plan.get("version"):
                out.tuning_version = plan["version"]
                out.tuning_json = plan["plan_json"]
        return out

    def _get_tuning(self, m: msgs.TuningPlanRequest):
        if self.job_manager is None:
            return msgs.TuningPlanDirective()
        plan = self.job_manager.get_tuning()
        if not plan.get("version"):
            return msgs.TuningPlanDirective()
        return msgs.TuningPlanDirective(
            version=plan["version"],
            plan_json=plan["plan_json"],
            reason=plan["reason"],
        )

    def _get_ps_version(self, m: msgs.PsVersionRequest):
        if not self.ps_service:
            return msgs.PsVersionResponse()
        if m.version_type == "global":
            version = self.ps_service.get_global_version()
        else:
            version = self.ps_service.get_node_version(m.node_id)
        return msgs.PsVersionResponse(
            version=version,
            servers=list(self.ps_service.get_servers()),
            weights=self.ps_service.get_weights(),
        )

    def _get_running_nodes(self, m: msgs.RunningNodesRequest):
        if not self.job_manager:
            return msgs.RunningNodesResponse()
        return msgs.RunningNodesResponse(
            nodes=[
                msgs.NodeInfo(
                    id=n.id,
                    type=n.type,
                    name=n.name,
                    status=n.status,
                    host_addr=n.host_addr or "",
                    rank_index=n.rank_index,
                )
                for n in self.job_manager.running_nodes()
            ]
        )

    _GET_HANDLERS = {
        "RunningNodesRequest": _get_running_nodes,
        "PsVersionRequest": _get_ps_version,
        "HeartbeatReport": _get_heartbeat,
        "NodeRegisterRequest": _get_register,
        "JoinRendezvousRequest": _get_join_rdzv,
        "CommWorldRequest": _get_comm_world,
        "NetworkCheckStatusRequest": _get_network_status,
        "ReshardPlanRequest": _get_reshard_plan,
        "ServingReshardRequest": _get_serving_reshard,
        "ServingScaleRequest": _get_serving_scale,
        "TuningPlanRequest": _get_tuning,
        "NumNodesWaitingRequest": _get_num_nodes_waiting,
        "TaskRequest": _get_task,
        "ShardCheckpointRequest": _get_shard_ckpt,
        "DatasetEpochRequest": _get_epoch,
        "KeyRequest": _get_kv,
        "SyncRequest": _get_sync,
        "CheckpointStepRequest": _get_ckpt_step,
        "ParallelConfigRequest": _get_paral_config,
    }
