"""Node status state machine (reference: master/node/status_flow.py).

Guards against out-of-order platform events (a DELETED watch event arriving
after the pod already FAILED must not resurrect the node, etc.).
"""

from dataclasses import dataclass
from typing import Tuple

from dlrover_tpu.common.constants import NodeStatus

ALLOWED: Tuple[Tuple[str, str], ...] = (
    (NodeStatus.INITIAL, NodeStatus.PENDING),
    (NodeStatus.INITIAL, NodeStatus.RUNNING),
    (NodeStatus.INITIAL, NodeStatus.DELETED),
    (NodeStatus.PENDING, NodeStatus.RUNNING),
    (NodeStatus.PENDING, NodeStatus.FAILED),
    (NodeStatus.PENDING, NodeStatus.DELETED),
    (NodeStatus.PENDING, NodeStatus.SUCCEEDED),
    (NodeStatus.RUNNING, NodeStatus.SUCCEEDED),
    (NodeStatus.RUNNING, NodeStatus.FAILED),
    (NodeStatus.RUNNING, NodeStatus.DELETED),
    (NodeStatus.RUNNING, NodeStatus.CHECK_FAILED),
    (NodeStatus.SUCCEEDED, NodeStatus.DELETED),
    (NodeStatus.FAILED, NodeStatus.DELETED),
    (NodeStatus.CHECK_FAILED, NodeStatus.DELETED),
)


@dataclass
class NodeStateFlow:
    from_status: str
    to_status: str
    allowed: bool


def transition(from_status: str, to_status: str) -> NodeStateFlow:
    if from_status == to_status:
        return NodeStateFlow(from_status, to_status, False)
    return NodeStateFlow(
        from_status, to_status, (from_status, to_status) in ALLOWED
    )
