"""Training speed monitor (reference: monitor/speed_monitor.py:43).

Collects (timestamp, global_step) reports and derives samples/sec; provides
the straggler baseline and the goodput numerator (steps while healthy).
"""

import threading
import time
from collections import deque
from typing import Deque, Optional, Tuple

from dlrover_tpu.common.constants import DefaultValues


class SpeedMonitor:
    def __init__(self, window: int = DefaultValues.SPEED_MONITOR_WINDOW):
        self._lock = threading.Lock()
        self._records: Deque[Tuple[float, int]] = deque(maxlen=window)
        self._global_step = 0
        self._start_time = time.time()
        self._worker_num = 0
        self._init_step = 0
        self._first_report: Optional[float] = None

    def set_worker_num(self, n: int):
        with self._lock:
            self._worker_num = n

    def collect_global_step(self, step: int, timestamp: float = 0.0):
        ts = timestamp or time.time()
        with self._lock:
            if self._first_report is None:
                self._first_report = ts
                self._init_step = step
            self._global_step = step
            self._records.append((ts, step))

    @property
    def global_step(self) -> int:
        with self._lock:
            return self._global_step

    @property
    def running_speed(self) -> float:
        """steps/sec over the sliding window."""
        with self._lock:
            if len(self._records) < 2:
                return 0.0
            (t0, s0), (t1, s1) = self._records[0], self._records[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    def all_time_speed(self) -> float:
        with self._lock:
            if self._first_report is None:
                return 0.0
            dt = time.time() - self._first_report
            return (self._global_step - self._init_step) / dt if dt > 0 else 0.0

    def reset_running_speed(self):
        with self._lock:
            self._records.clear()
