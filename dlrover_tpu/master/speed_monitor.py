"""Training speed monitor (reference: monitor/speed_monitor.py:43).

Collects global-step reports and derives steps/sec; provides the
straggler baseline and the goodput numerator (steps while healthy).

Interval arithmetic runs on the master's ``time.monotonic()`` arrival
clock — worker-supplied wall timestamps cross NTP-skewed hosts and a
wall-clock step would otherwise produce negative speeds or inflated
goodput.  The worker wall timestamp is still retained per watermark for
display/correlation, it just never enters a subtraction.

Per-worker step watermarks track each reporting node's frontier; a node
whose step rate falls behind the median by ``DefaultValues
.STRAGGLER_RATIO`` is flagged onto the telemetry bus as a
:class:`~dlrover_tpu.observability.telemetry.StragglerRecord` (edge-
triggered — one record per transition into straggling, not per report).
"""

import statistics
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from dlrover_tpu.common.constants import DefaultValues
from dlrover_tpu.observability import telemetry


class SpeedMonitor:
    def __init__(
        self,
        window: int = DefaultValues.SPEED_MONITOR_WINDOW,
        straggler_ratio: float = DefaultValues.STRAGGLER_RATIO,
    ):
        self._lock = threading.Lock()
        self._records: Deque[Tuple[float, int]] = deque(maxlen=window)
        self._global_step = 0
        self._worker_num = 0
        self._init_step = 0
        self._first_report: Optional[float] = None  # monotonic
        self._straggler_ratio = straggler_ratio
        # node_id → (step, mono_arrival, wall_ts, step_rate)
        self._watermarks: Dict[int, Tuple[int, float, float, float]] = {}
        self._flagged: set = set()
        self._hub = None

    def attach_hub(self, hub) -> None:
        """Publish straggler flags onto this telemetry hub."""
        self._hub = hub

    def set_worker_num(self, n: int):
        with self._lock:
            self._worker_num = n

    def collect_global_step(
        self,
        step: int,
        timestamp: float = 0.0,
        node_id: int = -1,
        now: Optional[float] = None,
    ):
        """Ingest one step report.

        ``timestamp`` is the worker's wall clock (kept on the watermark
        only); ``now`` is the master-side monotonic arrival time,
        injectable for tests.
        """
        now = time.monotonic() if now is None else now
        flag = None
        with self._lock:
            if self._first_report is None:
                self._first_report = now
                self._init_step = step
            self._global_step = step
            self._records.append((now, step))
            if node_id >= 0:
                flag = self._update_watermark(node_id, step, now, timestamp)
        if flag is not None and self._hub is not None and self._hub.enabled:
            self._hub.publish(flag)

    def _update_watermark(self, node_id, step, now, wall_ts):
        """Lock held.  Returns a StragglerRecord on a fresh flag."""
        prev = self._watermarks.get(node_id)
        rate = prev[3] if prev else 0.0
        if prev and now > prev[1] and step > prev[0]:
            rate = (step - prev[0]) / (now - prev[1])
        self._watermarks[node_id] = (step, now, wall_ts, rate)
        rates = [w[3] for w in self._watermarks.values() if w[3] > 0]
        if len(rates) < 2 or rate <= 0:
            return None
        med = statistics.median(rates)
        if med > 0 and med / rate >= self._straggler_ratio:
            if node_id not in self._flagged:
                self._flagged.add(node_id)
                max_step = max(w[0] for w in self._watermarks.values())
                return telemetry.StragglerRecord(
                    node_id=node_id,
                    step=step,
                    max_step=max_step,
                    lag_steps=max_step - step,
                    ratio=med / rate,
                )
        else:
            self._flagged.discard(node_id)
        return None

    def worker_watermarks(self) -> Dict[int, Dict]:
        """Per-node step frontier: {node: {step, age_s, wall_ts, rate}}."""
        now = time.monotonic()
        with self._lock:
            return {
                n: {
                    "step": w[0],
                    "age_s": max(0.0, now - w[1]),
                    "wall_ts": w[2],
                    "rate": w[3],
                }
                for n, w in self._watermarks.items()
            }

    def stragglers(self) -> set:
        with self._lock:
            return set(self._flagged)

    def drop_node(self, node_id: int):
        """A node left: its stale watermark must not skew the median."""
        with self._lock:
            self._watermarks.pop(node_id, None)
            self._flagged.discard(node_id)

    @property
    def global_step(self) -> int:
        with self._lock:
            return self._global_step

    @property
    def running_speed(self) -> float:
        """steps/sec over the sliding window."""
        with self._lock:
            if len(self._records) < 2:
                return 0.0
            (t0, s0), (t1, s1) = self._records[0], self._records[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    def all_time_speed(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._first_report is None:
                return 0.0
            dt = now - self._first_report
            return (self._global_step - self._init_step) / dt if dt > 0 else 0.0

    def reset_running_speed(self):
        with self._lock:
            self._records.clear()
