"""ServingAutoScaler: the SLO-driven serving scale loop.

The serving-side sibling of :class:`~dlrover_tpu.master.auto_scaler.
JobAutoScaler` — where the trainer loop consumes the resource
optimizer's throughput plans, this loop consumes the serving tier's
OWN telemetry and closes the watchdog → ScalePlan gap that made the
PR 15 gates capture-only:

- **Signals** — merged fleet latency histograms
  (``ReplicaRouter.fleet_histograms``), per-role TTFT/TPOT windows,
  scheduler queue depth + drop counters, and PageAllocator occupancy
  (the same axes ``engine.observability_snapshot()`` freezes into a
  capture artifact). Histograms are LIFETIME counters, so every
  evaluation judges the delta window since the previous one
  (:func:`~dlrover_tpu.observability.histogram.histogram_delta`) —
  minutes of healthy history must not mask a fresh breach, nor a
  fresh recovery.
- **Attribution** — a breach names a ROLE before it names a size: a
  TTFT breach points at the prefill pool, an e2e/TPOT breach at the
  decode pool, out-of-pages at the most-occupied pool (reusing
  ``healthcheck._slow_role``, the replay-side version of the same
  judgement). Prefill and decode therefore scale independently,
  which is the whole reason serving nodes register role-tagged.
- **Decisions** — edge-triggered with hysteresis (a breach latches
  until the window drops below ``clear_frac`` × target), per-role
  cooldown, and min/max bounds, like the trainer loop. Scale-out
  attaches a warm replica to the live router
  (``ReplicaRouter.add_replica``); scale-in drains the least-loaded
  victim over the live-migration wire (``remove_replica`` — zero
  lost, zero re-prefilled) after ``shrink_after_clear`` consecutive
  clear windows.
- **Versioning** — every decision flows through
  ``JobManager.plan_serving_scale`` (in-process master) or
  ``MasterClient.report_serving_scale`` (remote), falling back to a
  local counter, and is published as a
  :class:`~dlrover_tpu.observability.telemetry.ScaleDecisionRecord`
  so the healthcheck can replay WHY the fleet is its current size.

``evaluate(signals=...)`` is a pure decision function — tests drive
it with synthetic signal dicts and a fake clock; only
``collect()``/``apply()`` touch live replicas.
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.observability.healthcheck import _slow_role
from dlrover_tpu.observability.histogram import (
    histogram_delta,
    merge_histograms,
)
from dlrover_tpu.observability.telemetry import ScaleDecisionRecord

logger = get_logger(__name__)

# breach signals in detection priority order: page exhaustion starves
# everything downstream of it, so it outranks the latency symptoms it
# causes; queue depth is the earliest (cheapest) overload indicator but
# the least specific, so it ranks last
SCALE_SIGNALS = (
    "out_of_pages",
    "ttft_regression",
    "slo_breach",
    "tpot_breach",
    "queue_depth",
)


@dataclass
class ServingScalerConfig:
    """Targets + control knobs for one serving fleet's scale loop.

    A target of 0 disables that signal, mirroring
    ``ServingWatchdogConfig``. ``role_min``/``role_max`` override the
    scalar bounds per role so a disaggregated fleet can pin, say, the
    decode pool while the prefill pool breathes."""

    p99_target_ms: float = 0.0
    ttft_target_ms: float = 0.0
    tpot_target_ms: float = 0.0
    # queue depth (per role, summed over the pool) above this is an
    # overload signal even before latency percentiles move
    queue_depth_high: int = 0
    # fraction of KV pages in use (worst replica of the pool)
    occupancy_high: float = 0.92
    # hysteresis: a latched breach clears only when the window value
    # drops below clear_frac × target — never at target itself, or an
    # oscillating trace flaps the gate every evaluation
    clear_frac: float = 0.8
    # at most one actionable decision per role per cooldown window
    cooldown_s: float = 10.0
    min_replicas: int = 1
    max_replicas: int = 2
    role_min: Dict[str, int] = field(default_factory=dict)
    role_max: Dict[str, int] = field(default_factory=dict)
    # judging percentiles on a handful of window samples is noise
    min_window_n: int = 8
    # consecutive clear evaluations before a scale-in is considered
    shrink_after_clear: int = 3
    interval_s: float = 0.25


class ServingAutoScaler:
    """Close the telemetry → ScalePlan loop for one serving fleet.

    ``provision_fn(role) -> ServingReplica`` supplies a warm (started)
    replica on scale-out — in production a launcher that boots a host
    and waits for its ``refresh_discovery`` registration, in drills a
    factory over pre-warmed spares. Without one, scale-out decisions
    are recorded but not applied (signal-only mode)."""

    def __init__(
        self,
        router,
        config: Optional[ServingScalerConfig] = None,
        *,
        provision_fn: Optional[Callable] = None,
        decommission_fn: Optional[Callable] = None,
        job_manager=None,
        master_client=None,
        watchdog=None,
        hub=None,
        node_id: int = 0,
        clock=time.monotonic,
    ):
        self.router = router
        self.cfg = config or ServingScalerConfig()
        self.provision_fn = provision_fn
        self.decommission_fn = decommission_fn
        self.job_manager = job_manager
        self.master_client = master_client
        self.hub = hub
        self.node_id = node_id
        self._clock = clock
        self._lock = threading.Lock()
        # lifetime-histogram snapshots per role, for delta windows
        self._prev_hists: Dict[str, Dict] = {}
        self._prev_drops: Dict[str, int] = {}
        # gate edges pushed by the watchdog subscription; drained into
        # the next evaluation so a breach the watchdog saw first still
        # starts the reaction clock at ITS edge, not our next tick
        self._gate_state: Dict[str, bool] = {}
        # first-seen time of each active breach signal (reaction clock)
        self._breach_t: Dict[str, float] = {}
        # per-role latched breach signal (hysteresis) + bookkeeping
        self._latched: Dict[str, str] = {}
        self._clear_streak: Dict[str, int] = {}
        self._last_decision_t: Dict[str, float] = {}
        self._local_version = 0
        self.decisions: List[ScaleDecisionRecord] = []
        self.last_reaction_s = 0.0   # breach edge → decision applied
        self.last_restore_s = 0.0    # breach edge → window back in SLO
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if watchdog is not None:
            watchdog.subscribe(self._on_gate)

    # ---- watchdog subscription -------------------------------------------

    def _on_gate(self, kind: str, breaching: bool, rec) -> None:
        """Gate-edge hook (``ServingWatchdog.subscribe``): latch the
        edge and stamp the breach start so reaction time is measured
        from the moment the SLO broke, not the next evaluation tick."""
        with self._lock:
            self._gate_state[kind] = breaching
            if breaching:
                self._breach_t.setdefault(kind, self._clock())

    # ---- signal collection -----------------------------------------------

    def _pools(self) -> Dict[str, List]:
        if self.router.disaggregated:
            return {
                "prefill": self.router.live_replicas("prefill"),
                "decode": self.router.live_replicas("decode"),
            }
        return {"unified": self.router.live_replicas()}

    def collect(self) -> Dict:
        """One evaluation's signal snapshot: per-role WINDOW latency
        percentiles (delta since the previous collect — membership
        changes between snapshots clamp at zero, never go negative),
        pool queue depth, new drops, and worst-replica page occupancy."""
        roles: Dict[str, Dict] = {}
        for role, reps in self._pools().items():
            if not reps:
                continue
            per = [r.server.scheduler.histograms() for r in reps]
            cur = {}
            for k in per[0]:
                merged = merge_histograms(p[k] for p in per)
                if merged is not None:
                    cur[k] = merged
            prev = self._prev_hists.get(role, {})
            win = {
                k: histogram_delta(h, prev.get(k)) for k, h in cur.items()
            }
            self._prev_hists[role] = cur
            occ = 0.0
            for r in reps:
                eng = r.server.engine
                n_pages = max(1, eng.geom.n_pages)
                occ = max(occ, 1.0 - eng.alloc.free_pages / n_pages)
            drops = sum(
                r.server.scheduler.shed
                + r.server.scheduler.rejected
                + r.server.scheduler.timed_out
                + r.server.scheduler.poisoned
                for r in reps
            )
            new_drops = drops - self._prev_drops.get(role, drops)
            self._prev_drops[role] = drops
            e2e = win.get("e2e")
            roles[role] = {
                "n": e2e.n if e2e is not None else 0,
                "p99_ms": e2e.percentile(99.0) if e2e is not None else 0.0,
                "ttft_p99_ms": (
                    win["ttft"].percentile(99.0) if "ttft" in win else 0.0
                ),
                "tpot_p99_ms": (
                    win["tpot"].percentile(99.0) if "tpot" in win else 0.0
                ),
                "queue_depth": sum(
                    r.server.scheduler.queue_depth() for r in reps
                ),
                "new_drops": new_drops,
                "occupancy": occ,
                "n_replicas": len(reps),
            }
        return {"roles": roles}

    # ---- pure decision logic ---------------------------------------------

    def _bounds(self, role: str):
        lo = self.cfg.role_min.get(role, self.cfg.min_replicas)
        hi = self.cfg.role_max.get(role, self.cfg.max_replicas)
        return lo, hi

    def _signal_reading(self, info: Dict, signal: str):
        """(value, target) of ``signal`` in one role's window, or None
        when the signal is disabled or the window is too thin to judge
        latency percentiles (depth/occupancy need no sample floor)."""
        cfg = self.cfg
        enough = info.get("n", 0) >= cfg.min_window_n
        if signal == "out_of_pages":
            return (info.get("occupancy", 0.0), cfg.occupancy_high)
        if signal == "queue_depth" and cfg.queue_depth_high > 0:
            return (
                float(info.get("queue_depth", 0)),
                float(cfg.queue_depth_high),
            )
        if signal == "ttft_regression" and cfg.ttft_target_ms > 0 and enough:
            return (info.get("ttft_p99_ms", 0.0), cfg.ttft_target_ms)
        if signal == "slo_breach" and cfg.p99_target_ms > 0 and enough:
            return (info.get("p99_ms", 0.0), cfg.p99_target_ms)
        if signal == "tpot_breach" and cfg.tpot_target_ms > 0 and enough:
            return (info.get("tpot_p99_ms", 0.0), cfg.tpot_target_ms)
        return None

    def _attribute(self, roles: Dict, signal: str) -> str:
        """Which pool a breach names. Resource/backlog signals point at
        the pressured pool directly; latency signals reuse the
        healthcheck's role attribution (TTFT → worst-TTFT role, pace →
        worst-pace role). Single-pool fleets have nothing to choose."""
        if len(roles) < 2:
            return next(iter(roles), "unified")
        if signal == "out_of_pages":
            return max(roles, key=lambda r: roles[r].get("occupancy", 0.0))
        if signal == "queue_depth":
            return max(roles, key=lambda r: roles[r].get("queue_depth", 0))
        kind = (
            "ttft_regression" if signal == "ttft_regression" else "slo"
        )
        return _slow_role({"roles": roles}, kind) or "unified"

    def evaluate(
        self, signals: Optional[Dict] = None, now: Optional[float] = None
    ) -> Optional[Dict]:
        """One control-loop tick, pure given ``signals``: detect a
        breach (or a hysteresis-clear), attribute it to a role, and
        return the decision dict — or None when nothing to do. Tests
        drive this with synthetic signal dicts and a fake clock;
        ``step()`` feeds it live ``collect()`` output and applies the
        result."""
        now = self._clock() if now is None else now
        if signals is None:
            signals = self.collect()
        roles = signals.get("roles") or {}
        with self._lock:
            gate_breach = [k for k, v in self._gate_state.items() if v]
        # -- detect: first breaching signal in priority order
        for signal in SCALE_SIGNALS:
            worst = None
            for role, info in roles.items():
                reading = self._signal_reading(info, signal)
                if reading is None:
                    continue
                value, target = reading
                if value > target and (
                    worst is None or value / target > worst[0] / worst[1]
                ):
                    worst = (value, target)
            if worst is None:
                continue
            value, target = worst
            role = self._attribute(roles, signal)
            with self._lock:
                self._breach_t.setdefault(signal, now)
                breach_start = self._breach_t[signal]
            self._latched[role] = signal
            self._clear_streak[role] = 0
            n_live = roles.get(role, {}).get("n_replicas", 0)
            lo, hi = self._bounds(role)
            last = self._last_decision_t.get(role)
            if n_live >= hi:
                return None  # already at the ceiling: breach stays latched
            if last is not None and now - last < self.cfg.cooldown_s:
                return None  # in cooldown: at most one decision per window
            return {
                "direction": "out",
                "role": role,
                "signal": signal,
                "value": value,
                "target": target,
                "n_before": n_live,
                "n_after": n_live + 1,
                "reaction_s": max(0.0, now - breach_start),
                "reason": f"{signal} {value:g}>{target:g}",
            }
        # -- no breach: run the clear / shrink ladder per latched role
        for role, signal in list(self._latched.items()):
            info = roles.get(role)
            if info is None:
                continue
            reading = self._signal_reading(info, signal)
            if reading is not None:
                value, target = reading
                if value > target * self.cfg.clear_frac:
                    continue  # inside the hysteresis band: stay latched
            else:
                value, target = 0.0, 0.0
            del self._latched[role]
            with self._lock:
                breach_start = self._breach_t.pop(signal, now)
                self._gate_state.pop(signal, None)
            self.last_restore_s = max(0.0, now - breach_start)
            return {
                "direction": "",
                "role": role,
                "signal": "clear",
                "value": value,
                "target": target,
                "n_before": info.get("n_replicas", 0),
                "n_after": info.get("n_replicas", 0),
                "reaction_s": self.last_restore_s,
                "reason": f"{signal} cleared",
            }
        if gate_breach:
            return None  # watchdog still holds a gate open: never shrink
        for role, info in roles.items():
            if role in self._latched:
                continue
            self._clear_streak[role] = self._clear_streak.get(role, 0) + 1
            lo, hi = self._bounds(role)
            n_live = info.get("n_replicas", 0)
            if (
                self._clear_streak[role] < self.cfg.shrink_after_clear
                or n_live <= lo
            ):
                continue
            last = self._last_decision_t.get(role)
            if last is not None and now - last < self.cfg.cooldown_s:
                continue
            self._clear_streak[role] = 0
            return {
                "direction": "in",
                "role": role,
                "signal": "planned",
                "value": 0.0,
                "target": 0.0,
                "n_before": n_live,
                "n_after": n_live - 1,
                "reaction_s": 0.0,
                "reason": (
                    f"{self.cfg.shrink_after_clear} clear windows, "
                    f"pool>{lo}"
                ),
            }
        return None

    # ---- apply ------------------------------------------------------------

    def _version(self, d: Dict) -> int:
        """Version the decision through whichever master plane is
        bound; a standalone fleet versions locally (version stays 0 in
        the record, matching the reshard directive convention)."""
        if self.job_manager is not None:
            return self.job_manager.plan_serving_scale(
                d["role"], d["n_after"], reason=d["reason"]
            )
        if self.master_client is not None:
            self.master_client.report_serving_scale(
                d["role"], d["direction"], d["n_before"], d["n_after"],
                signal=d["signal"], reason=d["reason"],
            )
            return self.master_client.get_serving_scale(
                d["role"]
            ).version
        self._local_version += 1
        return 0

    def _pick_victim(self, role: str):
        """Least-loaded live member of the pool: fewest occupied slots,
        queue depth as tiebreak — evacuating it moves the fewest pages."""
        pool = self.router.live_replicas(
            None if role == "unified" else role
        )
        if len(pool) < 2:
            return None
        return min(
            pool,
            key=lambda r: (
                sum(s is not None for s in r.server.engine.slots),
                r.server.scheduler.queue_depth(),
            ),
        )

    def apply(self, decision: Dict) -> Optional[ScaleDecisionRecord]:
        """Execute one ``evaluate()`` decision against the live fleet
        and publish its ScaleDecisionRecord. Clear decisions are
        telemetry-only; out/in mutate the router."""
        role = decision["role"]
        replica_name = ""
        now = self._clock()
        if decision["direction"] == "out":
            if self.provision_fn is None:
                logger.warning(
                    "scale-out wanted for %s pool but no provision_fn "
                    "bound — decision recorded, fleet unchanged", role,
                )
            else:
                rep = self.provision_fn(role)
                self.router.add_replica(rep)
                replica_name = rep.name
            self._last_decision_t[role] = now
            self.last_reaction_s = decision["reaction_s"]
        elif decision["direction"] == "in":
            victim = self._pick_victim(role)
            if victim is None:
                return None  # pool shrank under us: nothing to drain
            self.router.remove_replica(victim, reason="autoscale")
            if self.decommission_fn is not None:
                self.decommission_fn(victim)
            replica_name = victim.name
            self._last_decision_t[role] = now
        version = (
            self._version(decision) if decision["direction"] else 0
        )
        rec = ScaleDecisionRecord(
            role=role,
            direction=decision["direction"],
            signal=decision["signal"],
            value=float(decision["value"]),
            target=float(decision["target"]),
            n_before=int(decision["n_before"]),
            n_after=int(decision["n_after"]),
            version=version,
            reaction_s=float(decision["reaction_s"]),
            replica=replica_name,
            reason=decision["reason"],
            ts=time.time(),
        )
        self.decisions.append(rec)
        if self.hub is not None and getattr(self.hub, "enabled", True):
            self.hub.publish(rec)
        logger.info(
            "serving autoscale v%d: %s %s pool %d→%d (%s)",
            version, decision["direction"] or "clear", role,
            decision["n_before"], decision["n_after"], decision["reason"],
        )
        return rec

    def step(self) -> Optional[ScaleDecisionRecord]:
        decision = self.evaluate()
        if decision is None:
            return None
        return self.apply(decision)

    # ---- background loop ---------------------------------------------------

    def start(self) -> "ServingAutoScaler":
        self._thread = threading.Thread(
            target=self._loop, name="serving-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must outlive a tick
                logger.exception("serving autoscale tick failed")
