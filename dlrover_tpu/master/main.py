"""Standalone job-master entry point.

Reference: dlrover/python/master/main.py:43. Run one per job:

    python -m dlrover_tpu.master.main --port 7000 --num-workers 4
"""

import argparse
import sys
from typing import List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.master import DistributedJobMaster

logger = get_logger(__name__)


def parse_master_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="dlrover-tpu-master")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-workers", type=int, default=1)
    p.add_argument("--max-workers", type=int, default=0)
    p.add_argument("--node-unit", type=int, default=1)
    p.add_argument("--job-name", default="job")
    p.add_argument("--job-kind", default="")
    p.add_argument(
        "--optimize-mode",
        default="single-job",
        choices=["single-job", "cluster"],
        help="cluster = resource plans from a shared dlrover-tpu-brain",
    )
    p.add_argument(
        "--brain-addr",
        default="",
        help="host:port of the brain service (optimize-mode=cluster)",
    )
    return p.parse_args(argv)


def run(args: argparse.Namespace) -> str:
    master = DistributedJobMaster(
        port=args.port,
        num_workers=args.num_workers,
        max_workers=args.max_workers or args.num_workers,
        node_unit=args.node_unit,
        optimize_mode=args.optimize_mode,
        brain_addr=args.brain_addr,
        job_name=args.job_name,
        job_kind=args.job_kind,
    )
    master.prepare()
    # print the bound address for launchers/operators to scrape
    print(f"DLROVER_TPU_MASTER_ADDR={master.addr}", flush=True)
    return master.run()


def main(argv: Optional[List[str]] = None) -> int:
    reason = run(parse_master_args(argv))
    return 0 if reason == "succeeded" else 1


if __name__ == "__main__":
    sys.exit(main())
