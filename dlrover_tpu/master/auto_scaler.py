"""JobAutoScaler: periodic auto-scaling loop.

Reference: dlrover/python/master/node/job_auto_scaler.py:40
(AllreduceTrainingAutoScaler._periodic_adjust_worker:288). Consumes the
resource optimizer's plans, pushes ScalePlans to the scaler, and updates
the rendezvous bounds so the next re-mesh admits the new world.
"""

import threading
from typing import Optional

from dlrover_tpu.common.constants import DefaultValues
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.node_manager import JobManager, ScalePlan, Scaler
from dlrover_tpu.master.resource_optimizer import (
    LocalHeuristicOptimizer,
    ResourceOptimizer,
)
from dlrover_tpu.master.speed_monitor import SpeedMonitor

logger = get_logger(__name__)


class JobAutoScaler:
    def __init__(
        self,
        job_manager: JobManager,
        speed_monitor: SpeedMonitor,
        scaler: Scaler,
        rdzv_managers=None,
        optimizer: Optional[ResourceOptimizer] = None,
        interval_s: float = DefaultValues.AUTOSCALE_INTERVAL_S,
        min_workers: int = 1,
        max_workers: int = 1,
        node_unit: int = 1,
        ps_service=None,
        ps_scale_fn=None,
    ):
        self.job_manager = job_manager
        self.speed_monitor = speed_monitor
        self.scaler = scaler
        self.rdzv_managers = rdzv_managers or {}
        # sparse-tier consumers for Brain ps hints: weight rebalance goes
        # to the version service; count changes go to the platform hook
        # (fn(target_num) — the sparse tier's analog of SliceScaler)
        self.ps_service = ps_service
        self.ps_scale_fn = ps_scale_fn
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.optimizer = optimizer or LocalHeuristicOptimizer(
            min_workers=min_workers,
            max_workers=max_workers,
            node_unit=node_unit,
        )
        self.interval_s = interval_s
        # grace before treating unregistered nodes as unplaceable — newly
        # requested hosts legitimately take minutes to schedule and join
        self.pending_grace_s = DefaultValues.SECONDS_TO_WAIT_PENDING_POD
        self._last_scale_time = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="auto-scaler", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.adjust_once()
            except Exception:  # noqa: BLE001
                logger.exception("auto-scale iteration failed")

    def adjust_once(self):
        import time

        running = self.job_manager.running_nodes()
        pending = max(0, self.job_manager.worker_num - len(running))
        # while inside the grace window after a scale event, booting nodes
        # are not "unplaceable" — don't flap back down
        in_grace = (
            time.time() - self._last_scale_time < self.pending_grace_s
        )
        stats = {
            "worker_num": self.job_manager.worker_num,
            "speed": self.speed_monitor.running_speed,
            "pending_nodes": 0 if in_grace else pending,
        }
        if in_grace and pending > 0:
            return  # wait for the last scale event to settle
        plan = self.optimizer.generate_plan("running", stats)
        if plan.empty():
            return
        self.execute_plan(plan)

    def execute_plan(self, plan):
        import time

        # sparse-tier hints execute regardless of the worker target:
        # a hot-shard rebalance (Brain job_hot_ps_resource) installs HRW
        # weights and bumps the sparse cluster version so workers
        # re-partition with bounded migration
        ps_hints = plan.node_resources.get("ps", {})
        if self.ps_service is not None and "weights" in ps_hints:
            self.ps_service.set_weights(ps_hints["weights"])
        if "num" in ps_hints:
            if self.ps_scale_fn is not None:
                self.ps_scale_fn(int(ps_hints["num"]))
            else:
                logger.warning(
                    "plan requests %d sparse hosts but no ps_scale_fn "
                    "is bound — sparse tier not scaled",
                    ps_hints["num"],
                )

        target = plan.worker_num
        if target is None:
            return
        # clamp to the JOB's declared elastic range: a cluster-shared
        # optimizer (BrainClient) was not constructed with this job's
        # min/max the way the local heuristic is, and its plan must not
        # scale past what the job asked for
        clamped = max(self.min_workers, min(self.max_workers, target))
        if clamped != target:
            logger.info(
                "auto-scale: plan wants %d workers, clamped to the "
                "job's [%d, %d] range → %d",
                target,
                self.min_workers,
                self.max_workers,
                clamped,
            )
            target = clamped
        logger.info(
            "auto-scale: %d → %d workers", self.job_manager.worker_num, target
        )
        self.job_manager.set_worker_num(target)
        self._last_scale_time = time.time()
        for mgr in self.rdzv_managers.values():
            mgr.update_rdzv_params(
                min_nodes=min(target, mgr._min_nodes or target),
                max_nodes=target,
            )
        sp = ScalePlan()
        sp.worker_num = target
        self.scaler.scale(sp)
