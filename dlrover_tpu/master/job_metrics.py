"""Job metrics collection + export.

Reference: dlrover/python/master/stats/ (JobMetricCollector
job_collector.py:185, reporter.py, training_metrics.py) and the
xpu_timer Prometheus export. Collects model/runtime/speed records and
serves them as a Prometheus text endpoint + JSON dump — the master-side
observability surface.
"""

import json
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Deque, Dict, Optional


@dataclass
class RuntimeRecord:
    timestamp: float
    global_step: int
    speed_steps_per_s: float
    worker_num: int
    cpu_percent_avg: float = 0.0
    hbm_used_mb_avg: float = 0.0


@dataclass
class JobMeta:
    job_name: str = ""
    model_name: str = ""
    num_params: int = 0
    flops_per_token: float = 0.0
    global_batch_size: int = 0
    seq_len: int = 0
    strategy_json: str = ""


class GoodputTracker:
    """Training goodput = productive wall-time / total wall-time.

    The reference's headline fault-tolerance metric (GLM-65B goodput
    69% → 95%, README.md:57-58; flash-ckpt wasted-time reduction,
    docs/blogs/flash_checkpoint.md:38-41). The master marks the job
    STALLED from startup and from every node failure / hang kick until
    the next global-step report arrives — so rendezvous, restart,
    restore, and recompilation spans all land in lost time.

    Interval arithmetic defaults to ``time.monotonic()`` so an NTP
    step on the master can never inflate (or un-count) lost time.  The
    one wall-clock comparison — ``report_ts`` (a WORKER's wall clock)
    vs the stall detection time — keeps a separate wall-clock guard,
    because cross-host ordering is only expressible in wall time.
    Tests inject coherent ``now`` floats for both clocks.
    """

    def __init__(self, now: Optional[float] = None):
        self._lock = threading.Lock()
        self._start = now if now is not None else time.monotonic()
        self._stalled_since: Optional[float] = self._start
        self._stall_guard_ts: float = (
            now if now is not None else time.time()
        )
        self._stall_step: Optional[int] = None
        self._last_close: float = self._start
        self._lost = 0.0
        self._completed = False

    def mark_stalled(
        self,
        now: Optional[float] = None,
        at_step: Optional[int] = None,
        accounted_from: Optional[float] = None,
    ):
        """``at_step``: the global step when the stall began — a later
        step report only closes the stall once training ADVANCES past it
        (an in-flight report from a surviving worker, processed moments
        after a node died, must not mark the whole recovery productive).

        ``accounted_from``: backdated start for LOST-TIME accounting
        (hang detection learns of a stall only after its idle window) —
        clamped to the last stall close so no second is charged twice.
        The report-timestamp guard still uses ``now`` (detection time):
        reports taken inside the idle window prove nothing either way,
        but their steps cannot advance past ``at_step`` while hung.
        """
        with self._lock:
            if self._completed:
                return  # training finished — see mark_completed
            if self._stalled_since is None:
                ts = now if now is not None else time.monotonic()
                acct = accounted_from if accounted_from is not None else ts
                self._stalled_since = max(acct, self._last_close)
                # wall-clock guard for worker-reported timestamps; a
                # single injected ``now`` serves both clocks in tests
                self._stall_guard_ts = (
                    now if now is not None else time.time()
                )
                self._stall_step = at_step

    def mark_productive(
        self,
        now: Optional[float] = None,
        step: Optional[int] = None,
        report_ts: Optional[float] = None,
    ):
        """``report_ts``: when the step was actually taken (worker-side
        timestamp). A report generated BEFORE the stall opened is
        in-flight state from the pre-failure world — it proves nothing
        about recovery, whatever its step number (a surviving rank can
        race the failure with a step above the master's last-seen one).
        Clock skew between hosts shifts this boundary by the skew; the
        step guard below is the skew-free backstop."""
        with self._lock:
            if self._stalled_since is None:
                return
            if report_ts is not None and report_ts <= self._stall_guard_ts:
                return  # sent before the stall was detected — in-flight
            if (
                step is not None
                and self._stall_step is not None
                and step <= self._stall_step
            ):
                return  # stale report from before/at the stall point
            ts = now if now is not None else time.monotonic()
            self._lost += max(0.0, ts - self._stalled_since)
            self._stalled_since = None
            self._stall_step = None
            self._last_close = ts

    def mark_completed(self, now: Optional[float] = None):
        """A worker ran to its final training step: the job's training
        objective is reached, so there is no productive time left to
        lose. Any open stall closes here (charged up to completion) and
        later ``mark_stalled`` calls become no-ops — otherwise a failure
        *detected* after the job finished (a heartbeat timeout racing
        teardown: the dead node's stall can never be closed by a step
        report, since no step will ever advance past the final one)
        would accrue lost time forever."""
        with self._lock:
            if self._stalled_since is not None:
                ts = now if now is not None else time.monotonic()
                self._lost += max(0.0, ts - self._stalled_since)
                self._stalled_since = None
                self._stall_step = None
                self._last_close = ts
            self._completed = True

    def lost_seconds(self, now: Optional[float] = None) -> float:
        with self._lock:
            ts = now if now is not None else time.monotonic()
            lost = self._lost
            if self._stalled_since is not None:
                lost += max(0.0, ts - self._stalled_since)
            return lost

    def goodput(self, now: Optional[float] = None) -> float:
        ts = now if now is not None else time.monotonic()
        wall = ts - self._start
        if wall <= 0:
            return 1.0
        return max(0.0, 1.0 - self.lost_seconds(ts) / wall)

    def wall_seconds(self, now: Optional[float] = None) -> float:
        ts = now if now is not None else time.monotonic()
        return max(0.0, ts - self._start)


class JobMetricCollector:
    def __init__(self, max_records: int = 4096):
        self._lock = threading.Lock()
        self.meta = JobMeta()
        self.goodput_tracker: Optional[GoodputTracker] = None
        self.records: Deque[RuntimeRecord] = deque(maxlen=max_records)
        self.counters: Dict[str, float] = {
            "node_failures_total": 0,
            "worker_restarts_total": 0,
            "rdzv_rounds_total": 0,
            "ckpt_commits_total": 0,
        }
        # free-form gauges set by the telemetry bus (MetricsSink): plan
        # numbers, overlap drift, failover phase seconds, HBM watermark
        self.gauges: Dict[str, float] = {}

    def set_job_meta(self, **kw):
        with self._lock:
            for k, v in kw.items():
                if hasattr(self.meta, k):
                    setattr(self.meta, k, v)

    def collect_runtime(
        self,
        global_step: int,
        speed: float,
        worker_num: int,
        cpu_percent_avg: float = 0.0,
        hbm_used_mb_avg: float = 0.0,
    ):
        with self._lock:
            self.records.append(
                RuntimeRecord(
                    timestamp=time.time(),
                    global_step=global_step,
                    speed_steps_per_s=speed,
                    worker_num=worker_num,
                    cpu_percent_avg=cpu_percent_avg,
                    hbm_used_mb_avg=hbm_used_mb_avg,
                )
            )

    def inc(self, counter: str, delta: float = 1.0):
        with self._lock:
            self.counters[counter] = self.counters.get(counter, 0) + delta

    def set_gauge(self, name: str, value: float):
        with self._lock:
            self.gauges[name] = float(value)

    # ---- export ----------------------------------------------------------

    def _goodput(self) -> Optional[float]:
        if self.goodput_tracker is None:
            return None
        return self.goodput_tracker.goodput()

    def to_json(self) -> str:
        gp = self._goodput()
        # raw lost/wall terms let a consumer compute goodput over a
        # WINDOW (two samples), not just since master start — the fault
        # drill regression-gates windowed goodput this way
        tracker = self.goodput_tracker
        lost = tracker.lost_seconds() if tracker else None
        wall = tracker.wall_seconds() if tracker else None
        with self._lock:
            return json.dumps(
                {
                    "meta": asdict(self.meta),
                    "counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "goodput": gp,
                    "goodput_lost_seconds": lost,
                    "goodput_wall_seconds": wall,
                    "records": [asdict(r) for r in list(self.records)[-100:]],
                }
            )

    def prometheus_text(self) -> str:
        """Prometheus exposition format (xpu_timer-style export surface)."""
        gp = self._goodput()
        with self._lock:
            lines = []
            for name, value in self.counters.items():
                lines.append(f"# TYPE dlrover_tpu_{name} counter")
                lines.append(f"dlrover_tpu_{name} {value}")
            for name, value in sorted(self.gauges.items()):
                lines.append(f"# TYPE dlrover_tpu_{name} gauge")
                lines.append(f"dlrover_tpu_{name} {value}")
            if gp is not None:
                lines.append("# TYPE dlrover_tpu_goodput gauge")
                lines.append(f"dlrover_tpu_goodput {gp}")
            if self.records:
                last = self.records[-1]
                gauges = {
                    "global_step": last.global_step,
                    "speed_steps_per_second": last.speed_steps_per_s,
                    "worker_num": last.worker_num,
                    "hbm_used_mb_avg": last.hbm_used_mb_avg,
                }
                for name, value in gauges.items():
                    lines.append(f"# TYPE dlrover_tpu_{name} gauge")
                    lines.append(f"dlrover_tpu_{name} {value}")
            return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Tiny /metrics + /json endpoint (Prometheus scrape target).

    The socket binds in ``start()`` (not __init__) so constructing a master
    that never runs doesn't hold a port, and ``stop()`` before ``start()``
    is a safe no-op.
    """

    def __init__(self, collector: JobMetricCollector, port: int = 0):
        self._collector = collector
        self._requested_port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.port = 0

    def start(self):
        import http.server

        collector_ref = self._collector

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.startswith("/metrics"):
                    body = collector_ref.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/json"):
                    body = collector_ref.to_json().encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence request logging
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("", self._requested_port), Handler
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http", daemon=True
        )
        self._thread.start()

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
