"""Elastic sparse-tier (PS) cluster-version bookkeeping.

Reference: dlrover/python/master/elastic_training/elastic_ps.py:18
(ElasticPsService) + agent side elastic_agent/tensorflow/elastic_ps.py —
the master keeps a monotonically increasing "cluster version" for the
parameter-server set; when PS membership changes (scale-out/in,
migration), the version bumps and workers rebuild their sessions.

TPU framing: the "PS set" is the group of hosts serving sparse embedding
shards (the C++ KV tier, sparse/kv_table.py). On membership change the
master bumps the version; workers poll it and re-partition their
key→host assignment with ``sparse.partition`` (rendezvous hashing, so
only keys owned by the changed hosts migrate).
"""

import threading
from typing import Dict, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


class ElasticPsService:
    def __init__(self):
        self._lock = threading.Lock()
        self._global_version = 0
        # node_id -> restored/local version (reference keeps per-worker
        # versions so late joiners can detect they are behind)
        self._node_versions: Dict[int, int] = {}
        self._servers: List[str] = []
        # per-server HRW weights (Brain hot-shard rebalance); workers
        # pass them to sparse.partition.assign_servers
        self._weights: Dict[str, float] = {}

    # ---- versions (reference API surface) --------------------------------

    def get_global_version(self) -> int:
        with self._lock:
            return self._global_version

    def bump_global_version(self) -> int:
        with self._lock:
            self._global_version += 1
            logger.info(
                "sparse cluster version → %d", self._global_version
            )
            return self._global_version

    def get_node_version(self, node_id: int) -> int:
        with self._lock:
            return self._node_versions.get(node_id, 0)

    def set_node_version(self, node_id: int, version: int):
        with self._lock:
            self._node_versions[node_id] = version

    # ---- server-set bookkeeping ------------------------------------------

    def get_servers(self) -> List[str]:
        with self._lock:
            return list(self._servers)

    def set_servers(self, servers: List[str]) -> int:
        """Replace the sparse-serving host set; bumps the version when
        membership actually changed."""
        with self._lock:
            if servers == self._servers:
                return self._global_version
            self._servers = list(servers)
            self._global_version += 1
            logger.info(
                "sparse server set changed (%d hosts) → version %d",
                len(servers),
                self._global_version,
            )
            return self._global_version

    def add_server(self, name: str) -> int:
        """Atomically add one server (idempotent). Returns the version.

        The lifecycle callback runs on the servicer's thread pool —
        concurrent registrations doing get_servers/set_servers would
        lose each other's writes; membership edits must happen under
        THIS lock."""
        with self._lock:
            if name in self._servers:
                return self._global_version
            self._servers = sorted([*self._servers, name])
            self._global_version += 1
            logger.info(
                "sparse server %s joined (%d hosts) → version %d",
                name, len(self._servers), self._global_version,
            )
            return self._global_version

    def remove_server(self, name: str) -> int:
        """Atomically remove one server (idempotent)."""
        with self._lock:
            if name not in self._servers:
                return self._global_version
            self._servers = [s for s in self._servers if s != name]
            self._global_version += 1
            logger.info(
                "sparse server %s left (%d hosts) → version %d",
                name, len(self._servers), self._global_version,
            )
            return self._global_version

    # ---- HRW weights (Brain hot-shard rebalance consumer) ----------------

    def get_weights(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._weights)

    def set_weights(self, weights: Optional[Dict[str, float]]) -> int:
        """Install rebalance weights from a Brain plan
        (node_resources['ps']['weights']); bumps the version so workers
        re-partition (sparse.partition.assign_servers consumes them —
        changing one server's weight only migrates that server's keys)."""
        weights = dict(weights or {})
        with self._lock:
            if weights == self._weights:
                return self._global_version
            self._weights = weights
            self._global_version += 1
            logger.info(
                "sparse HRW weights updated (%d entries) → version %d",
                len(weights),
                self._global_version,
            )
            return self._global_version


class PsClusterCallback:
    """Node-lifecycle → sparse server set: the master-side orchestration
    of PS elasticity (reference: dlrover node/ps.py scale-in/out plans —
    there the manager edits TF_CONFIG cluster specs; here membership IS
    the versioned HRW ring workers re-route from).

    Register on the JobManager's event-callback registry: PS-typed node
    starts join the server set, failures/deletions leave it; each
    membership change bumps the cluster version, which trainers observe
    via get_ps_version → sparse.server.sync_with_master → bounded key
    migration. Duck-typed to master/event_callback.NodeEventCallback.
    """

    def __init__(self, ps_service: ElasticPsService):
        self._ps = ps_service

    def _is_ps(self, node) -> bool:
        from dlrover_tpu.common.constants import NodeType

        return getattr(node, "type", None) == NodeType.PS

    def _name(self, node) -> str:
        return getattr(node, "name", None) or f"ps-{node.id}"

    def on_node_started(self, node, ctx):
        if self._is_ps(node):
            # atomic: concurrent scale-out registrations must not lose
            # each other's membership (callbacks run on the servicer's
            # thread pool)
            self._ps.add_server(self._name(node))

    def on_node_succeeded(self, node, ctx):
        # an exited PS is not serving regardless of exit status (clean
        # drain / operator stop reports SUCCEEDED, not DELETED)
        self._drop(node)

    def on_node_failed(self, node, ctx):
        self._drop(node)

    def on_node_deleted(self, node, ctx):
        self._drop(node)

    def _drop(self, node):
        if self._is_ps(node):
            self._ps.remove_server(self._name(node))
