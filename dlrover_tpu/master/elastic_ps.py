"""Elastic sparse-tier (PS) cluster-version bookkeeping.

Reference: dlrover/python/master/elastic_training/elastic_ps.py:18
(ElasticPsService) + agent side elastic_agent/tensorflow/elastic_ps.py —
the master keeps a monotonically increasing "cluster version" for the
parameter-server set; when PS membership changes (scale-out/in,
migration), the version bumps and workers rebuild their sessions.

TPU framing: the "PS set" is the group of hosts serving sparse embedding
shards (the C++ KV tier, sparse/kv_table.py). On membership change the
master bumps the version; workers poll it and re-partition their
key→host assignment with ``sparse.partition`` (rendezvous hashing, so
only keys owned by the changed hosts migrate).
"""

import threading
from typing import Dict, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


class ElasticPsService:
    def __init__(self):
        self._lock = threading.Lock()
        self._global_version = 0
        # node_id -> restored/local version (reference keeps per-worker
        # versions so late joiners can detect they are behind)
        self._node_versions: Dict[int, int] = {}
        self._servers: List[str] = []
        # per-server HRW weights (Brain hot-shard rebalance); workers
        # pass them to sparse.partition.assign_servers
        self._weights: Dict[str, float] = {}

    # ---- versions (reference API surface) --------------------------------

    def get_global_version(self) -> int:
        with self._lock:
            return self._global_version

    def bump_global_version(self) -> int:
        with self._lock:
            self._global_version += 1
            logger.info(
                "sparse cluster version → %d", self._global_version
            )
            return self._global_version

    def get_node_version(self, node_id: int) -> int:
        with self._lock:
            return self._node_versions.get(node_id, 0)

    def set_node_version(self, node_id: int, version: int):
        with self._lock:
            self._node_versions[node_id] = version

    # ---- server-set bookkeeping ------------------------------------------

    def get_servers(self) -> List[str]:
        with self._lock:
            return list(self._servers)

    def set_servers(self, servers: List[str]) -> int:
        """Replace the sparse-serving host set; bumps the version when
        membership actually changed."""
        with self._lock:
            if servers == self._servers:
                return self._global_version
            self._servers = list(servers)
            self._global_version += 1
            logger.info(
                "sparse server set changed (%d hosts) → version %d",
                len(servers),
                self._global_version,
            )
            return self._global_version

    # ---- HRW weights (Brain hot-shard rebalance consumer) ----------------

    def get_weights(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._weights)

    def set_weights(self, weights: Optional[Dict[str, float]]) -> int:
        """Install rebalance weights from a Brain plan
        (node_resources['ps']['weights']); bumps the version so workers
        re-partition (sparse.partition.assign_servers consumes them —
        changing one server's weight only migrates that server's keys)."""
        weights = dict(weights or {})
        with self._lock:
            if weights == self._weights:
                return self._global_version
            self._weights = weights
            self._global_version += 1
            logger.info(
                "sparse HRW weights updated (%d entries) → version %d",
                len(weights),
                self._global_version,
            )
            return self._global_version
