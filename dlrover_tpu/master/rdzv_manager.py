"""Master-side rendezvous managers.

Reference: dlrover/python/master/elastic_training/rdzv_manager.py
(RendezvousManager:58, ElasticTrainingRendezvousManager:295,
NetworkCheckRendezvousManager:353).

TPU-native differences: the sealed world also carries the
``jax.distributed`` *coordinator address* (process 0's host:port) — the
analog of the reference handing out a MasterKVStore for NCCL bootstrap —
and node_unit defaults to the number of hosts in a slice, because a
TPU slice is only usable as a whole (ICI wraps around the full topology).
"""

import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import DefaultValues, RendezvousName
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.observability.tracing import get_tracer

logger = get_logger(__name__)


class _WaitingNode:
    def __init__(self, node_id, node_rank, local_world_size, host_addr=""):
        self.node_id = node_id
        self.node_rank = node_rank
        self.local_world_size = local_world_size
        self.host_addr = host_addr
        self.join_time = time.time()


class RendezvousManager:
    """Assemble a world of {node_rank: local_world_size} per round."""

    def __init__(self, name: str = RendezvousName.TRAINING):
        self.name = name
        self._lock = threading.Lock()
        self._waiting: Dict[int, _WaitingNode] = {}
        self._world: Dict[int, int] = {}
        self._world_coordinator: str = ""
        self._rdzv_round = 0
        self._min_nodes = 1
        self._max_nodes = 1
        self._node_unit = 1
        self._waiting_timeout = DefaultValues.RDZV_WAIT_EXTRA_NODES_S
        self._rdzv_timeout = DefaultValues.RDZV_TIMEOUT_S
        self._start_waiting_time = 0.0
        self._coordinator_port = 7010
        self._alive_nodes: set = set()
        # live-reshard directive (see plan_reshard); version 0 = none
        self._reshard: Optional[Dict] = None
        self._reshard_version = 0

    # ---- config ---------------------------------------------------------

    def update_rdzv_params(
        self,
        min_nodes: Optional[int] = None,
        max_nodes: Optional[int] = None,
        waiting_timeout: Optional[float] = None,
        node_unit: Optional[int] = None,
        rdzv_timeout: Optional[float] = None,
    ):
        """Partial update: None keeps the current value (auto-scaling must
        not silently reset node_unit/timeouts to defaults)."""
        with self._lock:
            if min_nodes is not None:
                self._min_nodes = min_nodes
            if max_nodes is not None:
                self._max_nodes = max_nodes
            if waiting_timeout is not None:
                self._waiting_timeout = waiting_timeout
            if node_unit is not None:
                self._node_unit = max(1, node_unit)
            if rdzv_timeout is not None:
                self._rdzv_timeout = rdzv_timeout

    def add_alive_node(self, node_rank: int):
        with self._lock:
            self._alive_nodes.add(node_rank)

    def remove_alive_node(self, node_rank: int):
        """A node died: drop it and force a new round if it was in-world.

        Exception: when a pending live-reshard directive already names
        this rank as lost, the survivors are migrating state in place —
        excise the rank from the sealed world without tearing the round
        down (the whole point of the live path is not to restart)."""
        with self._lock:
            self._alive_nodes.discard(node_rank)
            self._waiting.pop(node_rank, None)
            if node_rank not in self._world:
                return
            directive = self._reshard
            if directive is not None and node_rank in directive["lost_ranks"]:
                self._world.pop(node_rank)
                logger.info(
                    "%s: node %s excised from sealed world by reshard "
                    "directive v%d; survivors keep round %d",
                    self.name,
                    node_rank,
                    directive["version"],
                    self._rdzv_round,
                )
                return
            logger.info(
                "%s: node %s left the sealed world; next joins start "
                "round %d",
                self.name,
                node_rank,
                self._rdzv_round + 1,
            )
            self._world = {}
            self._world_coordinator = ""

    # ---- live reshard ---------------------------------------------------

    def plan_reshard(
        self,
        lost_dp_ranks: List[int],
        dp_size: int,
        deadline_s: float = 30.0,
        reason: str = "",
    ) -> int:
        """Issue a live-reshard directive: survivors migrate ZeRO-1
        shards to the shrunken dp layout instead of restarting.

        Returns the directive version (monotonic, starts at 1). Lost
        ranks already in the sealed world are excised immediately —
        the round stays sealed for the survivors."""
        lost = sorted(set(int(r) for r in lost_dp_ranks))
        with self._lock:
            dp_old = int(dp_size)
            dp_new = dp_old - len(lost)
            if dp_new <= 0:
                raise ValueError(
                    f"reshard would leave no survivors: dp={dp_old}, "
                    f"lost={lost}"
                )
            self._reshard_version += 1
            self._reshard = {
                "version": self._reshard_version,
                "rdzv_round": self._rdzv_round,
                "dp_old": dp_old,
                "dp_new": dp_new,
                "lost_ranks": lost,
                "deadline_s": float(deadline_s),
                "reason": reason,
            }
            for r in lost:
                self._world.pop(r, None)
            get_tracer().instant(
                "failover.reshard_plan",
                rdzv=self.name,
                version=self._reshard_version,
                dp_old=dp_old,
                dp_new=dp_new,
                lost=len(lost),
            )
            logger.info(
                "%s: reshard directive v%d: dp %d -> %d, lost=%s (%s)",
                self.name,
                self._reshard_version,
                dp_old,
                dp_new,
                lost,
                reason or "eviction",
            )
            return self._reshard_version

    def get_reshard_plan(self) -> Dict:
        """The pending directive, or ``{"version": 0}`` when none."""
        with self._lock:
            if self._reshard is None:
                return {"version": 0}
            return dict(self._reshard)

    # ---- join / poll ----------------------------------------------------

    def join_rendezvous(
        self,
        node_id: int,
        node_rank: int,
        local_world_size: int,
        host_addr: str = "",
    ) -> int:
        with self._lock:
            if node_rank in self._world:
                # a member of the sealed world re-joining ⇒ it restarted;
                # the old world is stale.
                self._world = {}
                self._world_coordinator = ""
            if not self._waiting:
                self._start_waiting_time = time.time()
                self._rdzv_round += 1
            self._waiting[node_rank] = _WaitingNode(
                node_id, node_rank, local_world_size, host_addr
            )
            self._alive_nodes.add(node_rank)
            get_tracer().instant(
                "failover.rdzv_join",
                rdzv=self.name,
                node=node_rank,
                rdzv_round=self._rdzv_round,
                waiting=len(self._waiting),
            )
            logger.info(
                "%s round %d: node %s joined (%d waiting, min=%d max=%d)",
                self.name,
                self._rdzv_round,
                node_rank,
                len(self._waiting),
                self._min_nodes,
                self._max_nodes,
            )
            return self._rdzv_round

    def _check_rdzv_completed(self) -> bool:
        """Called with the lock held."""
        n = len(self._waiting)
        if n >= self._max_nodes:
            return True
        waited = time.time() - self._start_waiting_time
        usable = n - (n % self._node_unit)
        if usable >= self._min_nodes and waited >= self._waiting_timeout:
            return True
        return False

    def _seal_world(self):
        """Seal min..max nodes into the world; lock held."""
        ranks = sorted(self._waiting.keys())
        n = len(ranks)
        usable = min(n - (n % self._node_unit), self._max_nodes)
        if usable <= 0:
            return
        chosen = ranks[:usable]
        self._world = {
            r: self._waiting[r].local_world_size for r in chosen
        }
        first = self._waiting[chosen[0]]
        host = first.host_addr or "localhost"
        self._world_coordinator = f"{host}:{self._coordinator_port}"
        for r in chosen:
            self._waiting.pop(r)
        get_tracer().instant(
            "failover.rdzv_seal",
            rdzv=self.name,
            rdzv_round=self._rdzv_round,
            world_size=len(self._world),
        )
        logger.info(
            "%s round %d sealed: world=%s coordinator=%s",
            self.name,
            self._rdzv_round,
            self._world,
            self._world_coordinator,
        )

    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, int], str]:
        """Poll for the sealed world: (round, group, world, coordinator)."""
        with self._lock:
            if not self._world and self._waiting:
                if self._check_rdzv_completed():
                    self._seal_world()
            return (
                self._rdzv_round,
                0,
                dict(self._world),
                self._world_coordinator,
            )

    def num_nodes_waiting(self) -> int:
        with self._lock:
            return len(self._waiting)

    @property
    def rdzv_round(self) -> int:
        with self._lock:
            return self._rdzv_round


class ElasticTrainingRendezvousManager(RendezvousManager):
    def __init__(self):
        super().__init__(RendezvousName.TRAINING)


class NetworkCheckRendezvousManager(RendezvousManager):
    """Pairs nodes for matmul+collective health checks.

    Round 1 pairs (0,1)(2,3)…; round 2 re-pairs (0,n-1)(1,2)(3,4)… so a node
    failing in *both* rounds with different partners is the faulty one
    (reference: rdzv_manager.py:412 _group_nodes, :511 check_fault_node,
    :554 _detect_stragglers).
    """

    def __init__(self):
        super().__init__(RendezvousName.NETWORK_CHECK)
        self._results: Dict[int, Dict[int, float]] = {}  # round → rank → t
        self._success: Dict[int, Dict[int, bool]] = {}
        self._check_round = 0
        self._last_world_size = 0

    def get_comm_world(self, node_rank):
        rdzv_round, _, world, coord = super().get_comm_world(node_rank)
        if world:
            with self._lock:
                self._last_world_size = len(world)
            groups = self._group_nodes(sorted(world.keys()))
            for gi, group in enumerate(groups):
                if node_rank in group:
                    sub = {r: world[r] for r in group}
                    return rdzv_round, gi, sub, coord
        return rdzv_round, 0, world, coord

    def _group_nodes(self, ranks: List[int]) -> List[List[int]]:
        n = len(ranks)
        if n <= 2:
            return [ranks]
        round_idx = self._check_round % 2
        groups = []
        if round_idx == 0:
            for i in range(0, n - 1, 2):
                groups.append([ranks[i], ranks[i + 1]])
            if n % 2:
                groups[-1].append(ranks[-1])
        else:
            # rotate pairing so every node gets a different partner:
            # (first, last), then consecutive pairs of the middle section,
            # any middle leftover joins the last group.
            groups.append([ranks[0], ranks[-1]])
            middle = ranks[1:-1]
            for i in range(0, len(middle) - 1, 2):
                groups.append([middle[i], middle[i + 1]])
            if len(middle) % 2:
                groups[-1].append(middle[-1])
        return [g for g in groups if g]

    def report_network_check_result(
        self, node_rank: int, succeeded: bool, elapsed_time: float
    ):
        with self._lock:
            self._results.setdefault(self._check_round, {})[node_rank] = (
                elapsed_time
            )
            self._success.setdefault(self._check_round, {})[node_rank] = (
                succeeded
            )
            # all members of the sealed world reported → advance the round
            # so the next rendezvous re-pairs with different partners
            expected = self._last_world_size
            if expected and len(
                self._success[self._check_round]
            ) >= expected:
                self._advance_round_locked()

    def _advance_round_locked(self):
        self._check_round += 1
        self._world = {}
        self._world_coordinator = ""

    def next_check_round(self):
        with self._lock:
            self._advance_round_locked()

    def check_fault_node(self) -> Tuple[List[int], int]:
        """Nodes failing every observed round are faulty."""
        with self._lock:
            if not self._success:
                return [], self._check_round
            fault: Optional[set] = None
            for results in self._success.values():
                bad = {r for r, ok in results.items() if not ok}
                fault = bad if fault is None else (fault & bad)
            return sorted(fault or []), self._check_round

    def get_stragglers(
        self, ratio: float = DefaultValues.STRAGGLER_RATIO
    ) -> Tuple[List[int], int]:
        with self._lock:
            latest = self._results.get(self._check_round) or self._results.get(
                self._check_round - 1, {}
            )
            if len(latest) < 2:
                return [], self._check_round
            times = sorted(latest.values())
            median = times[len(times) // 2]
            if median <= 0:
                return [], self._check_round
            return (
                sorted(
                    r for r, t in latest.items() if t / median >= ratio
                ),
                self._check_round,
            )
