"""Dataset splitters: carve a dataset into dispatchable shards.

Reference: dlrover/python/master/shard/dataset_splitter.py
(Shard:26, TableDatasetSplitter:144, TextDatasetSplitter:257,
StreamingDatasetSplitter:359).
"""

import random
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class Shard:
    name: str = ""
    start: int = 0
    end: int = 0
    record_indices: List[int] = field(default_factory=list)


class DatasetSplitter:
    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
    ):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = max(1, shard_size)
        self.num_epochs = max(1, num_epochs)
        self.epoch = 0

    def create_shards(self) -> List[Shard]:
        raise NotImplementedError

    def epoch_finished(self) -> bool:
        return self.epoch >= self.num_epochs


class TableDatasetSplitter(DatasetSplitter):
    """Contiguous [start, end) ranges over a random-access table."""

    def __init__(self, *args, shuffle: bool = False, seed: int = 0, **kw):
        super().__init__(*args, **kw)
        self.shuffle = shuffle
        self._rng = random.Random(seed)

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(name=self.dataset_name, start=start, end=end)
            )
        if self.shuffle:
            self._rng.shuffle(shards)
        return shards


class TextDatasetSplitter(DatasetSplitter):
    """Like Table, but shards carry per-record indices (shuffled lines)."""

    def __init__(self, *args, shuffle: bool = False, seed: int = 0, **kw):
        super().__init__(*args, **kw)
        self.shuffle = shuffle
        self._rng = random.Random(seed)

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        indices = list(range(self.dataset_size))
        if self.shuffle:
            self._rng.shuffle(indices)
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(
                    name=self.dataset_name,
                    start=start,
                    end=end,
                    record_indices=indices[start:end],
                )
            )
        return shards


class StreamingDatasetSplitter(DatasetSplitter):
    """Unbounded stream: shards are generated on demand from an offset."""

    def __init__(self, *args, max_shard_count: int = 0, **kw):
        super().__init__(*args, **kw)
        self._offset = 0
        self._max_shard_count = max_shard_count
        self._created = 0

    def epoch_finished(self) -> bool:
        return bool(
            self._max_shard_count and self._created >= self._max_shard_count
        )

    def create_shards(self) -> List[Shard]:
        if self.epoch == 0:
            self.epoch = 1
        shards = []
        # emit a window of shards; the task manager calls again when drained
        for _ in range(64):
            if self.epoch_finished():
                break
            shards.append(
                Shard(
                    name=self.dataset_name,
                    start=self._offset,
                    end=self._offset + self.shard_size,
                )
            )
            self._offset += self.shard_size
            self._created += 1
        return shards


def new_dataset_splitter(
    storage_type: str,
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
    seed: int = 0,
) -> DatasetSplitter:
    if storage_type == "text":
        return TextDatasetSplitter(
            dataset_name,
            dataset_size,
            shard_size,
            num_epochs=num_epochs,
            shuffle=shuffle,
            seed=seed,
        )
    if storage_type == "stream":
        return StreamingDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs=num_epochs
        )
    return TableDatasetSplitter(
        dataset_name,
        dataset_size,
        shard_size,
        num_epochs=num_epochs,
        shuffle=shuffle,
        seed=seed,
    )
