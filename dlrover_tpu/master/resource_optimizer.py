"""Resource plans + local heuristic optimizer.

Reference: dlrover/python/master/resource/ (JobResource job.py:71,
PSLocalOptimizer local_optimizer.py:66, BrainResoureOptimizer
brain_optimizer.py). The TPU unit of scaling is whole slices, so plans
speak in worker (host) counts and slice multiples rather than free-form
cpu/mem; an external "brain"-style service can subclass ResourceOptimizer.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


@dataclass
class ResourcePlan:
    """Target worker count (+ per-node resource hints)."""

    worker_num: Optional[int] = None
    node_resources: Dict[str, Dict] = field(default_factory=dict)

    def empty(self) -> bool:
        return self.worker_num is None and not self.node_resources


class ResourceOptimizer:
    def generate_plan(self, stage: str, stats: Dict) -> ResourcePlan:
        raise NotImplementedError


class LocalHeuristicOptimizer(ResourceOptimizer):
    """Speed-per-worker marginal-utility heuristic.

    Reference analog: AllreduceJobResourceOptimizer (resource/job.py:517) —
    grow while throughput/worker holds, shrink when marginal speedup
    collapses (stragglers / DCN saturation).
    """

    def __init__(
        self,
        min_workers: int = 1,
        max_workers: int = 1,
        node_unit: int = 1,
        efficiency_floor: float = 0.7,
    ):
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.node_unit = max(1, node_unit)
        self.efficiency_floor = efficiency_floor
        # history of (worker_num, steps/sec)
        self._speed_history: List[tuple] = []

    def observe(self, worker_num: int, speed: float):
        if speed > 0:
            self._speed_history.append((worker_num, speed))
            self._speed_history = self._speed_history[-64:]

    def generate_plan(self, stage: str, stats: Dict) -> ResourcePlan:
        plan = ResourcePlan()
        workers = stats.get("worker_num", self.min_workers)
        speed = stats.get("speed", 0.0)
        pending = stats.get("pending_nodes", 0)
        self.observe(workers, speed)

        if pending > 0 and workers > self.min_workers:
            # can't place all nodes: fall back to a smaller world
            target = max(
                self.min_workers,
                (workers - pending) // self.node_unit * self.node_unit,
            )
            if target != workers:
                plan.worker_num = target
                logger.info(
                    "scale-in to %d (pending=%d unplaceable)", target, pending
                )
            return plan

        if workers < self.max_workers and self._scaling_efficient():
            plan.worker_num = min(
                self.max_workers, workers + self.node_unit
            )
            logger.info("scale-out to %d workers", plan.worker_num)
        return plan

    def _scaling_efficient(self) -> bool:
        """Did the last scale-up keep per-worker speed above the floor?"""
        by_workers: Dict[int, float] = {}
        for w, s in self._speed_history:
            by_workers[w] = max(by_workers.get(w, 0.0), s)
        if len(by_workers) < 2:
            return True
        sizes = sorted(by_workers)
        w0, w1 = sizes[-2], sizes[-1]
        if by_workers[w0] <= 0:
            return True
        actual = by_workers[w1] / by_workers[w0]
        ideal = w1 / w0
        return actual >= self.efficiency_floor * ideal
