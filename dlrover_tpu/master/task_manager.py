"""TaskManager: dispatch data shards as tasks; re-queue on failure/timeout.

Reference: dlrover/python/master/shard/task_manager.py:37 and
batch_dataset_manager.py:29. This is the dynamic-data-sharding heart: a
worker that dies mid-shard has its in-flight shards re-queued for the
survivors, so elasticity never loses or duplicates data beyond the shard
granularity. Shard checkpoints make dataset position restorable.
"""

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import DefaultValues, TaskType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.dataset_splitter import (
    DatasetSplitter,
    Shard,
    new_dataset_splitter,
)

logger = get_logger(__name__)


@dataclass
class Task:
    task_id: int
    task_type: str
    shard: Shard
    epoch: int = 0
    worker_id: int = -1
    create_time: float = field(default_factory=time.time)
    start_time: float = 0.0

    @classmethod
    def create_invalid_task(cls) -> "Task":
        return cls(task_id=-1, task_type=TaskType.NONE, shard=Shard())


class DatasetManager:
    """Pending/doing task bookkeeping for one dataset."""

    def __init__(self, splitter: DatasetSplitter, task_type: str):
        self.splitter = splitter
        self.task_type = task_type
        self.todo: List[Task] = []
        self.doing: Dict[int, Task] = {}
        self._task_id = 0
        self._completed = 0

    def create_tasks(self):
        if self.splitter.epoch_finished():
            return
        for shard in self.splitter.create_shards():
            self.todo.append(
                Task(
                    task_id=self._task_id,
                    task_type=self.task_type,
                    shard=shard,
                    epoch=self.splitter.epoch,
                )
            )
            self._task_id += 1

    def get_task(self, worker_id: int) -> Task:
        if not self.todo and not self.splitter.epoch_finished():
            self.create_tasks()
        if not self.todo:
            if self.doing:
                # all shards are in flight elsewhere; they may yet be
                # re-queued (worker death / timeout) — tell the worker to
                # wait, not to stop (reference: TaskType.WAIT)
                return Task(
                    task_id=-1, task_type=TaskType.WAIT, shard=Shard()
                )
            return Task.create_invalid_task()
        task = self.todo.pop(0)
        task.worker_id = worker_id
        task.start_time = time.time()
        self.doing[task.task_id] = task
        return task

    def report_task_status(self, task_id: int, success: bool) -> Optional[Task]:
        task = self.doing.pop(task_id, None)
        if task is None:
            return None
        if success:
            self._completed += 1
        else:
            task.worker_id = -1
            task.start_time = 0.0
            self.todo.insert(0, task)
        return task

    def recover_worker_tasks(self, worker_id: int) -> int:
        """Re-queue in-flight tasks of a dead worker."""
        lost = [
            tid for tid, t in self.doing.items() if t.worker_id == worker_id
        ]
        for tid in lost:
            task = self.doing.pop(tid)
            task.worker_id = -1
            self.todo.insert(0, task)
        return len(lost)

    def recover_timeout_tasks(self, timeout_s: float) -> int:
        now = time.time()
        expired = [
            tid
            for tid, t in self.doing.items()
            if t.start_time and now - t.start_time > timeout_s
        ]
        for tid in expired:
            task = self.doing.pop(tid)
            task.worker_id = -1
            self.todo.insert(0, task)
        return len(expired)

    def completed(self) -> bool:
        return (
            self.splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    @property
    def completed_count(self) -> int:
        return self._completed

    # ---- checkpoint ------------------------------------------------------

    def checkpoint(self) -> Dict:
        """Undispatched + in-flight shard ranges; restore re-queues both."""
        return {
            "epoch": self.splitter.epoch,
            "todo": [
                [t.shard.start, t.shard.end, t.epoch] for t in self.todo
            ],
            "doing": [
                [t.shard.start, t.shard.end, t.epoch]
                for t in self.doing.values()
            ],
            "splitter_offset": getattr(self.splitter, "_offset", 0),
        }

    def restore_checkpoint(self, ckpt: Dict):
        self.splitter.epoch = ckpt.get("epoch", 0)
        if hasattr(self.splitter, "_offset"):
            self.splitter._offset = ckpt.get("splitter_offset", 0)
        self.todo = []
        self.doing = {}
        name = self.splitter.dataset_name
        for start, end, epoch in ckpt.get("doing", []) + ckpt.get("todo", []):
            self.todo.append(
                Task(
                    task_id=self._task_id,
                    task_type=self.task_type,
                    shard=Shard(name=name, start=start, end=end),
                    epoch=epoch,
                )
            )
            self._task_id += 1


class TaskManager:
    """Cross-dataset task dispatch + periodic timeout re-queue."""

    def __init__(self, shard_timeout_s: float = DefaultValues.SHARD_TIMEOUT_S):
        self._lock = threading.Lock()
        self._datasets: Dict[str, DatasetManager] = {}
        self._shard_timeout_s = shard_timeout_s
        self._worker_last_task: Dict[int, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.speed_monitor = None  # wired by the master

    def start(self):
        self._thread = threading.Thread(
            target=self._check_timeout_loop,
            name="task-timeout",
            daemon=True,
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _check_timeout_loop(self):
        while not self._stop.wait(30.0):
            with self._lock:
                for name, ds in self._datasets.items():
                    n = ds.recover_timeout_tasks(self._shard_timeout_s)
                    if n:
                        logger.info(
                            "dataset %s: re-queued %d timed-out shards",
                            name,
                            n,
                        )

    # ---- RPC surface -----------------------------------------------------

    def new_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
        task_type: str = TaskType.TRAINING,
    ):
        with self._lock:
            if dataset_name in self._datasets:
                return
            splitter = new_dataset_splitter(
                storage_type,
                dataset_name,
                dataset_size,
                shard_size,
                num_epochs=num_epochs,
                shuffle=shuffle,
            )
            ds = DatasetManager(splitter, task_type)
            ds.create_tasks()
            self._datasets[dataset_name] = ds
            logger.info(
                "registered dataset %s size=%d shard=%d epochs=%d",
                dataset_name,
                dataset_size,
                shard_size,
                num_epochs,
            )

    def get_task(self, dataset_name: str, worker_id: int) -> Task:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                return Task.create_invalid_task()
            self._worker_last_task[worker_id] = time.time()
            return ds.get_task(worker_id)

    def report_task_status(
        self, dataset_name: str, task_id: int, success: bool, worker_id: int
    ):
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds:
                ds.report_task_status(task_id, success)

    def recover_worker_tasks(self, worker_id: int):
        with self._lock:
            for name, ds in self._datasets.items():
                n = ds.recover_worker_tasks(worker_id)
                if n:
                    logger.info(
                        "dataset %s: re-queued %d shards of dead worker %d",
                        name,
                        n,
                        worker_id,
                    )

    def get_epoch(self, dataset_name: str) -> int:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.splitter.epoch if ds else 0

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(
                ds.completed()
                for ds in self._datasets.values()
                if ds.task_type == TaskType.TRAINING
            )

    def checkpoint(self, dataset_name: str) -> str:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return json.dumps(ds.checkpoint()) if ds else ""

    def restore_checkpoint(self, dataset_name: str, content: str):
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds and content:
                ds.restore_checkpoint(json.loads(content))
