"""Pluggable node-event callbacks for the master.

Reference: dlrover/python/master/node/event_callback.py:42 —
``NodeEventCallback`` observers (on_node_started/succeeded/failed/
deleted, each wrapped so an observer exception can never break node
bookkeeping) registered with the job manager, plus the concrete
callbacks the master wires by default (task reschedule on node death,
job-exit decisions). TPU-native differences: the cluster context also
carries the rendezvous managers (elastic worlds are sealed by the
master, not torch elastic agents), and the chief role maps to rank 0 of
the slice rather than a separate TF process type.
"""

import abc
from typing import List

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node

logger = get_logger(__name__)


class ClusterContext:
    """What callbacks may touch: the managers, never raw node dicts."""

    def __init__(self, job_manager, task_manager=None, rdzv_managers=None,
                 speed_monitor=None):
        self.job_manager = job_manager
        self.task_manager = task_manager
        self.rdzv_managers = rdzv_managers or {}
        self.speed_monitor = speed_monitor


class NodeEventCallback(abc.ABC):
    """Override any subset. Exception isolation lives in ONE place —
    the registry dispatch (JobManager._fire) — so observers here stay
    plain methods and a raised exception is logged with its hook name."""

    def on_node_started(self, node: Node, ctx: ClusterContext):
        pass

    def on_node_succeeded(self, node: Node, ctx: ClusterContext):
        pass

    def on_node_failed(self, node: Node, ctx: ClusterContext):
        pass

    def on_node_deleted(self, node: Node, ctx: ClusterContext):
        pass


class TaskRescheduleCallback(NodeEventCallback):
    """Requeue a dead node's in-flight dataset shards (reference:
    TaskRescheduleCallback, event_callback.py:111)."""

    def __init__(self, task_manager):
        self._tasks = task_manager

    def on_node_failed(self, node, ctx):
        self._tasks.recover_worker_tasks(node.id)

    def on_node_deleted(self, node, ctx):
        self._tasks.recover_worker_tasks(node.id)


class RendezvousPruneCallback(NodeEventCallback):
    """Drop a dead node from every rendezvous world so the next seal
    does not wait on it."""

    def __init__(self, rdzv_managers):
        self._managers = rdzv_managers

    def on_node_failed(self, node, ctx):
        for mgr in self._managers.values():
            mgr.remove_alive_node(node.rank_index)

    on_node_deleted = on_node_failed


class ChiefFailureCallback(NodeEventCallback):
    """Chief semantics (reference: TFPSNodeHandlingCallback
    _stop_job_if_needed): an unrecoverable chief death fails the JOB —
    workers can be relaunched, the coordination anchor cannot."""

    def __init__(self, on_job_failed):
        self._on_job_failed = on_job_failed

    def on_node_failed(self, node, ctx):
        from dlrover_tpu.common.constants import NodeType

        if (
            node.type == NodeType.CHIEF
            and not node.is_released
            and not node.should_relaunch()
        ):
            logger.error("chief exhausted its budget: failing the job")
            self._on_job_failed(f"chief {node.name}: {node.exit_reason}")

    # a platform-deleted chief past its budget is the same headless job
    on_node_deleted = on_node_failed


class JobCompletionCallback(NodeEventCallback):
    """Evaluator-aware completion (reference: evaluator manager
    wait-then-finish): the job is done when all WORKERS succeeded AND
    every evaluator has exited."""

    def __init__(self, on_job_completed):
        self._on_job_completed = on_job_completed

    def on_node_succeeded(self, node, ctx):
        jm = ctx.job_manager
        if jm.all_workers_succeeded() and jm.all_evaluators_exited():
            self._on_job_completed()


def default_callbacks(
    task_manager=None,
    rdzv_managers=None,
    on_job_failed=None,
    on_job_completed=None,
) -> List[NodeEventCallback]:
    out: List[NodeEventCallback] = []
    if task_manager is not None:
        out.append(TaskRescheduleCallback(task_manager))
    if rdzv_managers:
        out.append(RendezvousPruneCallback(rdzv_managers))
    if on_job_failed is not None:
        out.append(ChiefFailureCallback(on_job_failed))
    if on_job_completed is not None:
        out.append(JobCompletionCallback(on_job_completed))
    return out
