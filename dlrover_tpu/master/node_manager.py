"""Job-level node lifecycle management.

Reference: dlrover/python/master/node/dist_job_manager.py:88 (monitor loops,
relaunch decisions), node/training_node.py, event_callback.py. The platform
watcher/scaler pair is pluggable: tests use in-memory fakes, production uses
the pod-slice scaler (``master/scaler.py``).
"""

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu.common.constants import (
    DefaultValues,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.messages import NodeMeta
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.status_flow import transition

logger = get_logger(__name__)


@dataclass
class NodeEvent:
    event_type: str
    node_id: int
    status: str = ""
    exit_reason: str = ""
    # which incarnation of the node the event is about (pods carry a
    # relaunch-count label); -1 = unknown → always accepted. Guards the
    # relaunch loop against stale events for an already-replaced pod
    # (e.g. the platform GC deleting the dead predecessor) cascading
    # into relaunches of the healthy replacement.
    incarnation: int = -1


class ScalePlan:
    """What the scaler must do (reference: scaler/base_scaler.py ScalePlan)."""

    def __init__(self):
        self.launch_nodes: List[Node] = []
        self.remove_nodes: List[Node] = []
        self.worker_num: Optional[int] = None

    def empty(self) -> bool:
        return not self.launch_nodes and not self.remove_nodes and (
            self.worker_num is None
        )

    def __repr__(self):
        return (
            f"ScalePlan(launch={[n.name for n in self.launch_nodes]}, "
            f"remove={[n.name for n in self.remove_nodes]}, "
            f"worker_num={self.worker_num})"
        )


class Scaler:
    """Executes ScalePlans on the platform."""

    def scale(self, plan: ScalePlan):
        raise NotImplementedError


class NoopScaler(Scaler):
    def __init__(self):
        self.plans: List[ScalePlan] = []

    def scale(self, plan: ScalePlan):
        self.plans.append(plan)


class JobManager:
    """Track nodes, consume events, decide relaunches."""

    def __init__(
        self,
        num_workers: int = 1,
        relaunch_budget: int = DefaultValues.RELAUNCH_BUDGET,
        heartbeat_timeout_s: float = DefaultValues.HEARTBEAT_TIMEOUT_S,
        pending_timeout_s: float = DefaultValues.PENDING_TIMEOUT_S,
        scaler: Optional[Scaler] = None,
    ):
        self._lock = threading.Lock()
        self._nodes: Dict[int, Node] = {}
        self._num_workers = num_workers
        self._relaunch_budget = relaunch_budget
        self._heartbeat_timeout_s = heartbeat_timeout_s
        self._pending_timeout_s = pending_timeout_s
        self._scaler = scaler or NoopScaler()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._start_time = time.time()
        # legacy fn(node) hooks fired on terminal transitions
        self.node_failed_callbacks: List[Callable[[Node], None]] = []
        self.node_succeeded_callbacks: List[Callable[[Node], None]] = []
        # pluggable observer registry (reference: event_callback.py:42);
        # populate with master.event_callback.NodeEventCallback objects
        self.event_callbacks: List[Any] = []
        self.cluster_context: Any = None  # set by the master (ClusterContext)
        # serving-tier reshard directive (serving/migration.py): the
        # KV-page analogue of RendezvousManager._reshard — versioned,
        # monotonic, one pending directive at a time
        self._serving_reshard_version = 0
        self._serving_reshard: Optional[Dict] = None
        # serving-tier scale directives (master/serving_autoscaler.py):
        # versioned per decision, latest directive kept PER ROLE so
        # prefill and decode pools scale independently
        self._serving_scale_version = 0
        self._serving_scale: Dict[str, Dict] = {}
        # brain tuning directives (cluster/brain.py): one monotonic
        # version counter, latest plan/revision kept; trainers pick it
        # up through the ParallelConfig poll (tuning_json field)
        self._tuning_version = 0
        self._tuning: Optional[Dict] = None
        self._init_nodes()

    def _init_nodes(self):
        for i in range(self._num_workers):
            self._nodes[i] = Node(
                node_type=NodeType.WORKER,
                node_id=i,
                rank_index=i,
                max_relaunch_count=self._relaunch_budget,
            )
            self._nodes[i].create_time = time.time()

    # ---- lifecycle -------------------------------------------------------

    def start(self):
        t = threading.Thread(
            target=self._monitor_heartbeats, name="hb-monitor", daemon=True
        )
        t.start()
        self._threads.append(t)

    def stop(self):
        self._stop.set()

    # ---- RPC-surface handlers -------------------------------------------

    def register_node(self, meta: NodeMeta, restart_count: int = 0) -> Node:
        with self._lock:
            node = self._nodes.get(meta.node_id)
            if node is None:
                node = Node(
                    node_type=meta.node_type or NodeType.WORKER,
                    node_id=meta.node_id,
                    rank_index=(
                        meta.node_rank if meta.node_rank >= 0 else meta.node_id
                    ),
                    max_relaunch_count=self._relaunch_budget,
                )
                self._nodes[meta.node_id] = node
            elif meta.node_type and node.type != meta.node_type:
                # pre-created records default to WORKER; honor the
                # registrant's declared role (a PS landing on a
                # pre-created id must still enter the sparse tier —
                # PsClusterCallback keys off node.type). The default
                # name derives from the type: refresh it too, or the PS
                # ring would publish a stale "worker-N" entry that never
                # resolves to the server's registered address
                if node.name == f"{node.type}-{node.id}":
                    node.name = f"{meta.node_type}-{node.id}"
                node.type = meta.node_type
            node.host_addr = meta.host_addr
            if getattr(meta, "role", ""):
                node.role = meta.role
            node.config_resource = NodeResource(
                tpu_chips=meta.local_chips, tpu_type=meta.tpu_type
            )
            node.topology.slice_id = meta.slice_id
            node.topology.slice_index = meta.slice_index
            node.heartbeat_time = time.time()
            prev_status = node.status
            prev_rc = node.agent_restart_count
            node.agent_restart_count = max(prev_rc, restart_count)
            self._apply_status(node, NodeStatus.RUNNING)
            started = node.status == NodeStatus.RUNNING and (
                prev_status != NodeStatus.RUNNING
                or restart_count > prev_rc
            )
            logger.info("registered %s from %s", node, meta.host_addr)
        # outside the lock: observers may call back into query methods.
        # Fire on an actual transition INTO running OR on a worker
        # restart (higher restart_count — the replacement registering
        # before any failure event landed); never for a straggler
        # re-registering a terminal node or a network-blip duplicate.
        if started:
            self._fire("on_node_started", node)
        return node

    def handle_heartbeat(self, node_id: int) -> List[str]:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return []
            node.heartbeat_time = time.time()
            return []

    def handle_status_report(
        self, node_id: int, status: str, exit_reason: str = ""
    ):
        self.process_event(
            NodeEvent(
                NodeEventType.MODIFIED,
                node_id,
                status=status,
                exit_reason=exit_reason,
            )
        )

    # ---- event processing ------------------------------------------------

    def process_event(self, event: NodeEvent):
        with self._lock:
            node = self._nodes.get(event.node_id)
            if node is None:
                return
            if 0 <= event.incarnation < node.incarnation:
                return  # stale: about a pod this node already replaced
            if event.event_type == NodeEventType.HEARTBEAT_TIMEOUT:
                status = NodeStatus.FAILED
                node.exit_reason = NodeExitReason.KILLED
            elif event.event_type == NodeEventType.DELETED:
                status = NodeStatus.DELETED
                node.exit_reason = event.exit_reason or NodeExitReason.KILLED
            else:
                status = event.status
                if event.exit_reason:
                    node.exit_reason = event.exit_reason
            flow = transition(node.status, status)
            if not flow.allowed:
                return
            self._apply_status(node, status)

        if status in (NodeStatus.FAILED, NodeStatus.DELETED):
            self._fire(
                "on_node_deleted"
                if status == NodeStatus.DELETED
                else "on_node_failed",
                node,
            )
            self._on_node_down(node)
        elif status == NodeStatus.SUCCEEDED:
            self._fire("on_node_succeeded", node)
            for cb in self.node_succeeded_callbacks:
                cb(node)
        elif status == NodeStatus.RUNNING:
            self._fire("on_node_started", node)

    def _apply_status(self, node: Node, status: str):
        flow = transition(node.status, status)
        if flow.allowed:
            node.update_status(status)

    def _fire(self, hook: str, node: Node):
        """Dispatch one lifecycle hook to every registered observer; an
        observer exception never breaks node bookkeeping."""
        for cb in self.event_callbacks:
            try:
                getattr(cb, hook)(node, self.cluster_context)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "event callback %s.%s failed", type(cb).__name__, hook
                )

    def _on_node_down(self, node: Node):
        if node.is_released:
            # the master removed this node on purpose (scale-in): its
            # termination is expected, not a failure to relaunch
            return
        for cb in self.node_failed_callbacks:
            cb(node)
        if node.should_relaunch():
            node.inc_relaunch_count()
            self._relaunch_node(node)
        else:
            logger.warning(
                "%s exhausted relaunch budget (reason=%s)",
                node,
                node.exit_reason,
            )

    def _relaunch_node(self, node: Node):
        logger.info(
            "relaunching %s (attempt %d/%d, reason=%s)",
            node.name,
            node.relaunch_count,
            node.max_relaunch_count,
            node.exit_reason,
        )
        with self._lock:
            new_node = node.new_incarnation()
            self._nodes[node.id] = new_node
        plan = ScalePlan()
        plan.launch_nodes.append(new_node)
        self._scaler.scale(plan)

    # ---- monitors --------------------------------------------------------

    def _monitor_heartbeats(self):
        interval = min(30.0, self._heartbeat_timeout_s / 4)
        while not self._stop.wait(interval):
            now = time.time()
            dead: List[int] = []
            with self._lock:
                for node in self._nodes.values():
                    if node.status != NodeStatus.RUNNING:
                        continue
                    last = node.heartbeat_time or node.create_time or now
                    if now - last > self._heartbeat_timeout_s:
                        dead.append(node.id)
            for node_id in dead:
                logger.warning("node %d heartbeat timeout", node_id)
                self.process_event(
                    NodeEvent(NodeEventType.HEARTBEAT_TIMEOUT, node_id)
                )

    # ---- job-level queries ----------------------------------------------

    def get_node(self, node_id: int) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def running_nodes(self) -> List[Node]:
        with self._lock:
            return [
                n
                for n in self._nodes.values()
                if n.status == NodeStatus.RUNNING
            ]

    def nodes_of_type(self, node_type: str) -> List[Node]:
        with self._lock:
            return [n for n in self._nodes.values() if n.type == node_type]

    def serving_nodes(self, role: Optional[str] = None) -> List[Node]:
        """Generation-serving replicas (serving/replica.py). They register
        like trainer nodes — heartbeats, failure detection and eviction
        flow through the same machinery — but live outside the train
        rendezvous, so job completion never waits on them. ``role``
        filters a disaggregated fleet to one pool ("prefill" /
        "decode" / "unified") so each can be scaled independently."""
        nodes = self.nodes_of_type(NodeType.SERVING)
        if role is None:
            return nodes
        return [n for n in nodes if n.role == role]

    # ---- serving reshard (KV-page migration directives) ------------------

    def plan_serving_reshard(
        self,
        victim: str,
        survivors: Optional[List[str]] = None,
        deadline_s: float = 10.0,
        reason: str = "",
    ) -> int:
        """Issue a serving-reshard directive: migrate the victim
        replica's held KV pages onto the survivors within the deadline
        (degrading to re-prefill past it). ``survivors`` defaults to
        every other running serving replica IN THE VICTIM'S POOL when
        the victim registered with a role (a decode replica's pages
        must land on decode peers — a prefill-role survivor would park
        them with no decode step to run them) and to the whole fleet
        otherwise. Returns the directive version (monotonic, starts
        at 1)."""
        from dlrover_tpu.observability.tracing import get_tracer

        if survivors is None:
            victim_role = next(
                (n.role for n in self.serving_nodes() if n.name == victim),
                "",
            )
            pool = self.serving_nodes(
                victim_role if victim_role in ("prefill", "decode") else None
            )
            survivors = [
                n.name
                for n in pool
                if n.name and n.name != victim and not n.is_exited()
            ]
        with self._lock:
            self._serving_reshard_version += 1
            self._serving_reshard = {
                "version": self._serving_reshard_version,
                "victim": victim,
                "survivors": sorted(survivors),
                "deadline_s": float(deadline_s),
                "reason": reason,
            }
            version = self._serving_reshard_version
        get_tracer().instant(
            "failover.serving_reshard_plan",
            version=version,
            victim=victim,
            survivors=len(survivors),
        )
        logger.info(
            "serving reshard directive v%d: victim=%s survivors=%s (%s)",
            version,
            victim,
            sorted(survivors),
            reason or "eviction",
        )
        return version

    def get_serving_reshard(self) -> Dict:
        """The pending serving directive, or ``{"version": 0}``."""
        with self._lock:
            if self._serving_reshard is None:
                return {"version": 0}
            return dict(self._serving_reshard)

    # ---- serving scale (SLO-driven autoscaler directives) ----------------

    def plan_serving_scale(
        self, role: str, target: int, reason: str = ""
    ) -> int:
        """Version one autoscaler decision: bring the ``role`` pool to
        ``target`` live replicas. The latest directive is kept per role
        (a prefill scale-out never clobbers a pending decode scale-in)
        but versions draw from one monotonic counter, so the fleet-wide
        decision ORDER is still total. Returns the version (starts
        at 1)."""
        from dlrover_tpu.observability.tracing import get_tracer

        with self._lock:
            self._serving_scale_version += 1
            version = self._serving_scale_version
            self._serving_scale[role] = {
                "version": version,
                "role": role,
                "target": int(target),
                "reason": reason,
            }
        get_tracer().instant(
            "serving.scale_plan",
            version=version,
            role=role,
            target=int(target),
        )
        logger.info(
            "serving scale directive v%d: role=%s target=%d (%s)",
            version, role, int(target), reason or "slo",
        )
        return version

    def get_serving_scale(self, role: str = "") -> Dict:
        """The latest scale directive for ``role`` — or, with no role,
        the newest across all roles. ``{"version": 0}`` when none."""
        with self._lock:
            if role:
                d = self._serving_scale.get(role)
                return dict(d) if d else {"version": 0}
            if not self._serving_scale:
                return {"version": 0}
            return dict(
                max(
                    self._serving_scale.values(),
                    key=lambda d: d["version"],
                )
            )

    # ---- brain tuning directives -----------------------------------------

    def plan_tuning(self, plan_json: str, reason: str = "") -> int:
        """Version one brain tuning plan/revision (cluster/brain.py
        TuningPlan as asdict JSON). Same contract as
        :meth:`plan_serving_scale`: monotonic counter, latest directive
        wins, trainers poll it via the ParallelConfig path. Returns the
        version (starts at 1)."""
        from dlrover_tpu.observability.tracing import get_tracer

        with self._lock:
            self._tuning_version += 1
            version = self._tuning_version
            self._tuning = {
                "version": version,
                "plan_json": plan_json,
                "reason": reason,
            }
        get_tracer().instant("brain.tuning_plan", version=version)
        logger.info(
            "tuning directive v%d (%s)", version, reason or "brain"
        )
        return version

    def get_tuning(self) -> Dict:
        """The latest tuning directive, or ``{"version": 0}``."""
        with self._lock:
            if self._tuning is None:
                return {"version": 0}
            return dict(self._tuning)

    def all_workers_exited(self) -> bool:
        with self._lock:
            return all(
                n.is_exited()
                for n in self._nodes.values()
                if n.type in (NodeType.WORKER, NodeType.CHIEF)
            )

    def all_workers_succeeded(self) -> bool:
        with self._lock:
            return all(
                n.status == NodeStatus.SUCCEEDED
                for n in self._nodes.values()
                if n.type in (NodeType.WORKER, NodeType.CHIEF)
            )

    def all_evaluators_exited(self) -> bool:
        """Evaluators run outside the train mesh; job completion waits
        for them (reference: EvaluatorManager wait-then-finish)."""
        with self._lock:
            return all(
                n.is_exited()
                for n in self._nodes.values()
                if n.type == NodeType.EVALUATOR
            )

    def is_chief_running(self) -> bool:
        with self._lock:
            return any(
                n.type == NodeType.CHIEF and n.status == NodeStatus.RUNNING
                for n in self._nodes.values()
            )

    def any_node_failed_fatally(self) -> bool:
        with self._lock:
            return any(
                n.is_exited()
                and n.status == NodeStatus.FAILED
                and not n.should_relaunch()
                for n in self._nodes.values()
            )

    def pending_timeout(self) -> bool:
        now = time.time()
        with self._lock:
            for n in self._nodes.values():
                if n.status in (NodeStatus.INITIAL, NodeStatus.PENDING):
                    created = n.create_time or self._start_time
                    if now - created > self._pending_timeout_s:
                        return True
            return False

    def release_node(self, node_id: int):
        """Mark a node as removed-on-purpose (scale-in): its upcoming
        pod deletion/failure events must not trigger a relaunch."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.is_released = True

    def set_worker_num(self, n: int):
        """Elastic scale target; new node slots get fresh bookkeeping.

        Scale-in releases the highest-indexed nodes (mirroring the
        scaler's drop-highest-first policy) so their pod deletions read
        as intentional, not as failures to relaunch."""
        with self._lock:
            self._num_workers = n
            for i, node in self._nodes.items():
                if i >= n and not node.is_exited():
                    node.is_released = True
            for i in range(n):
                if i not in self._nodes:
                    node = Node(
                        node_type=NodeType.WORKER,
                        node_id=i,
                        rank_index=i,
                        max_relaunch_count=self._relaunch_budget,
                    )
                    node.create_time = time.time()
                    self._nodes[i] = node

    @property
    def worker_num(self) -> int:
        with self._lock:
            return self._num_workers
