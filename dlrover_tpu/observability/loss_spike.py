"""Loss-spike capture: record spiking iterations + the samples that caused them.

Reference: atorch/atorch/utils/loss_spike_utils.py (LossSpikeBase /
TokenLossSpike) — when a step's loss exceeds a threshold past a warmup
iteration, append ``iter, loss, sample-ids`` to a dated file so the bad
samples can be decoded and inspected offline.

TPU-first differences: losses arrive as jax arrays (possibly per-sequence
vectors from a vmapped loss); detection adds a rolling z-score mode on top
of the reference's absolute threshold so slow loss decay doesn't need
manual threshold retuning.
"""

import os
import time
from collections import deque
from typing import Deque, List, Optional, Sequence, Tuple

import numpy as np


def format_culprits(
    sample_ids: Optional[Sequence[int]] = None,
    per_sample_losses=None,
    top_k: int = 8,
) -> str:
    """``id:loss`` pairs for the worst offending samples (reference:
    TokenLossSpike's sample decoding), or the raw ids when no per-sample
    losses are available. Shared by the dated-file record and the
    NumericEvent detail the detector publishes."""
    if per_sample_losses is not None:
        ps = np.asarray(per_sample_losses).reshape(-1)
        order = np.argsort(-ps)[: min(top_k, ps.size)]
        ids = (
            [int(sample_ids[i]) for i in order]
            if sample_ids is not None
            else [int(i) for i in order]
        )
        return ",".join(
            f"{i}:{ps_i:.4f}" for i, ps_i in zip(ids, ps[order])
        )
    if sample_ids is not None:
        return ",".join(str(int(i)) for i in sample_ids)
    return ""


class LossSpikeDetector:
    """Detect + persist loss spikes.

    Args:
        save_dir: where spike records are appended (one file per day,
            reference layout). ``None`` disables persistence.
        min_iter: ignore the first N iterations (warmup noise).
        min_loss: absolute floor — a loss below this is never a spike.
        zscore: if set (and the window is warm), a loss above the floor
            must ALSO exceed ``mean + zscore * std`` of the trailing
            window, so a run that merely plateaus above the floor does
            not flag every step.
        window: trailing window length for the rolling statistics.
        publish_events: publish every detected spike onto the telemetry
            hub as a ``NumericEvent(kind="loss_spike")`` carrying the
            offending sample ids in ``detail``. Off for auxiliary
            detectors (e.g. the watchdog's internal one) so a spike is
            published exactly once per run.
    """

    def __init__(
        self,
        save_dir: Optional[str] = None,
        min_iter: int = 100,
        min_loss: float = 4.0,
        zscore: Optional[float] = 4.0,
        window: int = 200,
        publish_events: bool = True,
    ):
        self.save_dir = save_dir
        if save_dir:
            os.makedirs(save_dir, exist_ok=True)
        self.min_iter = min_iter
        self.min_loss = min_loss
        self.zscore = zscore
        self.publish_events = publish_events
        self._window: Deque[float] = deque(maxlen=window)
        self.spikes: List[Tuple[int, float]] = []

    def _is_spike(self, it: int, loss: float) -> bool:
        if it < self.min_iter or loss < self.min_loss:
            return False
        # past the floor, the z-score gate separates a genuinely high
        # plateau from a spike above it; it needs a warm baseline, so no
        # spikes are declared until the window has filled enough
        if self.zscore is not None:
            if len(self._window) < 20:
                return False
            xs = np.asarray(self._window)
            mu, sd = float(xs.mean()), float(xs.std())
            return sd > 0 and loss > mu + self.zscore * sd
        return True

    def update(
        self,
        it: int,
        loss,
        sample_ids: Optional[Sequence[int]] = None,
        per_sample_losses=None,
    ) -> bool:
        """Record one step; returns True when the step is a spike.

        ``per_sample_losses`` (e.g. per-sequence CE from the loss fn)
        narrows the record to the worst offenders, mirroring the
        reference's sample decoding path.
        """
        loss = float(loss)
        spike = self._is_spike(it, loss)
        if not spike:
            # spikes are kept out of the rolling baseline so one outlier
            # does not inflate the std and mask the next one
            self._window.append(loss)
            return False
        self.spikes.append((it, loss))
        culprits = format_culprits(sample_ids, per_sample_losses)
        if self.publish_events:
            from dlrover_tpu.observability import telemetry

            hub = telemetry.get_hub()
            if hub.enabled:
                hub.publish(
                    telemetry.NumericEvent(
                        kind="loss_spike",
                        step=it,
                        value=loss,
                        detail=culprits,
                    )
                )
        if self.save_dir:
            fname = os.path.join(
                self.save_dir,
                time.strftime("loss_spike_%Y%m%d.txt"),
            )
            with open(fname, "a") as f:
                f.write(f"{int(time.time())}\t{it}\t{loss:.6f}\t{culprits}\n")
        return True

    def update_block(
        self,
        first_it: int,
        losses,
        sample_ids: Optional[Sequence[Sequence[int]]] = None,
        per_sample_losses: Optional[Sequence] = None,
    ) -> List[int]:
        """Ingest a fused block's stacked per-step loss vector.

        ``losses[i]`` is the loss of global step ``first_it + i`` (the
        [K] array a K-step ``train_block`` returns).  Steps run through
        the SAME rolling baseline in order, so detection fires at the
        exact offending step — a spike at position i inside a block is
        recorded as iteration ``first_it + i``, not at the block
        boundary.  ``sample_ids``/``per_sample_losses``, when given, are
        per-step sequences aligned with ``losses``.  Returns the
        spiking iterations.
        """
        spiked: List[int] = []
        for i, loss in enumerate(np.asarray(losses).reshape(-1)):
            it = first_it + i
            if self.update(
                it,
                loss,
                sample_ids=sample_ids[i] if sample_ids is not None else None,
                per_sample_losses=per_sample_losses[i]
                if per_sample_losses is not None
                else None,
            ):
                spiked.append(it)
        return spiked

    @staticmethod
    def decode(path: str, min_loss: float = 0.0):
        """Read back spike records: [(ts, iter, loss, culprit_str), ...]."""
        out = []
        with open(path) as f:
            for line in f:
                ts, it, loss, culprits = (line.rstrip("\n").split("\t") + [""])[
                    :4
                ]
                if float(loss) >= min_loss:
                    out.append((int(ts), int(it), float(loss), culprits))
        return out
