"""Kernel census + step timing + Prometheus export.

Reference behaviors re-created TPU-first:

- xpu_timer (atorch/dev/xpu_timer/nvidia/hook.cc, common/manager.h): hooks
  CUDA to time GEMM launches clustered by (B, M, N, K) and NCCL collectives,
  exported as Prometheus gauges. Here the equivalent information is read
  from the *compiled HLO*: every dot/convolution/collective the chip will
  run, with exact shapes, FLOPs and bytes — no interception layer needed
  because XLA compiles the whole step ahead of time.
- AProfiler (atorch/atorch/utils/prof.py:38): per-module FLOPs/params/
  duration. Here ``profile_compiled`` returns FLOPs, bytes accessed and
  peak HBM from XLA's own cost/memory analysis.
"""

import contextlib
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax

# HLO ops we census, mapped to a short kind label.
_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _parse_shape(text: str) -> Tuple[str, Tuple[int, ...]]:
    m = _SHAPE_RE.search(text)
    if not m:
        return ("?", ())
    dims = tuple(int(d) for d in m.group(2).split(",") if d)
    return (m.group(1), dims)


@dataclass
class KernelRecord:
    """One censused HLO op cluster (cf. xpu_timer's GEMM buckets)."""

    kind: str  # "dot" | "convolution" | one of _COLLECTIVES
    dtype: str
    shape: Tuple[int, ...]  # result shape = the (B,)M,N of the GEMM bucket
    count: int = 0


class KernelCensus:
    """Census of dots/convs/collectives in a compiled step function.

    xpu_timer discovers GEMMs at runtime by intercepting launches; on TPU
    the compiled HLO is the ground truth, so the census is exact and free.

    Usage::

        compiled = jax.jit(step).lower(state, batch).compile()
        census = KernelCensus.from_compiled(compiled)
        census.matmuls        # clustered dot records
        census.collectives    # all-reduce/all-gather/... records
        census.flops          # XLA cost-analysis total
    """

    def __init__(self, records: List[KernelRecord], cost: Dict[str, Any]):
        self.records = records
        self.cost = cost

    @property
    def matmuls(self) -> List[KernelRecord]:
        return [r for r in self.records if r.kind in ("dot", "convolution")]

    @property
    def collectives(self) -> List[KernelRecord]:
        return [r for r in self.records if r.kind in _COLLECTIVES]

    @property
    def flops(self) -> float:
        return float(self.cost.get("flops", 0.0))

    @property
    def bytes_accessed(self) -> float:
        return float(self.cost.get("bytes accessed", 0.0))

    @classmethod
    def from_compiled(cls, compiled) -> "KernelCensus":
        buckets: Dict[Tuple[str, str, Tuple[int, ...]], KernelRecord] = {}
        for module in compiled.as_text().splitlines():
            line = module.strip()
            # HLO instruction lines look like:  %name = bf16[8,1024]{...} dot(...)
            m = re.match(r"%?[\w.\-]+ = (\S+) ([\w\-]+)\(", line)
            if not m:
                continue
            shape_text, op = m.group(1), m.group(2)
            # TPU backends emit async pairs (all-reduce-start/-done);
            # count the -start and skip the -done so pairs aren't doubled
            if op.endswith("-done"):
                continue
            if op.endswith("-start"):
                op = op[: -len("-start")]
            if op == "dot" or op == "convolution" or op in _COLLECTIVES:
                dtype, shape = _parse_shape(shape_text)
                key = (op, dtype, shape)
                rec = buckets.get(key)
                if rec is None:
                    buckets[key] = KernelRecord(op, dtype, shape, 1)
                else:
                    rec.count += 1
        try:
            cost = compiled.cost_analysis() or {}
            if isinstance(cost, (list, tuple)):  # older jax returns [dict]
                cost = cost[0] if cost else {}
        except Exception:  # cost analysis is best-effort on some backends
            cost = {}
        return cls(list(buckets.values()), dict(cost))

    def summary(self) -> Dict[str, Any]:
        return {
            "num_matmul_buckets": len(self.matmuls),
            "num_collective_buckets": len(self.collectives),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
        }


def profile_compiled(fn, *args, **kwargs) -> Dict[str, Any]:
    """AProfiler-style one-shot profile of a jittable function.

    Returns flops, bytes accessed, peak HBM (when the backend reports it),
    and the kernel census — all from compilation, without running a step.
    """
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    census = KernelCensus.from_compiled(compiled)
    out = census.summary()
    try:
        mem = compiled.memory_analysis()
        out["output_bytes"] = getattr(mem, "output_size_in_bytes", None)
        out["temp_bytes"] = getattr(mem, "temp_size_in_bytes", None)
        out["argument_bytes"] = getattr(mem, "argument_size_in_bytes", None)
    except Exception:
        pass
    out["census"] = census
    return out


@contextlib.contextmanager
def xla_trace(logdir: str):
    """Capture an XLA/Perfetto trace for the enclosed steps.

    TPU replacement for xpu_timer's timeline dump: the XLA profiler already
    records every kernel + ICI collective with device timestamps.
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Host wall-clock step timing ring buffer → throughput/MFU gauges.

    The device queue hides dispatch latency, so call ``stop()`` after a
    ``jax.block_until_ready`` on the step outputs (or pass the outputs to
    ``stop``) for honest numbers.
    """

    def __init__(self, window: int = 256, flops_per_step: float = 0.0,
                 peak_flops: float = 0.0):
        self._times: Deque[float] = deque(maxlen=window)
        self._t0: Optional[float] = None
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops
        self.steps = 0

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, outputs=None):
        if outputs is not None:
            jax.block_until_ready(outputs)
        if self._t0 is None:
            return
        self.record(time.perf_counter() - self._t0)
        self._t0 = None

    def record(self, dt: float, n_steps: int = 1):
        """Ingest one measured duration covering ``n_steps`` steps.

        Fused multi-step train blocks report once per block with
        ``n_steps=K``; the time is attributed per step so ``mean_s``,
        percentiles, ``steps_per_s`` and ``mfu`` keep their per-step
        meaning regardless of block size.
        """
        n = max(int(n_steps), 1)
        per = dt / n
        for _ in range(n):
            self._times.append(per)
        self.steps += n

    @contextlib.contextmanager
    def step(self):
        self.start()
        out_box = []
        yield out_box
        self.stop(out_box[0] if out_box else None)

    @property
    def last_s(self) -> float:
        return self._times[-1] if self._times else 0.0

    @property
    def mean_s(self) -> float:
        return sum(self._times) / len(self._times) if self._times else 0.0

    def percentile(self, p: float) -> float:
        if not self._times:
            return 0.0
        xs = sorted(self._times)
        idx = min(len(xs) - 1, int(p / 100.0 * len(xs)))
        return xs[idx]

    @property
    def steps_per_s(self) -> float:
        m = self.mean_s
        return 1.0 / m if m > 0 else 0.0

    @property
    def mfu(self) -> float:
        if not (self.flops_per_step and self.peak_flops and self.mean_s):
            return 0.0
        return self.flops_per_step / self.mean_s / self.peak_flops


class WorkerMetrics:
    """Worker-local counters/gauges with a Prometheus text surface.

    Duck-types the collector interface of
    ``dlrover_tpu.master.job_metrics.MetricsHTTPServer`` so a worker can
    expose its own scrape endpoint (xpu_timer exposes per-host brpc/bvar;
    here it is the same tiny HTTP server the master uses).
    """

    def __init__(self, prefix: str = "dlrover_tpu_worker"):
        self._prefix = prefix
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}

    def inc(self, name: str, delta: float = 1.0):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def set_gauge(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = float(value)

    def observe_timer(self, timer: StepTimer):
        self.set_gauge("step_time_mean_s", timer.mean_s)
        self.set_gauge("step_time_p99_s", timer.percentile(99))
        self.set_gauge("steps_per_second", timer.steps_per_s)
        if timer.mfu:
            self.set_gauge("mfu", timer.mfu)

    def observe_census(self, census: KernelCensus):
        self.set_gauge("hlo_flops_per_step", census.flops)
        self.set_gauge("hlo_bytes_per_step", census.bytes_accessed)
        self.set_gauge("hlo_matmul_buckets", len(census.matmuls))
        self.set_gauge("hlo_collective_buckets", len(census.collectives))

    def prometheus_text(self) -> str:
        with self._lock:
            lines = []
            for name, v in sorted(self._counters.items()):
                lines.append(f"# TYPE {self._prefix}_{name} counter")
                lines.append(f"{self._prefix}_{name} {v}")
            for name, v in sorted(self._gauges.items()):
                lines.append(f"# TYPE {self._prefix}_{name} gauge")
                lines.append(f"{self._prefix}_{name} {v}")
            return "\n".join(lines) + "\n"

    def to_json(self) -> str:
        import json

        with self._lock:
            return json.dumps(
                {"counters": dict(self._counters), "gauges": dict(self._gauges)}
            )
