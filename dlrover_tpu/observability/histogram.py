"""Mergeable log-bucketed latency histograms (HDR-histogram style).

The serving tier needs fleet-level percentiles at millions-of-users
scale, and percentiles do not average: p99 of a fleet is NOT the mean
of per-replica p99s. The only way to get an exact fleet percentile
without shipping every raw sample is to ship *mergeable* histograms —
fixed bucket boundaries, counts per bucket — and merge by adding
counts. That is what this module provides:

- **Fixed log2 geometry.** Bucket boundaries depend only on the
  histogram's ``(min_value, sub_bits)`` geometry, never on the data, so
  the bucket index of a value is a pure function of the value. Merging
  two histograms of the same geometry and histogramming the
  concatenated raw samples therefore yield *identical* bucket counts —
  the exactness property the fleet rollup relies on (pinned in
  ``tests/test_histogram.py``).
- **Bounded relative error.** Each octave (power of two) is split into
  ``2**sub_bits`` linear sub-buckets, bounding the relative quantile
  error at ``2**-(sub_bits+1)`` (~1.6 % at the default ``sub_bits=5``)
  across the full range — no truncation window, no per-call sort, O(1)
  record.
- **Lossless wire format.** ``to_dict``/``from_dict`` (and the
  ``to_json``/``from_json`` string wrappers) round-trip the sparse
  counts exactly, with string bucket keys so the envelope survives JSON.

Pure host-side Python, no jax import — safe from any thread and any
process tier (engine loop, router, master, offline healthcheck).
"""

import json
import math
from typing import Dict, Iterable, Optional

__all__ = ["LatencyHistogram", "histogram_delta", "merge_histograms"]


class LatencyHistogram:
    """Fixed-geometry log2-bucketed histogram with exact merge.

    ``min_value`` is the resolution floor (values at or below it share
    bucket 0); with the default 1e-3 the unit is "milliseconds with
    microsecond floor". ``sub_bits`` sets sub-buckets per octave.
    """

    __slots__ = ("min_value", "sub_bits", "_sub", "counts", "n",
                 "total", "vmin", "vmax")

    def __init__(self, *, min_value: float = 1e-3, sub_bits: int = 5):
        if min_value <= 0.0:
            raise ValueError(f"min_value must be > 0, got {min_value}")
        if not (0 <= sub_bits <= 12):
            raise ValueError(f"sub_bits must be in [0, 12], got {sub_bits}")
        self.min_value = float(min_value)
        self.sub_bits = int(sub_bits)
        self._sub = 1 << self.sub_bits
        self.counts: Dict[int, int] = {}
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    # ---- geometry --------------------------------------------------------

    def bucket_index(self, value: float) -> int:
        """Deterministic bucket of ``value`` — a pure function of the
        value and the geometry (this is what makes merge exact)."""
        x = value / self.min_value
        if not (x > 1.0):        # <= min_value, zero, negative, NaN
            return 0
        m, e = math.frexp(x)     # x = m * 2**e, m in [0.5, 1)
        sub = int((m - 0.5) * 2.0 * self._sub)
        if sub >= self._sub:     # fp round-up at the octave edge
            sub = self._sub - 1
        return 1 + (e - 1) * self._sub + sub

    def bucket_mid(self, idx: int) -> float:
        """Representative (midpoint) value of bucket ``idx``."""
        if idx <= 0:
            return self.min_value
        k = idx - 1
        e = k // self._sub + 1
        s = k % self._sub
        m_lo = 0.5 + s / (2.0 * self._sub)
        m_hi = 0.5 + (s + 1) / (2.0 * self._sub)
        return self.min_value * math.ldexp((m_lo + m_hi) / 2.0, e)

    # ---- recording -------------------------------------------------------

    def record(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        idx = self.bucket_index(v)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.n += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def clear(self) -> None:
        self.counts.clear()
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    # ---- queries ---------------------------------------------------------

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile (``q`` in [0, 100]) from bucket
        counts, clamped to the observed [min, max] so the bucket
        midpoint never reports a value outside what was recorded."""
        if self.n == 0:
            return 0.0
        rank = max(1, min(self.n, math.ceil(q / 100.0 * self.n)))
        cum = 0
        for idx in sorted(self.counts):
            cum += self.counts[idx]
            if cum >= rank:
                mid = self.bucket_mid(idx)
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax  # unreachable: counts sum to n

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def summary(self) -> dict:
        """The scheduler's historical ``latency_ms()`` shape."""
        return {
            "p50": self.percentile(50.0),
            "p99": self.percentile(99.0),
            "n": self.n,
        }

    # ---- merge -----------------------------------------------------------

    def geometry(self) -> tuple:
        return (self.min_value, self.sub_bits)

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold ``other`` into self by adding bucket counts. Exact:
        equivalent to having recorded the union of both sample sets.
        Raises on geometry mismatch — silently merging histograms with
        different bucket boundaries would fabricate percentiles."""
        if other.geometry() != self.geometry():
            raise ValueError(
                f"histogram geometry mismatch: {other.geometry()} vs "
                f"{self.geometry()}"
            )
        for idx, c in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + c
        self.n += other.n
        self.total += other.total
        if other.n:
            self.vmin = min(self.vmin, other.vmin)
            self.vmax = max(self.vmax, other.vmax)
        return self

    def copy(self) -> "LatencyHistogram":
        h = LatencyHistogram(min_value=self.min_value, sub_bits=self.sub_bits)
        h.counts = dict(self.counts)
        h.n = self.n
        h.total = self.total
        h.vmin = self.vmin
        h.vmax = self.vmax
        return h

    # ---- wire format -----------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "v": 1,
            "min_value": self.min_value,
            "sub_bits": self.sub_bits,
            "n": self.n,
            "total": self.total,
            # inf min/max (empty hist) are not JSON — encode as None
            "min": self.vmin if self.n else None,
            "max": self.vmax if self.n else None,
            "counts": {str(k): v for k, v in sorted(self.counts.items())},
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "LatencyHistogram":
        h = cls(min_value=doc["min_value"], sub_bits=doc["sub_bits"])
        h.counts = {int(k): int(v) for k, v in doc.get("counts", {}).items()}
        h.n = int(doc.get("n", 0))
        h.total = float(doc.get("total", 0.0))
        h.vmin = float(doc["min"]) if doc.get("min") is not None else math.inf
        h.vmax = (
            float(doc["max"]) if doc.get("max") is not None else -math.inf
        )
        return h

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "LatencyHistogram":
        return cls.from_dict(json.loads(line))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        s = self.summary()
        return (
            f"LatencyHistogram(n={self.n}, p50={s['p50']:.3g}, "
            f"p99={s['p99']:.3g})"
        )


def merge_histograms(
    hists: Iterable[LatencyHistogram],
) -> Optional[LatencyHistogram]:
    """Merge an iterable of same-geometry histograms into a fresh one
    (inputs untouched). Returns None for an empty iterable."""
    out: Optional[LatencyHistogram] = None
    for h in hists:
        if out is None:
            out = h.copy()
        else:
            out.merge(h)
    return out


def histogram_delta(
    cur: LatencyHistogram, prev: Optional[LatencyHistogram]
) -> LatencyHistogram:
    """The WINDOW between two snapshots of one lifetime histogram:
    ``cur``'s bucket counts minus ``prev``'s. Scheduler histograms only
    reset on ``reset_latencies``, so a controller that must judge *this
    window's* p99 (the serving autoscaler's hysteresis-clear check)
    subtracts its previous snapshot instead of letting minutes of
    healthy history mask a fresh breach — or a cleared breach.

    The exactness property carries over: counts of the window equal
    counts of the raw samples recorded between the snapshots. The one
    approximation is the clamp range — vmin/vmax of the WINDOW are not
    recoverable from the snapshots, so ``cur``'s lifetime extremes are
    used and window percentiles inherit lifetime clamping. ``prev`` of
    None (first window) returns a copy of ``cur``. Raises on geometry
    mismatch, same as ``merge``."""
    if prev is None:
        return cur.copy()
    if prev.geometry() != cur.geometry():
        raise ValueError(
            f"histogram geometry mismatch: {prev.geometry()} vs "
            f"{cur.geometry()}"
        )
    out = LatencyHistogram(
        min_value=cur.min_value, sub_bits=cur.sub_bits
    )
    for idx, c in cur.counts.items():
        d = c - prev.counts.get(idx, 0)
        if d > 0:
            out.counts[idx] = d
    out.n = max(0, cur.n - prev.n)
    out.total = max(0.0, cur.total - prev.total)
    out.vmin = cur.vmin
    out.vmax = cur.vmax
    return out
