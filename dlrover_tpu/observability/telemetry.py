"""Unified telemetry bus: typed records, pluggable sinks, one stream.

The observability layer grew as disconnected point tools (StepTimer,
KernelCensus, the runtime sampler, loss-spike/numeric checks,
GoodputTracker) with nothing consuming them at runtime.  This module
is the substrate that joins them: producers publish small, typed,
JSON-serializable records into a :class:`TelemetryHub`; consumers
(JSONL flight-recorder files, the Prometheus surfaces in
``profiler.WorkerMetrics`` / ``master/job_metrics.py``, master
reporting over the wire, the diagnosis manager) attach as sinks.

Contracts:

* **Lossless wire format.**  ``record.to_json()`` /
  ``from_json(line)`` round-trip every registered record exactly
  (pinned by the tier-1 schema lint) — the same envelope discipline as
  ``common/messages.py``, so master-side code can rehydrate a record a
  worker serialized.
* **Zero-cost when off.**  ``get_hub()`` returns a module-pinned
  ``_NullHub`` unless telemetry is configured; producers guard with
  ``if hub.enabled:`` so on the hot path a disabled hub costs one
  attribute load — no record construction, no publish, no allocation
  (pinned by the tier-1 overhead guard).
* **Sinks never break training.**  A sink raising is logged once and
  detached; the publisher never sees the exception.
"""

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Type

from dlrover_tpu.common.constants import GraftEnv
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# ---- record registry ------------------------------------------------------

_RECORD_TYPES: Dict[str, type] = {}


def _to_json(self) -> str:
    return json.dumps(
        {"r": type(self).__name__, "d": dataclasses.asdict(self)},
        sort_keys=True,
    )


def telemetry_record(cls):
    """Class decorator: dataclass + registry entry + ``to_json``."""
    cls = dataclasses.dataclass(cls)
    cls.to_json = _to_json
    _RECORD_TYPES[cls.__name__] = cls
    return cls


def from_json(line: str):
    """Rehydrate any registered record from its ``to_json`` line."""
    obj = json.loads(line)
    cls = _RECORD_TYPES[obj["r"]]
    return cls(**obj["d"])


def record_types() -> Dict[str, type]:
    """Registered name → class map (schema lint iterates this)."""
    return dict(_RECORD_TYPES)


# ---- record types ---------------------------------------------------------
# All fields are JSON scalars (str/int/float/bool) or plain dicts so
# asdict → json round-trips losslessly.  ``ts`` is seconds since epoch,
# stamped by the hub at publish when left 0.


@telemetry_record
class StepRecord:
    """One optimizer step as seen by the trainer."""

    step: int = 0
    loss: float = 0.0
    step_time_s: float = 0.0
    tokens_per_s: float = 0.0
    accum: int = 1
    ts: float = 0.0


@telemetry_record
class CollectiveRecord:
    """One collective class's wire traffic (planned or measured)."""

    op: str = ""
    bytes: int = 0
    wire_dtype: str = ""
    wire_us: float = 0.0
    exposed_us: float = 0.0
    ts: float = 0.0


@telemetry_record
class CheckpointRecord:
    """One save/restore action at any tier of the checkpoint stack."""

    kind: str = ""  # save_memory | persist | emergency | restore_* ...
    step: int = -1
    seconds: float = 0.0
    nbytes: int = 0
    ok: bool = True
    tier: str = ""  # memory | replica | storage
    ts: float = 0.0


@telemetry_record
class ElasticEvent:
    """A failover / membership phase transition."""

    kind: str = ""  # detect | rendezvous | mesh_replan | restore |
    #                 first_step | node_down | worker_exit ...
    node_id: int = -1
    rdzv_round: int = -1
    restart: int = -1
    seconds: float = 0.0
    detail: str = ""
    ts: float = 0.0


@telemetry_record
class NumericEvent:
    """A numeric-health incident (loss spike, non-finite grads, ...)."""

    kind: str = ""
    step: int = -1
    value: float = 0.0
    detail: str = ""
    ts: float = 0.0


@telemetry_record
class KernelSample:
    """One op from a sampled runtime-profiler step breakdown.

    ``block`` is the number of train steps the trace covered: 1 for the
    classic per-step loop, K when the profiled dispatch was a fused
    K-step block (the µs then span the whole block, not one step)."""

    step: int = -1
    op: str = ""
    us: float = 0.0
    share: float = 0.0
    block: int = 1
    ts: float = 0.0


@telemetry_record
class PlanRecord:
    """Bench/accelerate compile-time planning numbers, surfaced at
    runtime so tuners can compare plan vs reality."""

    config: str = ""
    suggested_bucket_mb: float = 0.0
    planned_exposed_us: float = 0.0
    planned_hidden_us: float = 0.0
    assumed_ici_gbps: float = 0.0
    update_sharding_reason: str = ""
    # measured mean step wall time at the bench shape — the watchdog's
    # baseline for step_time_regression (0 = no plan available)
    planned_step_time_s: float = 0.0
    ts: float = 0.0


@telemetry_record
class OverlapDriftRecord:
    """Planned exposed-collective µs vs measured (from the sampled
    ``xla_trace``) — the signal ``config_tuner``/``brain`` consume."""

    step: int = -1
    planned_exposed_us: float = 0.0
    measured_collective_us: float = 0.0
    drift_us: float = 0.0
    drift_frac: float = 0.0
    ts: float = 0.0


@telemetry_record
class StragglerRecord:
    """A worker lagging the per-worker step watermark front."""

    node_id: int = -1
    step: int = 0
    max_step: int = 0
    lag_steps: int = 0
    ratio: float = 0.0
    ts: float = 0.0


@telemetry_record
class ResourceRecord:
    """Per-node host/HBM usage as reported by the agent monitor."""

    node_id: int = -1
    cpu_percent: float = 0.0
    mem_mb: float = 0.0
    hbm_mb: float = 0.0
    hbm_peak_mb: float = 0.0
    ts: float = 0.0


@telemetry_record
class AnomalyRecord:
    """One classified training anomaly from the host-side watchdog.

    ``kind`` is one of observability.watchdog.ANOMALY_KINDS
    (nan_grads | loss_spike | fp8_saturation | step_time_regression |
    straggler) or watchdog.SERVING_ANOMALY_KINDS (slo_breach |
    ttft_regression | spec_accept_collapse | shed_storm |
    migration_fallback).  ``capture`` is the path of the
    triggered-capture artifact when the rate limiter granted one, else
    "".  ``replica`` names the serving replica for serving kinds
    ("" for training anomalies)."""

    kind: str = ""
    step: int = -1
    node_id: int = -1
    value: float = 0.0
    detail: str = ""
    capture: str = ""
    replica: str = ""
    ts: float = 0.0


@telemetry_record
class HealthSummary:
    """Master-side cross-host correlation of worker AnomalyRecords.

    ``verdict`` encodes the attribution rule: one rank reporting →
    suspect data/hardware on that host; every rank reporting → suspect
    model/config.  ``ranks`` is a comma-joined sorted rank list."""

    kind: str = ""
    first_step: int = -1
    ranks: str = ""
    n_ranks: int = 0
    world: int = 0
    verdict: str = ""
    detail: str = ""
    ts: float = 0.0


@telemetry_record
class ServingRecord:
    """Periodic serving-replica snapshot (serving/scheduler.py publish).

    Latencies are end-to-end request milliseconds (submit → complete)
    over the scheduler's sliding window; ``tokens_per_s`` is the
    engine's decode throughput since its first step. ``re_admitted``
    counts failover re-admissions this replica ABSORBED from dead
    peers (serving/replica.py ReplicaRouter).

    Speculative decoding (engine ``spec_k > 0``): ``draft_tokens`` /
    ``accepted_tokens`` are lifetime counts of drafts proposed to and
    accepted by the verify step; ``spec_accept_rate`` is their ratio
    (0 with speculation off). Recordings from builds that predate
    these fields replay fine — ``from_json`` fills missing fields from
    the dataclass defaults.

    Migration robustness (serving/migration.py): ``migrated_in`` /
    ``migrated_out`` are lifetime counts of requests this engine
    imported/exported as live KV pages; ``shed`` counts queued new
    admissions failed with a retry-after hint to protect a migration
    under page pressure.

    Phase latencies (observability/histogram.py): ``ttft_*`` is
    time-to-first-token (submit → first emitted token), ``tpot_*`` is
    time-per-output-token (mean inter-token ms within a request),
    ``queue_wait_p99_ms`` is enqueue → engine admission.  ``hists`` is
    the JSON-encoded envelope of all the per-phase histograms
    (``scheduler.LATENCY_PHASES`` → LatencyHistogram.to_dict()) —
    a *string* field so the record stays scalar-only on the wire; the
    router/master parse it to merge fleet percentiles from counts
    rather than averaging per-replica percentiles.

    Disaggregated serving (serving/disagg.py): ``role`` is this
    replica's pool ("prefill" | "decode" | "unified");
    ``handoffs_in`` / ``handoffs_out`` are lifetime counts of
    prefill→decode streaming handoffs this engine received/shipped,
    ``handoff_bytes`` the wire bytes they moved, ``handoff_ms_p99``
    the receiving-side first-fragment→commit latency. Recordings from
    builds predating the split replay with the defaults (unified, 0).

    Drop accounting (goodput vs offered load): ``rejected`` counts
    admission failures (queue at capacity + oversize requests),
    ``timed_out`` counts per-request deadline expiries, ``poisoned``
    counts requests failed for invalid sampling parameters; together
    with ``shed`` every dropped request is in exactly one counter."""

    replica: str = ""
    active_slots: int = 0
    queue_depth: int = 0
    admitted: int = 0
    completed: int = 0
    re_admitted: int = 0
    tokens_per_s: float = 0.0
    p50_ms: float = 0.0
    p99_ms: float = 0.0
    draft_tokens: int = 0
    accepted_tokens: int = 0
    spec_accept_rate: float = 0.0
    shed: int = 0
    migrated_in: int = 0
    migrated_out: int = 0
    ttft_p50_ms: float = 0.0
    ttft_p99_ms: float = 0.0
    tpot_p50_ms: float = 0.0
    tpot_p99_ms: float = 0.0
    queue_wait_p99_ms: float = 0.0
    rejected: int = 0
    timed_out: int = 0
    poisoned: int = 0
    # prefix sharing (serving/prefix.py): hit rate over sharing-on
    # admissions, prompt tokens whose prefill was skipped, live radix
    # index size in pages, and resident-bytes dedup (slot cells per
    # unique physical page). Defaults replay pre-sharing recordings.
    prefix_hit_rate: float = 0.0
    prefill_tokens_saved: int = 0
    trie_pages: int = 0
    dedup_ratio: float = 1.0
    role: str = "unified"
    handoffs_in: int = 0
    handoffs_out: int = 0
    handoff_bytes: int = 0
    handoff_ms_p99: float = 0.0
    hists: str = ""
    ts: float = 0.0


@telemetry_record
class ScaleDecisionRecord:
    """One serving-autoscaler decision (master/serving_autoscaler.py).

    ``direction`` is "out" (a warm replica joined ``role``'s pool) or
    "in" (the least-loaded member drained via live migration and
    detached); ``signal`` names the gate that drove it (slo_breach |
    ttft_regression | out_of_pages | queue_depth | shed_storm | clear |
    planned), with ``value`` the measured reading against ``target``.
    ``reaction_s`` is the breach-edge → decision-applied latency (the
    control-loop half of the bench's breach → p99-restored headline);
    ``version`` is the master's serving-scale directive version (0 when
    the scaler versioned locally). ``replica`` names the joiner
    (scale-out) or the drained victim (scale-in). Recordings that
    predate autoscaling simply contain no lines of this type — the
    healthcheck replay treats absence as "no decisions"."""

    role: str = "unified"
    direction: str = ""
    signal: str = ""
    value: float = 0.0
    target: float = 0.0
    n_before: int = 0
    n_after: int = 0
    version: int = 0
    reaction_s: float = 0.0
    replica: str = ""
    reason: str = ""
    ts: float = 0.0


@telemetry_record
class SparseServingRecord:
    """Periodic sparse-serving snapshot (serving/sparse_engine.py).

    The recommendation analog of ``ServingRecord``: one line per
    publish interval from a replica serving DeepFM predictions over the
    tiered embedding tier. ``qps`` is completed requests per second
    since the engine's first step; latency percentiles are the same
    scheduler histograms the LLM path uses (``hists`` carries the full
    per-phase envelope for fleet merges).

    Tier gauges (sparse/tiered.py TierStats): ``hot_hit_rate`` is the
    fraction of gathered keys already resident in the hot KvTable,
    ``prefetch_coverage`` the fraction of cold promotions done by the
    lookahead prefetcher instead of synchronously in the request path
    (1.0 when nothing was cold), ``promote_latency_avg_ms`` the mean
    cold→hot batch promotion latency, ``cold_faults`` / ``prefetched``
    / ``demoted`` lifetime key counts, ``hot_rows`` / ``cold_rows``
    current tier occupancy.

    PS resharding (sparse/server.py + master/elastic_ps.py):
    ``ps_version`` is the last master server-set version this replica
    adopted, ``ps_reshards`` how many reshard migrations it executed,
    ``last_reshard_s`` the most recent pause→resync→resume wall time
    (the recovery-seconds half of the reshard drill's acceptance bar).
    Recordings from builds that predate this type simply contain no
    lines of it — healthcheck replay treats absence as "no sparse
    serving"."""

    replica: str = ""
    queue_depth: int = 0
    admitted: int = 0
    completed: int = 0
    re_admitted: int = 0
    shed: int = 0
    rejected: int = 0
    timed_out: int = 0
    qps: float = 0.0
    p50_ms: float = 0.0
    p99_ms: float = 0.0
    queue_wait_p99_ms: float = 0.0
    hot_hit_rate: float = 0.0
    prefetch_coverage: float = 0.0
    promote_latency_avg_ms: float = 0.0
    cold_faults: int = 0
    prefetched: int = 0
    demoted: int = 0
    hot_rows: int = 0
    cold_rows: int = 0
    ps_version: int = 0
    ps_reshards: int = 0
    last_reshard_s: float = 0.0
    hists: str = ""
    ts: float = 0.0


# ---- sinks ----------------------------------------------------------------


class JsonlSink:
    """Append one ``to_json`` line per record (line-buffered, so records
    survive the process dying mid-failover)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._lock = threading.Lock()

    def emit(self, record) -> None:
        with self._lock:
            self._f.write(record.to_json() + "\n")

    def close(self) -> None:
        with self._lock:
            self._f.close()


# gauge/counter mappings per record type for any collector duck-typing
# inc(name)/set_gauge(name, value) — WorkerMetrics on the worker,
# JobMetricCollector on the master.
_GAUGE_MAP: Dict[str, List[Tuple[str, str]]] = {
    "StepRecord": [
        ("telemetry_step_time_s", "step_time_s"),
        ("telemetry_loss", "loss"),
        ("telemetry_tokens_per_s", "tokens_per_s"),
    ],
    "PlanRecord": [
        ("plan_suggested_bucket_mb", "suggested_bucket_mb"),
        ("plan_exposed_collective_us", "planned_exposed_us"),
        ("plan_hidden_collective_us", "planned_hidden_us"),
    ],
    "OverlapDriftRecord": [
        ("overlap_planned_exposed_us", "planned_exposed_us"),
        ("overlap_measured_collective_us", "measured_collective_us"),
        ("overlap_drift_us", "drift_us"),
        ("overlap_drift_frac", "drift_frac"),
    ],
    "CheckpointRecord": [("ckpt_last_seconds", "seconds")],
    "ResourceRecord": [
        ("hbm_used_mb", "hbm_mb"),
        ("hbm_peak_mb", "hbm_peak_mb"),
    ],
    "StragglerRecord": [("straggler_lag_steps", "lag_steps")],
    "AnomalyRecord": [("anomaly_last_step", "step")],
    "ServingRecord": [
        ("serving_tokens_per_s", "tokens_per_s"),
        ("serving_p50_ms", "p50_ms"),
        ("serving_p99_ms", "p99_ms"),
        ("serving_queue_depth", "queue_depth"),
        ("serving_draft_tokens", "draft_tokens"),
        ("serving_accepted_tokens", "accepted_tokens"),
        ("serving_spec_accept_rate", "spec_accept_rate"),
        ("serving_shed", "shed"),
        ("serving_migrated_in", "migrated_in"),
        ("serving_migrated_out", "migrated_out"),
        ("serving_ttft_p50_ms", "ttft_p50_ms"),
        ("serving_ttft_p99_ms", "ttft_p99_ms"),
        ("serving_tpot_p50_ms", "tpot_p50_ms"),
        ("serving_tpot_p99_ms", "tpot_p99_ms"),
        ("serving_queue_wait_p99_ms", "queue_wait_p99_ms"),
        ("serving_rejected", "rejected"),
        ("serving_timed_out", "timed_out"),
        ("serving_poisoned", "poisoned"),
        ("serving_prefix_hit_rate", "prefix_hit_rate"),
        ("serving_prefill_tokens_saved", "prefill_tokens_saved"),
        ("serving_trie_pages", "trie_pages"),
        ("serving_dedup_ratio", "dedup_ratio"),
        ("serving_handoffs_in", "handoffs_in"),
        ("serving_handoffs_out", "handoffs_out"),
        ("serving_handoff_bytes", "handoff_bytes"),
        ("serving_handoff_ms_p99", "handoff_ms_p99"),
    ],
    "ScaleDecisionRecord": [
        ("autoscale_pool_size", "n_after"),
        ("autoscale_reaction_s", "reaction_s"),
    ],
    "SparseServingRecord": [
        ("sparse_serving_qps", "qps"),
        ("sparse_serving_p50_ms", "p50_ms"),
        ("sparse_serving_p99_ms", "p99_ms"),
        ("sparse_serving_queue_depth", "queue_depth"),
        ("sparse_serving_queue_wait_p99_ms", "queue_wait_p99_ms"),
        ("sparse_hot_hit_rate", "hot_hit_rate"),
        ("sparse_prefetch_coverage", "prefetch_coverage"),
        ("sparse_promote_latency_avg_ms", "promote_latency_avg_ms"),
        ("sparse_cold_faults", "cold_faults"),
        ("sparse_prefetched", "prefetched"),
        ("sparse_demoted", "demoted"),
        ("sparse_hot_rows", "hot_rows"),
        ("sparse_cold_rows", "cold_rows"),
        ("sparse_ps_version", "ps_version"),
        ("sparse_ps_reshards", "ps_reshards"),
        ("sparse_last_reshard_s", "last_reshard_s"),
    ],
    # cluster/brain.py records (registered on brain import)
    "TuningPlan": [
        ("tuning_version", "version"),
        ("tuning_comm_bucket_mb", "comm_bucket_mb"),
        ("tuning_spec_k", "spec_k"),
        ("tuning_prefill_chunk", "prefill_chunk"),
    ],
    "JobMetrics": [
        ("brain_steps_per_sec", "steps_per_sec"),
        ("brain_samples_per_sec", "samples_per_sec"),
        ("brain_hbm_used_bytes", "hbm_used_bytes"),
    ],
}
_COUNTER_MAP: Dict[str, str] = {
    "ElasticEvent": "elastic_events_total",
    "NumericEvent": "numeric_events_total",
    "CheckpointRecord": "ckpt_records_total",
    "StragglerRecord": "straggler_flags_total",
    "AnomalyRecord": "anomaly_records_total",
    "HealthSummary": "health_summaries_total",
    "ServingRecord": "serving_records_total",
    "SparseServingRecord": "sparse_serving_records_total",
    "ScaleDecisionRecord": "scale_decisions_total",
    "TuningPlan": "tuning_plans_total",
    "JobMetrics": "brain_job_metrics_total",
}


class MetricsSink:
    """Project records onto a Prometheus-style collector.

    ``collector`` is duck-typed: anything with ``inc(name)`` and
    ``set_gauge(name, value)`` (``profiler.WorkerMetrics`` worker-side,
    ``master.job_metrics.JobMetricCollector`` master-side).
    """

    def __init__(self, collector):
        self._c = collector

    def emit(self, record) -> None:
        tname = type(record).__name__
        for gauge, attr in _GAUGE_MAP.get(tname, ()):
            self._c.set_gauge(gauge, float(getattr(record, attr)))
        counter = _COUNTER_MAP.get(tname)
        if counter:
            self._c.inc(counter)
        if tname == "ElasticEvent" and record.seconds > 0 and record.kind:
            self._c.set_gauge(f"failover_{record.kind}_s", record.seconds)


class MasterSink:
    """Forward selected record types to the master over the existing
    agent↔master wire (``MasterClient.report_telemetry``).

    Per-step records are excluded by default: the bus must not turn the
    hot path into an RPC-per-step — the speed monitor already gets step
    reports through ``report_global_step``.
    """

    DEFAULT_TYPES = (
        "AnomalyRecord",
        "CheckpointRecord",
        "ElasticEvent",
        "NumericEvent",
        "OverlapDriftRecord",
        "PlanRecord",
        "TuningPlan",
    )

    def __init__(self, client, types: Optional[Tuple[str, ...]] = None):
        self._client = client
        self._types = frozenset(
            types if types is not None else self.DEFAULT_TYPES
        )

    def emit(self, record) -> None:
        if type(record).__name__ in self._types:
            self._client.report_telemetry(record.to_json())


class CallbackSink:
    """Deliver records to a plain callable (diagnosis subscription)."""

    def __init__(self, fn: Callable, types: Optional[Tuple[str, ...]] = None):
        self._fn = fn
        self._types = frozenset(types) if types is not None else None

    def emit(self, record) -> None:
        if self._types is None or type(record).__name__ in self._types:
            self._fn(record)


# ---- hub ------------------------------------------------------------------


class TelemetryHub:
    """Fan records out to attached sinks; a failing sink is detached
    after logging once, never propagated to the producer."""

    enabled = True

    def __init__(self):
        self._sinks: List = []
        self._lock = threading.Lock()

    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def subscribe(
        self, fn: Callable, types: Optional[Tuple[str, ...]] = None
    ) -> CallbackSink:
        sink = CallbackSink(fn, types)
        self.add_sink(sink)
        return sink

    def publish(self, record) -> None:
        if not record.ts:
            record.ts = time.time()
        # snapshot under the lock; emit outside it so a slow sink
        # (file write, RPC) never serializes other publishers
        with self._lock:
            sinks = tuple(self._sinks)
        for sink in sinks:
            try:
                sink.emit(record)
            except Exception as e:
                logger.warning(
                    "telemetry sink %s failed (%s); detaching",
                    type(sink).__name__,
                    e,
                )
                self.remove_sink(sink)


def _noop(record) -> None:
    pass


class _NullHub:
    """Disabled hub: ``enabled`` is False and every method is a pinned
    no-op.  Producers guard ``if hub.enabled:`` so records are never
    even constructed on the disabled path."""

    __slots__ = ()
    enabled = False
    publish = staticmethod(_noop)

    def add_sink(self, sink) -> None:
        pass

    def remove_sink(self, sink) -> None:
        pass

    def subscribe(self, fn, types=None):
        return None


_NULL_HUB = _NullHub()
_hub = None
_hub_lock = threading.Lock()


def configure_hub(
    sinks: Optional[List] = None, jsonl_path: Optional[str] = None
):
    """Install the process hub (idempotent: reconfiguring adds sinks)."""
    global _hub
    with _hub_lock:
        if _hub is None or _hub is _NULL_HUB:
            _hub = TelemetryHub()
        for s in sinks or ():
            _hub.add_sink(s)
        if jsonl_path:
            _hub.add_sink(JsonlSink(jsonl_path))
        return _hub


def get_hub():
    """The process hub, or the pinned ``_NullHub`` when telemetry is
    off.  Auto-enables with a JSONL sink when
    ``DLROVER_TPU_TELEMETRY_DIR`` is set (one file per process, role
    from ``DLROVER_TPU_TRACE_ROLE``)."""
    if _hub is not None:
        return _hub
    tdir = os.getenv(GraftEnv.TELEMETRY_DIR)
    if tdir:
        role = os.getenv(GraftEnv.TRACE_ROLE, "proc")
        return configure_hub(
            jsonl_path=os.path.join(
                tdir, f"telemetry-{role}-{os.getpid()}.jsonl"
            )
        )
    return _NULL_HUB


def reset_hub() -> None:
    """Drop the installed hub (tests)."""
    global _hub
    with _hub_lock:
        _hub = None


# ---- producers' helpers ---------------------------------------------------


def plan_record_from_overlap(
    config_name: str,
    overlap: Optional[Dict],
    suggested_bucket_mb: float = 0.0,
    update_sharding_reason: str = "",
    planned_step_time_s: float = 0.0,
) -> PlanRecord:
    """Build a :class:`PlanRecord` from ``bench.overlap_report`` output."""
    overlap = overlap or {}
    return PlanRecord(
        config=config_name,
        suggested_bucket_mb=float(suggested_bucket_mb or 0.0),
        planned_exposed_us=float(overlap.get("exposed_us_total", 0.0)),
        planned_hidden_us=float(overlap.get("hidden_us_total", 0.0)),
        assumed_ici_gbps=float(overlap.get("assumed_ici_gbps", 0.0)),
        update_sharding_reason=update_sharding_reason or "",
        planned_step_time_s=float(planned_step_time_s or 0.0),
    )


_COLLECTIVE_MARKERS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)


def measured_collective_us(breakdown: List) -> float:
    """Sum the measured µs of collective ops in a runtime-timer
    breakdown (list of objects with ``.name`` and ``.total_us``)."""
    total = 0.0
    for op in breakdown:
        name = op.name.lower()
        if any(m in name for m in _COLLECTIVE_MARKERS):
            total += op.total_us
    return total


def overlap_drift(
    step: int, planned_exposed_us: float, breakdown: List
) -> OverlapDriftRecord:
    """Planned exposed-collective time vs measured collective time from
    one sampled step.  ``drift_frac`` is relative to the plan (0 when
    nothing was planned — pure-measurement mode)."""
    measured = measured_collective_us(breakdown)
    drift = measured - planned_exposed_us
    frac = drift / planned_exposed_us if planned_exposed_us > 0 else 0.0
    return OverlapDriftRecord(
        step=step,
        planned_exposed_us=float(planned_exposed_us),
        measured_collective_us=float(measured),
        drift_us=float(drift),
        drift_frac=float(frac),
    )
